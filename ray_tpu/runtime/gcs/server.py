"""Global Control Service.

Role-equivalent of the reference's GCS server (src/ray/gcs/gcs_server.h:98):
one logical process on the head node composing node membership, internal KV,
pubsub, the actor directory/scheduler, the placement-group manager, job
accounting, cluster resource views, and raylet health checking. Every other
component finds the cluster through this service's address.

Storage is pluggable (reference: store_client/): the working set stays in
plain dicts for O(1) serving, with write-through to a ``StoreClient``. With
``gcs_storage_path`` configured the sqlite WAL backend makes actors, PGs,
jobs, and the internal KV survive a GCS restart; raylets re-register when
their resource report lands on a GCS that does not know them (reference:
NotifyGCSRestart, node_manager.proto:426).
"""

from __future__ import annotations

import asyncio
import json
import logging
import pickle
import time
from typing import Dict, List, Optional, Tuple

import cloudpickle

from ..._internal.config import Config
from ..._internal.event_loop import BackgroundTasks, PeriodicRunner
from ..._internal.ids import ActorID, JobID, NodeID, PlacementGroupID, WorkerID
from ..._internal.protocol import (
    label_match,
    ActorInfo,
    ActorState,
    NodeInfo,
    PlacementGroupInfo,
    TaskSpec,
)
from ..._internal.rpc import ClientPool, RpcClient, RpcServer
from ...util.events import NODE_SUSPECT, record_event
from . import keys as gcs_keys
from .actor_manager import GcsActorManager
from .placement_groups import GcsPlacementGroupManager
from .pubsub import Publisher
from .store import StoreClient, make_store
from .kvtier_registry import GcsKVTierRegistry
from .timeseries_store import GcsTimeseriesStore
from .weight_registry import GcsWeightRegistry

logger = logging.getLogger(__name__)


class GcsServer:
    def __init__(self, config: Config, storage: Optional[StoreClient] = None):
        self.config = config
        self.server = RpcServer("gcs")
        self.publisher = Publisher()
        self.client_pool = ClientPool("gcs-out")
        self.storage = storage or make_store(config.gcs_storage_path)
        self.actor_manager = GcsActorManager(self)
        self.pg_manager = GcsPlacementGroupManager(self)
        self.weight_registry = GcsWeightRegistry(self)
        self.kvtier_registry = GcsKVTierRegistry(self)
        self.timeseries = GcsTimeseriesStore(self)

        self._nodes: Dict[NodeID, NodeInfo] = {}
        self._node_available: Dict[NodeID, Dict[str, float]] = {}
        self._node_last_seen: Dict[NodeID, float] = {}
        # SUSPECT: reports stopped (age > suspect_after_s) and an active
        # raylet probe ran — between ALIVE and DEAD. Suspect nodes get no
        # new leases and serve replaces their replicas; the state clears on
        # the node's next report. Value: when suspicion started.
        self._node_suspect: Dict[NodeID, float] = {}
        # versioned delta sync (reference: RaySyncer ray_syncer.h:89): the
        # last applied per-raylet report version; a mismatched base on an
        # incoming delta triggers a resync (raylet re-sends a full snapshot)
        self._node_sync_versions: Dict[NodeID, int] = {}
        self._kv: Dict[str, bytes] = {}
        self._jobs: Dict[JobID, dict] = {}
        self._next_job = 1
        # task-event store (reference: GcsTaskManager, gcs_task_manager.h:97):
        # latest state per task, bounded
        self._task_events: Dict[str, dict] = {}
        self._task_events_order: List[str] = []
        self._task_events_cap = 10000
        # span store: finished spans streamed from every traced process so
        # worker spans outlive their process and join the cluster timeline
        # (capped like task events; tracing off -> nothing ever arrives)
        self._spans: List[dict] = []
        self._spans_cap = 50000
        # flight-recorder event store (util/events.py): every process's
        # structured-event ring is streamed here continuously, so the
        # cluster copy survives a SIGKILL of the recording process and
        # `ray_tpu events` can post-mortem a dead replica
        self._events: List[dict] = []
        self._events_cap = 50000
        # store-side truncation counter (the process-local twin is the
        # events_dropped_total metric): how many events this store evicted
        self._events_dropped = 0
        # autoscaler state (reference: GcsAutoscalerStateManager)
        self._node_demands: Dict[NodeID, list] = {}
        self._autoscaling_state: Optional[dict] = None
        self._runner: Optional[PeriodicRunner] = None
        self.address: Optional[Tuple[str, int]] = None
        # Nodes referenced by restored actors/PGs that have not re-registered
        # yet: given one health-check window to come back, then declared dead
        # (their raylets may have died with the previous GCS).
        self._restored_nodes_pending: Dict[NodeID, float] = {}
        # Background scheduling loops (actor/PG placement): tracked so stop()
        # cancels them — a killed-and-restarted GCS must not leave zombie
        # schedulers from the old instance double-creating actors.
        self._bg = BackgroundTasks()
        self._stopped = False

    def spawn(self, coro):
        """ensure_future with lifecycle tracking; no-op after stop()."""
        if self._stopped:
            coro.close()
            return None
        return self._bg.spawn(coro)

    async def start(self, host: str = "127.0.0.1", port: int = 0):
        self._restore_state()
        self.server.register_service(self)
        self.server.register("subscribe", self._handle_subscribe)
        self.server.register("subscriber_poll", self._handle_subscriber_poll)
        bound = await self.server.start(host, port)
        self.address = (host, bound)
        self._runner = PeriodicRunner(asyncio.get_event_loop())
        self._runner.run_every(self.config.health_check_period_s, self._health_check)
        logger.info("GCS listening on %s:%s", host, bound)
        return self.address

    async def stop(self):
        self._stopped = True
        self._bg.cancel_all()
        if self._runner:
            self._runner.stop()
        await self.server.stop()
        await self.client_pool.close_all()
        self.storage.close()

    # -- persistence -------------------------------------------------------

    def _restore_state(self):
        """Reload durable tables on startup (reference: the GCS table
        reload path in gcs_server.cc + gcs_init_data.h). With the in-memory
        backend every table is empty and this is a no-op."""
        self._kv = self.storage.get_all("kv")
        for key, raw in self.storage.get_all("jobs").items():
            try:
                self._jobs[JobID.from_hex(key)] = pickle.loads(raw)
            except Exception:
                logger.exception("dropping unreadable job record %s", key)
        raw_next = self.storage.get("meta", "next_job")
        if raw_next is not None:
            self._next_job = int(raw_next)
        restored_nodes = set()
        restored_nodes |= self.actor_manager.restore_from(self.storage)
        restored_nodes |= self.pg_manager.restore_from(self.storage)
        self.weight_registry.restore_from(self.storage)
        self.timeseries.restore_from(self.storage)
        if restored_nodes:
            deadline = time.time() + self.config.health_check_timeout_s
            self._restored_nodes_pending = {
                nid: deadline for nid in restored_nodes
            }
            logger.info(
                "GCS restored state referencing %d node(s); waiting for "
                "re-registration", len(restored_nodes),
            )

    def _persist_job(self, job_id: JobID):
        job = self._jobs.get(job_id)
        if job is not None:
            self.storage.put("jobs", job_id.hex(), cloudpickle.dumps(job))

    # -- helpers -----------------------------------------------------------

    def raylet_client(self, node_id: NodeID) -> RpcClient:
        node = self._nodes[node_id]
        return self.client_pool.get(*node.address)

    def alive_nodes(self) -> Dict[NodeID, NodeInfo]:
        return {nid: n for nid, n in self._nodes.items() if n.alive}

    def node_available(self, node_id: NodeID) -> Dict[str, float]:
        avail = self._node_available.get(node_id)
        if avail is not None:
            return avail
        node = self._nodes.get(node_id)
        return dict(node.resources_total) if node else {}

    async def lease_worker_for_task(self, spec: TaskSpec):
        """Lease a worker for a GCS-scheduled task (actor creation), walking
        the spillback chain (reference: GcsActorScheduler leasing from
        raylets)."""
        nodes = self.alive_nodes()
        # prefer nodes that can fit the request right now
        candidates = sorted(
            nodes,
            key=lambda nid: -sum(
                min(self.node_available(nid).get(k, 0.0), v)
                for k, v in spec.resources.items()
            )
            if spec.resources
            else 0,
        )
        for nid in candidates:
            node = nodes[nid]
            if nid in self._node_suspect and len(candidates) > 1:
                # A partitioned-but-not-yet-dead node must not receive the
                # very replacements its suspicion triggered; with no other
                # candidate it stays eligible (better a suspect lease than
                # an unschedulable actor).
                continue
            feasible = all(
                node.resources_total.get(k, 0.0) >= v - 1e-9
                for k, v in spec.resources.items()
            ) and label_match(node.labels, spec.label_selector)
            if not feasible:
                continue
            raylet = self.raylet_client(nid)
            try:
                reply = await raylet.call("request_worker_lease", spec, timeout=30.0)
            except Exception as e:
                logger.debug("lease from %s failed: %s", nid, e)
                continue
            if reply.get("granted"):
                return (nid, reply["worker_id"], reply["worker_address"], reply["lease_id"])
            # spillback or rejection: try the next candidate
        return None

    # -- node table --------------------------------------------------------

    async def handle_register_node(
        self, info: NodeInfo, live_worker_ids=None, actor_workers=None
    ):
        self._nodes[info.node_id] = info
        self._node_last_seen[info.node_id] = time.time()
        self._node_suspect.pop(info.node_id, None)
        self._restored_nodes_pending.pop(info.node_id, None)
        self.publisher.publish("node", ("alive", info))
        # Re-registration after a GCS restart: name the actor workers this
        # raylet still runs whose actors have moved on — e.g. the node missed
        # the grace window, its actors restarted elsewhere, and now two
        # incarnations would run side effects. Computed BEFORE reconcile so
        # current records are compared, then vanished workers are failed.
        stale_workers = []
        if actor_workers:
            for worker_id, actor_id in actor_workers.items():
                actor = self.actor_manager.get(actor_id)
                if actor is not None:
                    if (
                        actor.state == ActorState.DEAD
                        or actor.worker_id != worker_id
                    ):
                        stale_workers.append(worker_id)
                elif self.actor_manager.is_tombstoned(actor_id):
                    # terminally dead, record compacted to a tombstone
                    stale_workers.append(worker_id)
                # unknown with no tombstone: a blank (in-memory) GCS restart
                # — judging the worker stale here would SIGKILL every live
                # actor in the cluster on a transient GCS bounce
        self.actor_manager.reconcile_node(info.node_id, live_worker_ids)
        logger.info(
            "node %s registered: %s labels=%s", info.node_id, info.resources_total,
            info.labels,
        )
        return {"ok": True, "stale_workers": stale_workers}

    async def handle_unregister_node(self, node_id: NodeID):
        await self._mark_node_dead(node_id, "drained")
        return True

    async def handle_get_all_nodes(self) -> List[NodeInfo]:
        return list(self._nodes.values())

    async def handle_get_node_states(self) -> Dict[str, str]:
        """Three-valued liveness per node: ALIVE | SUSPECT | DEAD, keyed by
        node-id hex. SUSPECT (reports stopped, probe ran) is what the serve
        controller keys replica replacement on before the full dead window
        elapses."""
        out: Dict[str, str] = {}
        for node_id, node in self._nodes.items():
            if not node.alive:
                out[node_id.hex()] = "DEAD"
            elif node_id in self._node_suspect:
                out[node_id.hex()] = "SUSPECT"
            else:
                out[node_id.hex()] = "ALIVE"
        return out

    async def handle_chaos_fetch(self) -> Optional[bytes]:
        """Raw chaos-mesh spec for pollers (util/chaosnet.py). The method
        name is chaos-EXEMPT in the RPC layer on both sides: clearing a
        partition must propagate through the partition being cleared."""
        return self._kv.get(gcs_keys.CHAOS_NET_SPEC)

    async def handle_report_resources_delta(
        self,
        node_id: NodeID,
        version: int,
        base_version: Optional[int],
        changed: Optional[Dict[str, float]] = None,
        removed: Optional[list] = None,
        demands: Optional[list] = None,
    ):
        """Versioned, delta-suppressed resource view from each raylet (role
        of RaySyncer RESOURCE_VIEW streams, ray_syncer.h:89): steady-state
        reports carry no payload (pure liveness heartbeat); a change ships
        only the touched resource keys against the last acked version;
        ``base_version=None`` is a full snapshot (registration or resync).
        A base mismatch (GCS restart, lost report) returns ``resync`` and
        the raylet re-sends a snapshot. Applied views are re-broadcast to
        subscribed raylets for spillback decisions; ``demands`` carries the
        raylet's queued lease requests for the autoscaler (reference:
        GcsAutoscalerStateManager, gcs_autoscaler_state_manager.h:41)."""
        if node_id not in self._nodes:
            # this GCS restarted and does not know the reporter: tell the
            # raylet to re-register (reference: NotifyGCSRestart /
            # RegisterNodeAgain, node_manager.proto:426)
            return "unknown_node"
        self._node_last_seen[node_id] = time.time()
        if self._node_suspect.pop(node_id, None) is not None:
            logger.info("node %s reporting again; suspicion cleared", node_id)
        if base_version is None:
            # full snapshot
            avail = dict(changed or {})
            self._node_available[node_id] = avail
            self._node_sync_versions[node_id] = version
            if demands is not None:
                self._node_demands[node_id] = demands
            self.publisher.publish("resource_view", (node_id, avail))
            return {"ack": version}
        if self._node_sync_versions.get(node_id) != base_version:
            return {"resync": True}
        if version != base_version:
            self._node_sync_versions[node_id] = version
            if demands is not None:
                self._node_demands[node_id] = demands
            if changed or removed:
                avail = dict(self._node_available.get(node_id, {}))
                for key, value in (changed or {}).items():
                    avail[key] = value
                for key in removed or ():
                    avail.pop(key, None)
                self._node_available[node_id] = avail
                # demands-only deltas feed the autoscaler, not the
                # resource_view fan-out — broadcasting an unchanged
                # availability map per period would re-create the very
                # O(nodes x rate) cost delta sync removes
                self.publisher.publish("resource_view", (node_id, avail))
        return {"ack": version}

    async def handle_get_cluster_resource_state(self) -> dict:
        """Autoscaler view of the cluster (reference:
        GetClusterResourceState RPC, protobuf/autoscaler.proto:187)."""
        nodes = []
        for node_id, info in self._nodes.items():
            nodes.append(
                {
                    "node_id": node_id,
                    "alive": info.alive,
                    "is_head": info.is_head,
                    "resources_total": dict(info.resources_total),
                    "available": dict(self._node_available.get(node_id, {})),
                    "labels": dict(info.labels),
                }
            )
        demands = []
        for node_demands in self._node_demands.values():
            demands.extend(node_demands)
        pending_pgs = [
            {
                "pg_id": info.placement_group_id,
                "strategy": info.strategy,
                "bundles": [dict(b.resources) for b in info.bundles],
            }
            for info in self.pg_manager.pending_infos()
        ]
        return {
            "nodes": nodes,
            "pending_demands": demands,
            "pending_placement_groups": pending_pgs,
        }

    async def handle_report_autoscaling_state(self, state: dict):
        """Autoscaler posts its view for observability (reference:
        ReportAutoscalingState RPC, autoscaler.proto:199)."""
        self._autoscaling_state = state
        return True

    async def handle_get_autoscaling_state(self):
        return self._autoscaling_state

    async def _health_check(self):
        """Mark nodes dead when they stop reporting (reference:
        GcsHealthCheckManager, gcs_health_check_manager.h:45)."""
        now = time.time()
        for node_id, node in list(self._nodes.items()):
            if not node.alive:
                continue
            last = self._node_last_seen.get(node_id, now)
            age = now - last
            if age > self.config.health_check_timeout_s:
                await self._mark_node_dead(node_id, "health check timed out")
            elif (
                age > self.config.suspect_after_s
                and node_id not in self._node_suspect
            ):
                # reports stopped: probe the raylet actively instead of
                # sitting out the rest of the dead window passively
                self._node_suspect[node_id] = now
                self.spawn(self._probe_node(node_id, age))
        # Nodes referenced by restored state that never re-registered: their
        # raylets died with the previous GCS — fail their actors/bundles.
        for node_id, deadline in list(self._restored_nodes_pending.items()):
            if now > deadline and node_id not in self._nodes:
                self._restored_nodes_pending.pop(node_id, None)
                logger.warning(
                    "restored node %s never re-registered; declaring dead",
                    node_id,
                )
                # synthesize the dead broadcast _mark_node_dead would have
                # sent: surviving raylets must drop the node from their
                # cluster views or spillback keeps targeting it. Only the
                # node_id survived the restart, so the stub carries that.
                self.publisher.publish(
                    "node",
                    (
                        "dead",
                        NodeInfo(
                            node_id=node_id,
                            address=("", 0),
                            object_store_address="",
                            resources_total={},
                            alive=False,
                        ),
                    ),
                )
                await self.actor_manager.on_node_death(node_id)
                await self.pg_manager.on_node_death(node_id)
        # telemetry evaluation rides the health cadence so alerts resolve
        # and retention reaps even when no worker is pushing series
        self.timeseries.evaluate(now, force=True)

    async def _probe_node(self, node_id: NodeID, report_age_s: float):
        """Active liveness probe of a node whose reports stopped (reference:
        GcsHealthCheckManager's grpc health checks — ours layers on top of
        the passive report age). Confirms the SUSPECT transition: if a
        report raced in while probing, suspicion clears silently; otherwise
        the node is recorded SUSPECT with the probe verdict (reachable =
        control plane asymmetric, likely a directional partition; not
        reachable = node fully gone, the dead window will catch it)."""
        node = self._nodes.get(node_id)
        if node is None or not node.alive:
            self._node_suspect.pop(node_id, None)
            return
        reachable = False
        try:
            await self.client_pool.get(*node.address).call(
                "ping", timeout=max(self.config.health_check_period_s, 1.0)
            )
            reachable = True
        except Exception:
            pass
        if node_id not in self._node_suspect:
            return  # a report landed while probing
        age = time.time() - self._node_last_seen.get(node_id, 0.0)
        if age <= self.config.suspect_after_s:
            self._node_suspect.pop(node_id, None)
            return
        logger.warning(
            "node %s SUSPECT: no report for %.1fs, raylet %s",
            node_id, age, "reachable" if reachable else "unreachable",
        )
        record_event(
            NODE_SUSPECT,
            node=node_id.hex(),
            report_age_s=round(report_age_s, 3),
            reachable=reachable,
        )
        self.publisher.publish("node", ("suspect", node))

    async def _mark_node_dead(self, node_id: NodeID, reason: str):
        node = self._nodes.get(node_id)
        if node is None or not node.alive:
            return
        node.alive = False
        self._node_suspect.pop(node_id, None)
        self._node_available.pop(node_id, None)
        # invalidate the delta-sync stream: if this raylet was only
        # partitioned and reports again, a base-version match would apply
        # its delta onto the now-empty availability dict and publish a
        # partial view forever — a popped version forces a resync/snapshot
        self._node_sync_versions.pop(node_id, None)
        logger.warning("node %s dead: %s", node_id, reason)
        self._reap_node_metrics(node_id)
        self._abort_member_groups(node_hex=node_id.hex(), reason=reason)
        self.publisher.publish("node", ("dead", node))
        self.weight_registry.on_node_death(node.address)
        self.kvtier_registry.on_node_death(node.address)
        await self.actor_manager.on_node_death(node_id)
        await self.pg_manager.on_node_death(node_id)

    # -- workers -----------------------------------------------------------

    async def handle_report_worker_death(self, worker_id: WorkerID, reason: str):
        # synthetic flight-recorder marker: the dead worker can't dump its
        # own ring (SIGKILL), but its continuously pushed events are already
        # here — this stitches the death cause into the same event stream
        self.append_synthetic_event(
            "worker_death", worker_id=worker_id.hex(), reason=reason
        )
        await self.actor_manager.on_worker_death(worker_id, reason)
        # reap the dead worker's pushed metrics snapshot, or its series
        # would live in every /metrics scrape forever
        self._drop_metrics_key(gcs_keys.METRICS.key(worker_id.hex()))
        # abort any collective group the dead worker was a member of, so
        # surviving ranks blocked in a rendezvous unblock within ~1 s
        # instead of burning the full timeout (covers raylet
        # connection-loss AND memory-monitor recall kills — both land here)
        self._abort_member_groups(worker_hex=worker_id.hex(), reason=reason)
        return True

    def _abort_member_groups(self, *, worker_hex: str = None,
                             node_hex: str = None, reason: str = ""):
        """Scan ``colmember:<group>:<epoch>:<rank>`` registrations and write
        ``colabort:<group>`` (ascii epoch, monotonic max) for every group
        the dead worker/node belonged to. Plain-ascii value on purpose: the
        server writes it without the client serialization module, and any
        client can parse it with int()."""
        for key in [k for k in self._kv
                    if gcs_keys.COLLECTIVE_MEMBER.matches(k)]:
            try:
                payload = json.loads(self._kv[key])
            except Exception:
                continue
            if not isinstance(payload, dict):
                continue
            if worker_hex is not None and payload.get("worker_id") != worker_hex:
                continue
            if node_hex is not None and payload.get("node_id") != node_hex:
                continue
            # group names may themselves contain ':' — epoch and rank are
            # always the last two segments
            parts = gcs_keys.COLLECTIVE_MEMBER.rsplit_tail(key, 2)
            if len(parts) != 3:
                continue
            group, epoch_s, _rank = parts
            try:
                epoch = int(epoch_s)
            except ValueError:
                continue
            abort_key = gcs_keys.COLLECTIVE_ABORT.key(group)
            prev = self._kv.get(abort_key)
            try:
                prev_epoch = int(prev.decode()) if prev is not None else -1
            except (ValueError, UnicodeDecodeError):
                prev_epoch = -1
            if epoch > prev_epoch:
                value = str(epoch).encode()
                self._kv[abort_key] = value
                self.storage.put("kv", abort_key, value)
                logger.warning(
                    "collective group %r epoch %d aborted: member rank %s "
                    "died (%s)", group, epoch, _rank, reason,
                )
            # the registration served its purpose; drop it so a later
            # unrelated death doesn't rescan a dead member
            self._kv.pop(key, None)
            try:
                self.storage.delete("kv", key)
            except Exception:
                pass

    def _drop_metrics_key(self, key: str):
        if self._kv.pop(key, None) is not None:
            try:
                self.storage.delete("kv", key)
            except Exception:
                pass

    def _reap_node_metrics(self, node_id: NodeID):
        """Drop metrics snapshots pushed by workers of a dead node: every
        push is tagged with the pusher's node identity (util/metrics), so a
        node death reaps all of its workers' series at once."""
        want = node_id.hex()
        for key in [k for k in self._kv if gcs_keys.METRICS.matches(k)]:
            try:
                payload = json.loads(self._kv[key])
            except Exception:
                continue
            if isinstance(payload, dict) and payload.get("node_id") == want:
                self._drop_metrics_key(key)

    # -- internal KV (reference: GcsInternalKVManager) ---------------------

    async def handle_kv_put(self, key: str, value: bytes, overwrite: bool = True):
        if not overwrite and key in self._kv:
            return False
        self._kv[key] = value
        self.storage.put("kv", key, value)
        return True

    async def handle_kv_get(self, key: str) -> Optional[bytes]:
        return self._kv.get(key)

    async def handle_kv_multi_get(self, keys: List[str]):
        return {k: self._kv.get(k) for k in keys}

    async def handle_kv_del(self, key: str):
        self.storage.delete("kv", key)
        return self._kv.pop(key, None) is not None

    async def handle_kv_exists(self, key: str):
        return key in self._kv

    async def handle_kv_keys(self, prefix: str = ""):
        return [k for k in self._kv if k.startswith(prefix)]

    # -- pubsub ------------------------------------------------------------

    async def _handle_subscribe(self, subscriber_id: str, channel: str):
        self.publisher.subscribe(subscriber_id, channel)
        return True

    async def _handle_subscriber_poll(self, subscriber_id: str):
        return await self.publisher.poll(subscriber_id, timeout=30.0)

    async def handle_publish(self, channel: str, message):
        self.publisher.publish(channel, message)
        return True

    # -- jobs --------------------------------------------------------------

    # -- task events (reference: TaskEventBuffer -> GcsTaskManager ->
    # state API `ray list tasks`) -----------------------------------------

    _TASK_STATE_RANK = {
        "PENDING": 0,
        "RUNNING": 1,
        "FINISHED": 2,
        "FAILED": 2,
    }

    async def handle_report_task_events(self, events: List[dict]):
        for ev in events:
            tid = ev["task_id"]
            # keep a per-state timestamp so the timeline view can compute
            # durations (reference: per-state ts in GcsTaskManager events
            # feeding `ray timeline` chrome traces)
            if ev.get("state") and "ts" in ev:
                ev = {**ev, f"ts_{ev['state'].lower()}": ev["ts"]}
            cur = self._task_events.get(tid)
            if cur is None:
                self._task_events[tid] = dict(ev)
                self._task_events_order.append(tid)
                if len(self._task_events_order) > self._task_events_cap:
                    drop = self._task_events_order.pop(0)
                    self._task_events.pop(drop, None)
            else:
                # events arrive from different processes on independent
                # flush cadences: never let a late RUNNING (executor) regress
                # a FINISHED/FAILED (owner) state, and never let a stale
                # duplicate flush flip one terminal state into the other —
                # terminal->different-terminal only applies with a newer
                # attempt number
                new_state = ev.get("state")
                if new_state is not None:
                    new_attempt = ev.get("attempt", 0)
                    cur_attempt = cur.get("attempt", 0)
                    if new_attempt < cur_attempt:
                        # an older attempt's event (late flush from a worker
                        # the task was retried away from): its state/node/
                        # worker describe the wrong attempt and must not
                        # overwrite anything — but attempt-invariant fields
                        # the record still lacks (name/type/job_id, carried
                        # only by the owner's PENDING event) are kept
                        for k, v in ev.items():
                            if (
                                k
                                not in (
                                    "state",
                                    "attempt",
                                    "error",
                                    "ts",
                                    "node_id",
                                    "worker_pid",
                                )
                                and k not in cur
                            ):
                                cur[k] = v
                        continue
                    if new_attempt == cur_attempt:
                        new_rank = self._TASK_STATE_RANK.get(new_state, 0)
                        cur_rank = self._TASK_STATE_RANK.get(
                            cur.get("state"), 0
                        )
                        regress = new_rank < cur_rank
                        terminal_flip = (
                            new_rank == 2
                            and cur_rank == 2
                            and new_state != cur.get("state")
                        )
                        if regress or terminal_flip:
                            # same attempt, stale ordering (executor's
                            # RUNNING flush landing after the owner's
                            # terminal event): keep the terminal state but
                            # merge the metadata only the executor knows
                            # (node_id/worker_pid)
                            ev = {
                                k: v
                                for k, v in ev.items()
                                if k
                                not in ("state", "attempt", "error", "ts")
                            }
                    # new_attempt > cur_attempt: newer attempt wins outright
                cur.update(ev)
        return True

    async def handle_list_task_events(
        self, filters: Optional[dict] = None, limit: int = 1000
    ):
        out = []
        for tid in reversed(self._task_events_order):
            ev = self._task_events[tid]
            if filters and any(ev.get(k) != v for k, v in filters.items()):
                continue
            out.append(dict(ev))
            if len(out) >= limit:
                break
        return out

    # -- span store (cluster-wide tracing; see util/tracing.py) ------------

    async def handle_report_spans(self, spans: List[dict]):
        self._spans.extend(spans)
        if len(self._spans) > self._spans_cap:
            del self._spans[: len(self._spans) - self._spans_cap]
        return True

    async def handle_list_spans(self, limit: int = 100000):
        return self._spans[-limit:]

    # -- flight-recorder event store (see util/events.py) ------------------

    def _trim_events(self):
        if len(self._events) > self._events_cap:
            drop = len(self._events) - self._events_cap
            del self._events[:drop]
            self._events_dropped += drop

    def append_synthetic_event(self, name: str, **fields):
        """Server-originated flight-recorder entry (worker deaths, straggler
        verdicts, alert transitions): the source process can't or won't push
        one, so the store stitches it into the same stream itself."""
        ev = {"ts": time.time(), "pid": None, "name": str(name),
              "synthetic": True}
        ev.update(fields)
        self._events.append(ev)
        self._trim_events()

    async def handle_report_events(self, events: List[dict]):
        self._events.extend(events)
        self._trim_events()
        return True

    async def handle_list_events(
        self, limit: int = 1000, name: Optional[str] = None,
        since: Optional[float] = None,
    ):
        events = self._events
        if name is not None:
            events = [e for e in events if e.get("name") == name]
        if since is not None:
            events = [e for e in events if e.get("ts", 0) >= since]
        return events[-limit:]

    async def handle_events_stats(self):
        """Truncation accounting for /api/events: how much history the
        store itself has already forgotten."""
        return {
            "stored": len(self._events),
            "cap": self._events_cap,
            "dropped_total": self._events_dropped,
        }

    # -- telemetry time-series plane (see util/timeseries.py) --------------

    async def handle_ts_push(self, payload: dict) -> int:
        return self.timeseries.push(payload)

    async def handle_ts_query(
        self, name: Optional[str] = None, labels: Optional[dict] = None,
        since: Optional[float] = None, worker_id: Optional[str] = None,
        limit_points: int = 500,
    ):
        return self.timeseries.query(
            name=name, labels=labels, since=since, worker_id=worker_id,
            limit_points=limit_points,
        )

    async def handle_ts_list(self):
        return self.timeseries.list_series()

    async def handle_alerts_snapshot(self):
        return self.timeseries.alerts_snapshot()

    async def handle_alerts_set_rule(self, rule: dict):
        return self.timeseries.set_rule(rule)

    async def handle_alerts_delete_rule(self, name: str) -> bool:
        return self.timeseries.delete_rule(name)

    async def handle_straggler_verdicts(self):
        self.timeseries.evaluate()
        return self.timeseries.straggler_detector.verdicts()

    async def handle_register_job(self, metadata: dict) -> JobID:
        job_id = JobID.from_int(self._next_job)
        self._next_job += 1
        self._jobs[job_id] = {"metadata": metadata, "start_time": time.time()}
        self.storage.put("meta", "next_job", str(self._next_job).encode())
        self._persist_job(job_id)
        self.publisher.publish("job", ("started", job_id))
        return job_id

    async def handle_finish_job(self, job_id: JobID):
        job = self._jobs.get(job_id)
        if job is not None:
            job["end_time"] = time.time()
            self._persist_job(job_id)
        await self.actor_manager.on_job_finished(job_id)
        self.publisher.publish("job", ("finished", job_id))
        return True

    async def handle_list_jobs(self):
        return dict(self._jobs)

    # -- actors ------------------------------------------------------------

    async def handle_register_actor(self, spec: TaskSpec, detached: bool) -> ActorInfo:
        return await self.actor_manager.register_actor(spec, detached)

    async def handle_get_actor(self, actor_id: ActorID) -> Optional[ActorInfo]:
        return self.actor_manager.get(actor_id)

    async def handle_get_actor_by_name(self, name: str, namespace: str):
        return self.actor_manager.get_by_name(name, namespace)

    async def handle_list_actors(self):
        return self.actor_manager.list_actors()

    async def handle_kill_actor(self, actor_id: ActorID, no_restart: bool = True):
        await self.actor_manager.kill_actor(actor_id, no_restart)
        return True

    # -- weight plane (ray_tpu.weights registry) ---------------------------

    async def handle_weights_publish(
        self, name: str, manifest_blob: bytes, meta: Optional[dict] = None
    ):
        return self.weight_registry.publish(name, manifest_blob, meta)

    async def handle_weights_get(self, name: str, version: Optional[int] = None):
        return self.weight_registry.get(name, version)

    async def handle_weights_head(self, name: str):
        return self.weight_registry.head(name)

    async def handle_weights_pin(self, name: str, version: int, reader_id: str):
        return self.weight_registry.pin(name, version, reader_id)

    async def handle_weights_unpin(self, name: str, version: int, reader_id: str):
        return self.weight_registry.unpin(name, version, reader_id)

    async def handle_weights_collect(self, name: str):
        return self.weight_registry.collect(name)

    async def handle_weights_plan(self, name: str, node_address):
        return self.weight_registry.plan(name, node_address)

    async def handle_weights_report_fallback(self, name: str, node_address):
        self.weight_registry.report_fallback(name, node_address)
        return True

    async def handle_weights_list(self):
        return self.weight_registry.list_models()

    # -- KV prefix tier (ray_tpu.kvtier registry) --------------------------

    async def handle_kvtier_register(
        self, model: str, fps: List[str], holder_id: str, holder_address,
        blob: bytes, meta: Optional[dict] = None
    ):
        return self.kvtier_registry.register(
            model, fps, holder_id, holder_address, blob, meta
        )

    async def handle_kvtier_resolve(self, model: str, fps: List[str]):
        return self.kvtier_registry.resolve(model, fps)

    async def handle_kvtier_lease(self, entry_id: int, lease_id: str):
        return self.kvtier_registry.lease(entry_id, lease_id)

    async def handle_kvtier_release(self, entry_id: int, lease_id: str):
        return self.kvtier_registry.release(entry_id, lease_id)

    async def handle_kvtier_evict(
        self, entry_ids: List[int], holder_id: Optional[str] = None
    ):
        return self.kvtier_registry.evict(entry_ids, holder_id)

    async def handle_kvtier_collect(self, holder_id: str):
        return self.kvtier_registry.collect(holder_id)

    async def handle_kvtier_stats(self):
        return self.kvtier_registry.stats()

    # -- placement groups --------------------------------------------------

    async def handle_create_placement_group(self, info: PlacementGroupInfo):
        return await self.pg_manager.create(info)

    async def handle_remove_placement_group(self, pg_id: PlacementGroupID):
        await self.pg_manager.remove(pg_id)
        return True

    async def handle_get_placement_group(self, pg_id: PlacementGroupID):
        return self.pg_manager.get(pg_id)

    async def handle_get_placement_group_by_name(self, name: str):
        return self.pg_manager.get_by_name(name)

    async def handle_pg_wait_ready(self, pg_id: PlacementGroupID, timeout=None):
        return await self.pg_manager.wait_ready(pg_id, timeout)

    async def handle_list_placement_groups(self):
        return self.pg_manager.list_groups()

    # -- cluster info ------------------------------------------------------

    async def handle_cluster_resources(self):
        total: Dict[str, float] = {}
        for node in self.alive_nodes().values():
            for k, v in node.resources_total.items():
                total[k] = total.get(k, 0.0) + v
        return total

    async def handle_cluster_available_resources(self):
        avail: Dict[str, float] = {}
        for nid in self.alive_nodes():
            for k, v in self.node_available(nid).items():
                avail[k] = avail.get(k, 0.0) + v
        return avail
