"""GCS weight registry: the control plane of the weight plane.

Durable directory of named models with monotonically versioned manifests
(role analogue of the actor directory, but for model state): publishers
register a new manifest per publish and get back the assigned version;
subscribers resolve head (or a pinned version), take version pins that
block garbage collection, and receive broadcast-tree positions so chunk
pulls fan out node-to-node instead of hammering the publisher.

GC mirrors the actor-tombstone compaction pattern (actor_manager.py
_mark_dead): a superseded version with no pinned readers is compacted to a
tombstone — manifest deleted from storage, a tiny marker written instead —
and queued on a per-model ``released`` list. Only the PUBLISHER drains
``released`` (through its publish reply or an explicit weights_collect):
subscriber unpins trigger GC but never consume the queue, so a release
produced by a late unpin is delivered on the publisher's next
publish/collect instead of vanishing into a reply nobody reads.
Head versions are never GC'd.

Pins are leases, not permanent marks: a pin older than
``weights_pin_lease_s`` is reaped during GC, so a crashed reader (whose
restart pins under a fresh reader_id) cannot block tombstoning forever.
Live subscribers refresh their pins as a heartbeat (weights_pin is
idempotent and re-timestamps). Pins are NOT persisted: after a GCS restart
superseded versions survive until the next publish/unpin/collect cycle
re-judges them, so readers that re-pin promptly keep their version.
"""

from __future__ import annotations

import logging
import time
from typing import Dict, List, Optional, Set, Tuple, TYPE_CHECKING

if TYPE_CHECKING:
    from .server import GcsServer
    from .store import StoreClient

logger = logging.getLogger(__name__)


class _Model:
    __slots__ = (
        "name", "head", "versions", "meta", "pins", "released",
        "tombstones", "subscriber_nodes", "fallback_reports",
    )

    def __init__(self, name: str):
        self.name = name
        self.head: int = 0  # 0 = nothing published yet
        # version -> opaque manifest blob (serialized client-side; the
        # registry never decodes it, so manifest evolution is client-only)
        self.versions: Dict[int, bytes] = {}
        # version -> {"total_bytes": int, "num_chunks": int, "ts": float}
        self.meta: Dict[int, dict] = {}
        # version -> reader_id -> pin timestamp (a lease: reaped when older
        # than weights_pin_lease_s; re-pinning refreshes it)
        self.pins: Dict[int, Dict[str, float]] = {}
        # tombstoned versions whose chunks the publisher may free, drained
        # ONLY by the publisher (publish reply / weights_collect)
        self.released: List[int] = []
        self.tombstones: Set[int] = set()
        # broadcast-tree membership: raylet addresses in first-subscribe
        # order; a node's index is its stable tree position. Pruned on node
        # death and on repeated child fallback reports.
        self.subscriber_nodes: List[Tuple[str, int]] = []
        # node -> count of children that gave up waiting on it as a parent
        self.fallback_reports: Dict[Tuple[str, int], int] = {}


def _tree_parent(position: int) -> Optional[int]:
    """Binomial broadcast tree over subscriber positions: position 0 seeds
    from the publisher; every other position's parent clears its highest
    set bit (children of 0 are 1, 2, 4, 8, ...)."""
    if position <= 0:
        return None
    return position - (1 << (position.bit_length() - 1))


def _tree_depth(num_nodes: int) -> int:
    """Hops from the publisher to the deepest subscriber node: 1 for the
    seed plus the longest clear-highest-bit chain, i.e. the max popcount of
    any position < num_nodes — which is ``num_nodes.bit_length()`` total."""
    if num_nodes <= 0:
        return 0
    return num_nodes.bit_length()


class GcsWeightRegistry:
    def __init__(self, gcs: "GcsServer"):
        self._gcs = gcs
        self._models: Dict[str, _Model] = {}

    # -- persistence -------------------------------------------------------

    def _persist_version(self, model: _Model, version: int):
        try:
            self._gcs.storage.put(
                "weights", f"{model.name}:{version}", model.versions[version]
            )
            self._gcs.storage.put(
                "weights_meta",
                model.name,
                str(model.head).encode(),
            )
        except Exception:
            logger.exception(
                "failed to persist weights %s:%d", model.name, version
            )

    def restore_from(self, storage: "StoreClient"):
        """Reload manifests + heads after a GCS restart: the head version of
        every model stays resolvable; superseded-but-unGC'd versions come
        back resident and are re-judged on the next publish/unpin."""
        for key in storage.get_all("weight_tombstones"):
            name, _, v = key.rpartition(":")
            model = self._models.setdefault(name, _Model(name))
            try:
                model.tombstones.add(int(v))
            except ValueError:
                logger.exception("dropping unreadable weight tombstone %s", key)
        for key, raw in storage.get_all("weights").items():
            name, _, v = key.rpartition(":")
            try:
                version = int(v)
            except ValueError:
                logger.exception("dropping unreadable weight record %s", key)
                continue
            model = self._models.setdefault(name, _Model(name))
            model.versions[version] = raw
            model.head = max(model.head, version)
        for name, raw in storage.get_all("weights_meta").items():
            model = self._models.setdefault(name, _Model(name))
            try:
                model.head = max(model.head, int(raw))
            except ValueError:
                pass
        if self._models:
            logger.info(
                "restored %d weight model(s): %s",
                len(self._models),
                {m.name: m.head for m in self._models.values()},
            )

    # -- publish / resolve -------------------------------------------------

    def publish(
        self, name: str, manifest_blob: bytes, meta: Optional[dict] = None
    ) -> dict:
        """Register a new version; returns the assigned version, every
        version whose chunks the publisher may now free, and the live set
        (so the publisher can reconcile refs held for versions the registry
        no longer lists — e.g. released-lists lost with a GCS restart)."""
        model = self._models.setdefault(name, _Model(name))
        model.head += 1
        version = model.head
        model.versions[version] = manifest_blob
        model.meta[version] = {**(meta or {}), "ts": time.time()}
        self._persist_version(model, version)
        self._gc_superseded(model)
        self._gcs.publisher.publish("weights", ("published", name, version))
        return {
            "version": version,
            "released": self._drain_released(model),
            "live": sorted(model.versions),
        }

    def get(self, name: str, version: Optional[int] = None) -> Optional[dict]:
        model = self._models.get(name)
        if model is None or model.head == 0:
            return None
        v = model.head if version is None else version
        blob = model.versions.get(v)
        if blob is None:
            return None
        return {"version": v, "manifest": blob, "head": model.head}

    def head(self, name: str) -> Optional[int]:
        model = self._models.get(name)
        return model.head if model is not None and model.head else None

    # -- pins + GC ---------------------------------------------------------

    def pin(self, name: str, version: int, reader_id: str) -> bool:
        model = self._models.get(name)
        if model is None or version not in model.versions:
            return False
        model.pins.setdefault(version, {})[reader_id] = time.time()
        return True

    def unpin(self, name: str, version: int, reader_id: str) -> bool:
        """Drop one reader's pin and re-judge GC. Deliberately does NOT
        drain ``released``: the caller is a subscriber, and a release
        drained into a reply the subscriber ignores is lost forever — the
        publisher would hold the version's chunk refs (and their store
        weight-pins) for the rest of the run. Tombstoned versions stay
        queued for the publisher's next publish/collect drain."""
        model = self._models.get(name)
        if model is None:
            return False
        readers = model.pins.get(version)
        if readers is not None:
            readers.pop(reader_id, None)
            if not readers:
                model.pins.pop(version, None)
        self._gc_superseded(model)
        return True

    def collect(self, name: str) -> dict:
        """Publisher-side GC poll: versions safe to free now, plus the set
        still live (a publisher also drops refs for anything it holds that
        the registry no longer lists — covers released-lists lost with a
        GCS restart). Runs a GC pass first so expired pin leases are reaped
        even when no publish/unpin has happened since they lapsed."""
        model = self._models.get(name)
        if model is None:
            return {"released": [], "live": []}
        self._gc_superseded(model)
        return {
            "released": self._drain_released(model),
            "live": sorted(model.versions),
        }

    def _reap_expired_pins(self, model: _Model):
        """Expire pin leases older than weights_pin_lease_s: a crashed or
        partitioned reader must not block tombstoning forever (its restart
        pins under a fresh reader_id, so its old pin is unreachable). Live
        readers refresh their leases via pin() heartbeats."""
        lease = getattr(self._gcs.config, "weights_pin_lease_s", 0.0)
        if not lease or lease <= 0:
            return
        now = time.time()
        for version, readers in list(model.pins.items()):
            expired = [r for r, ts in readers.items() if now - ts > lease]
            for reader_id in expired:
                readers.pop(reader_id, None)
                logger.warning(
                    "weights %s v%d: reaping expired pin lease of reader %s",
                    model.name, version, reader_id,
                )
            if not readers:
                model.pins.pop(version, None)

    def _gc_superseded(self, model: _Model):
        self._reap_expired_pins(model)
        for version in sorted(model.versions):
            if version >= model.head:
                continue  # head is never GC'd
            if model.pins.get(version):
                continue  # pinned readers block the tombstone
            model.versions.pop(version, None)
            model.meta.pop(version, None)
            model.tombstones.add(version)
            model.released.append(version)
            try:
                self._gcs.storage.delete("weights", f"{model.name}:{version}")
                self._gcs.storage.put(
                    "weight_tombstones", f"{model.name}:{version}", b"1"
                )
            except Exception:
                logger.exception(
                    "failed to compact weights %s:%d", model.name, version
                )
            self._gcs.publisher.publish(
                "weights", ("tombstoned", model.name, version)
            )

    def _drain_released(self, model: _Model) -> List[int]:
        released, model.released = model.released, []
        return released

    # -- broadcast-tree planning ------------------------------------------

    def plan(self, name: str, node_address) -> dict:
        """Assign (or look up) a node's position in the model's binomial
        broadcast tree. Parent ``None`` means "pull from the publisher" —
        only the seed (position 0) does, which is what makes publisher
        upload volume O(1) in subscriber-node count."""
        model = self._models.setdefault(name, _Model(name))
        node = tuple(node_address)
        try:
            position = model.subscriber_nodes.index(node)
        except ValueError:
            position = len(model.subscriber_nodes)
            model.subscriber_nodes.append(node)
        parent_pos = _tree_parent(position)
        depth = _tree_depth(len(model.subscriber_nodes))
        return {
            "position": position,
            "parent": (
                model.subscriber_nodes[parent_pos]
                if parent_pos is not None
                else None
            ),
            "num_nodes": len(model.subscriber_nodes),
            "depth": depth,
        }

    def on_node_death(self, node_address) -> None:
        """Drop a dead node from every model's broadcast tree so children
        stop burning weights_prefer_wait_s per chunk on an unreachable
        parent, and subscriber_nodes stays bounded under autoscaling churn.
        Positions are recomputed from list order on each plan() call, so
        removal reparents affected children on their next fetch."""
        node = tuple(node_address)
        for model in self._models.values():
            self._prune_node(model, node)

    def report_fallback(self, name: str, node_address) -> None:
        """A child reports that it gave up waiting on ``node_address`` as
        its broadcast parent (unreachable, or never produced a chunk within
        the wait budget). Health checks catch dead *nodes*; this catches
        hung-but-connectable ones. Two independent reports prune the node —
        a live node that was merely slow simply re-subscribes and is
        re-appended at a fresh position on its next plan()."""
        model = self._models.get(name)
        if model is None:
            return
        node = tuple(node_address)
        if node not in model.subscriber_nodes:
            return
        count = model.fallback_reports.get(node, 0) + 1
        if count >= 2:
            self._prune_node(model, node)
        else:
            model.fallback_reports[node] = count

    def _prune_node(self, model: _Model, node: Tuple[str, int]):
        if node in model.subscriber_nodes:
            model.subscriber_nodes.remove(node)
            logger.info(
                "weights %s: pruned node %s from broadcast tree (%d left)",
                model.name, node, len(model.subscriber_nodes),
            )
        model.fallback_reports.pop(node, None)

    # -- introspection (state API / CLI) -----------------------------------

    def list_models(self) -> List[dict]:
        out = []
        for model in self._models.values():
            if model.head == 0:
                continue
            head_meta = model.meta.get(model.head, {})
            out.append(
                {
                    "name": model.name,
                    "head": model.head,
                    "versions": sorted(model.versions),
                    "pinned": {
                        v: sorted(readers)
                        for v, readers in model.pins.items()
                        if readers
                    },
                    "tombstoned": len(model.tombstones),
                    "subscriber_nodes": len(model.subscriber_nodes),
                    "tree_depth": _tree_depth(len(model.subscriber_nodes)),
                    "total_bytes": head_meta.get("total_bytes"),
                    "num_chunks": head_meta.get("num_chunks"),
                    # chunk codec + encoded size of the head version: how
                    # `ray_tpu list weights` shows whether a model rides
                    # the wire compressed (wire < total => int8 codec)
                    "codec": head_meta.get("codec", "raw"),
                    "wire_bytes": head_meta.get("wire_bytes"),
                }
            )
        return out
