"""OOM defense: system memory monitor + worker-killing policy.

Role-equivalent of the reference's MemoryMonitor (src/ray/common/
memory_monitor.h:52) and the worker-killing policies
(src/ray/raylet/worker_killing_policy.h:33,
worker_killing_policy_group_by_owner.h:87): the raylet polls system (or
cgroup) memory; above the usage threshold it kills the leased worker whose
loss is cheapest — retriable tasks first, grouped by submitting owner so a
fan-out caller loses one of many tasks rather than a lone task dying, and
the most recently started task within the group (least progress lost).
"""

from __future__ import annotations

import logging
import os
import time
from dataclasses import dataclass, field
from typing import Callable, List, Optional, Tuple

logger = logging.getLogger(__name__)

_CGROUP_V2 = "/sys/fs/cgroup"
_PROC_MEMINFO = "/proc/meminfo"


class MemoryMonitor:
    """Reads used/total memory from cgroup v2 limits when the process runs
    inside a limited cgroup, else from /proc/meminfo (reference:
    memory_monitor.cc GetMemoryBytes with the same cgroup-first order).

    ``usage_fn`` injects a fake reading for tests (reference: the fake
    memory monitors under src/mock)."""

    def __init__(
        self,
        usage_threshold: float = 0.95,
        min_memory_free_bytes: int = -1,
        usage_fn: Optional[Callable[[], Tuple[int, int]]] = None,
    ):
        self.usage_threshold = usage_threshold
        self.min_memory_free_bytes = min_memory_free_bytes
        self._usage_fn = usage_fn or self.system_memory

    @staticmethod
    def _cgroup_memory() -> Optional[Tuple[int, int]]:
        cur, maxf = (
            os.path.join(_CGROUP_V2, "memory.current"),
            os.path.join(_CGROUP_V2, "memory.max"),
        )
        try:
            with open(maxf) as f:
                raw = f.read().strip()
            if raw == "max":  # unlimited cgroup: fall through to meminfo
                return None
            total = int(raw)
            with open(cur) as f:
                used = int(f.read().strip())
            # memory.current counts reclaimable page cache; subtract the
            # inactive file cache so file-heavy workloads (e.g. the spill
            # path) don't read as pressure (reference: memory_monitor.cc
            # subtracts inactive_file for exactly this reason)
            try:
                with open(os.path.join(_CGROUP_V2, "memory.stat")) as f:
                    for line in f:
                        if line.startswith("inactive_file "):
                            used = max(used - int(line.split()[1]), 0)
                            break
            except (OSError, ValueError):
                pass
            return used, total
        except (OSError, ValueError):
            return None

    @staticmethod
    def _meminfo_memory() -> Tuple[int, int]:
        total = available = 0
        with open(_PROC_MEMINFO) as f:
            for line in f:
                if line.startswith("MemTotal:"):
                    total = int(line.split()[1]) * 1024
                elif line.startswith("MemAvailable:"):
                    available = int(line.split()[1]) * 1024
        return total - available, total

    @classmethod
    def system_memory(cls) -> Tuple[int, int]:
        """(used_bytes, total_bytes), cgroup-limited when applicable."""
        return cls._cgroup_memory() or cls._meminfo_memory()

    def usage(self) -> Tuple[int, int]:
        return self._usage_fn()

    def is_over_threshold(self) -> bool:
        used, total = self.usage()
        if total <= 0:
            return False
        threshold_bytes = total * self.usage_threshold
        if self.min_memory_free_bytes >= 0:
            # reference: min_memory_free_bytes overrides the fraction when
            # it implies an earlier trigger on huge-memory hosts
            threshold_bytes = min(
                threshold_bytes, total - self.min_memory_free_bytes
            )
        return used > threshold_bytes


@dataclass
class KillCandidate:
    """One leased worker the policy may choose to kill."""

    lease_id: object
    worker_id: object
    pid: int
    owner_id: object  # submitting worker (task owner)
    retriable: bool
    started_at: float = field(default_factory=time.time)


class GroupByOwnerWorkerKillingPolicy:
    """reference: GroupByOwnerIdWorkerKillingPolicy
    (worker_killing_policy_group_by_owner.h:87). Selection order:

    1. retriable tasks before non-retriable (a retried task re-runs; a
       non-retriable one surfaces an error to the user),
    2. within the same retriability, the task whose owner has the MOST
       running tasks on this node (a fan-out loses 1/N of its work),
    3. within the group, the last-started task (least progress lost).
    """

    def select(self, candidates: List[KillCandidate]) -> Optional[KillCandidate]:
        if not candidates:
            return None
        group_sizes: dict = {}
        for c in candidates:
            key = (c.retriable, c.owner_id)
            group_sizes[key] = group_sizes.get(key, 0) + 1
        return max(
            candidates,
            key=lambda c: (
                c.retriable,
                group_sizes[(c.retriable, c.owner_id)],
                c.started_at,
            ),
        )


class RetriableLIFOWorkerKillingPolicy:
    """reference: the default RetriableLIFOWorkerKillingPolicy
    (worker_killing_policy.h): retriable first, newest first."""

    def select(self, candidates: List[KillCandidate]) -> Optional[KillCandidate]:
        if not candidates:
            return None
        return max(candidates, key=lambda c: (c.retriable, c.started_at))
