"""Node-local resource accounting.

Role-equivalent of the reference's resource model (common/scheduling/
resource_set.h, resource_instance_set.h, fixed_point.h): vector resources with
fixed-point arithmetic, per-instance granularity for accelerator chips, label
selectors, and placement-group bundle sub-pools.

TPU-first design: ``TPU`` is a countable resource whose *instances* are chip
indices on the host; allocations return concrete chip ids so the worker can
set chip visibility (equivalent of TPU_VISIBLE_CHIPS handling in the
reference's TPUAcceleratorManager, _private/accelerators/tpu.py:36).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..._internal.ids import PlacementGroupID

# fixed-point: resource quantities are integers in units of 1/10000
# (reference: fixed_point.h)
_SCALE = 10_000


def _fp(v: float) -> int:
    return int(round(v * _SCALE))


def _unfp(v: int) -> float:
    return v / _SCALE


# resources whose allocations map to concrete device instances
INSTANCE_RESOURCES = ("TPU", "GPU")


@dataclass
class Allocation:
    resources: Dict[str, int]  # fixed-point amounts
    instance_ids: Dict[str, List[int]] = field(default_factory=dict)
    bundle: Optional[Tuple[PlacementGroupID, int]] = None


class ResourcePool:
    """One pool of vector resources with instance tracking."""

    def __init__(self, totals: Dict[str, float]):
        self.total: Dict[str, int] = {k: _fp(v) for k, v in totals.items()}
        self.available: Dict[str, int] = dict(self.total)
        # instance resources: free chip indices
        self._free_instances: Dict[str, List[int]] = {
            k: list(range(int(v)))
            for k, v in totals.items()
            if k in INSTANCE_RESOURCES and float(v).is_integer()
        }

    def feasible(self, demand: Dict[str, float]) -> bool:
        return all(self.total.get(k, 0) >= _fp(v) for k, v in demand.items())

    def can_allocate(self, demand: Dict[str, float]) -> bool:
        return all(self.available.get(k, 0) >= _fp(v) for k, v in demand.items())

    def allocate(self, demand: Dict[str, float]) -> Optional[Allocation]:
        if not self.can_allocate(demand):
            return None
        fp_demand = {k: _fp(v) for k, v in demand.items()}
        alloc = Allocation(resources=fp_demand)
        for k, amount in fp_demand.items():
            self.available[k] -= amount
            free = self._free_instances.get(k)
            if free is not None and amount % _SCALE == 0:
                n = amount // _SCALE
                alloc.instance_ids[k] = free[:n]
                del free[:n]
        return alloc

    def release(self, alloc: Allocation):
        for k, amount in alloc.resources.items():
            self.available[k] = min(
                self.available.get(k, 0) + amount, self.total.get(k, amount)
            )
        for k, ids in alloc.instance_ids.items():
            free = self._free_instances.get(k)
            if free is not None:
                free.extend(ids)
                free.sort()

    def available_float(self) -> Dict[str, float]:
        return {k: _unfp(v) for k, v in self.available.items()}

    def total_float(self) -> Dict[str, float]:
        return {k: _unfp(v) for k, v in self.total.items()}


class LocalResourceManager:
    """Per-node manager: the main pool plus per-bundle sub-pools reserved by
    placement-group 2-phase commit (reference: LocalResourceManager +
    bundle resource accounting in the raylet)."""

    def __init__(self, totals: Dict[str, float], labels: Dict[str, str]):
        self.pool = ResourcePool(totals)
        self.labels = dict(labels)
        # (pg_id, bundle_index) -> (reservation from main pool, sub-pool)
        self._bundles: Dict[Tuple[PlacementGroupID, int], Tuple[Allocation, ResourcePool]] = {}
        self._committed: set = set()

    # -- plain allocations -------------------------------------------------

    def matches_labels(self, selector: Dict[str, str]) -> bool:
        from ..._internal.protocol import label_match

        return label_match(self.labels, selector)

    def feasible(self, demand: Dict[str, float], selector: Dict[str, str]) -> bool:
        return self.matches_labels(selector) and self.pool.feasible(demand)

    def allocate(
        self,
        demand: Dict[str, float],
        bundle: Optional[Tuple[PlacementGroupID, int]] = None,
    ) -> Optional[Allocation]:
        if bundle is not None:
            entry = self._bundles.get(bundle)
            if entry is None or bundle not in self._committed:
                return None
            alloc = entry[1].allocate(demand)
            if alloc is not None:
                alloc.bundle = bundle
            return alloc
        return self.pool.allocate(demand)

    def release(self, alloc: Allocation):
        if alloc.bundle is not None:
            entry = self._bundles.get(alloc.bundle)
            if entry is not None:
                entry[1].release(alloc)
            return
        self.pool.release(alloc)

    # -- placement group bundles (2-phase) ---------------------------------

    def prepare_bundle(
        self, pg_id: PlacementGroupID, index: int, resources: Dict[str, float]
    ) -> bool:
        key = (pg_id, index)
        if key in self._bundles:
            return True
        reservation = self.pool.allocate(resources)
        if reservation is None:
            return False
        sub = ResourcePool(resources)
        # bundle sub-pool inherits the chip instances reserved from the main pool
        for k, ids in reservation.instance_ids.items():
            sub._free_instances[k] = list(ids)
        self._bundles[key] = (reservation, sub)
        return True

    def commit_bundle(self, pg_id: PlacementGroupID, index: int) -> bool:
        key = (pg_id, index)
        if key not in self._bundles:
            return False
        self._committed.add(key)
        return True

    def return_bundle(self, pg_id: PlacementGroupID, index: int):
        key = (pg_id, index)
        entry = self._bundles.pop(key, None)
        self._committed.discard(key)
        if entry is not None:
            self.pool.release(entry[0])

    def has_bundle(self, pg_id: PlacementGroupID, index: int) -> bool:
        return (pg_id, index) in self._committed

    def bundle_can_allocate(
        self, pg_id: PlacementGroupID, index: int, demand: Dict[str, float]
    ) -> bool:
        entry = self._bundles.get((pg_id, index))
        return entry is not None and entry[1].can_allocate(demand)

    # -- views -------------------------------------------------------------

    def available_float(self) -> Dict[str, float]:
        return self.pool.available_float()

    def total_float(self) -> Dict[str, float]:
        return self.pool.total_float()
