"""Worker pool: spawning and leasing worker processes.

Role-equivalent of the reference's WorkerPool (src/ray/raylet/worker_pool.h:276):
the raylet spawns language workers as subprocesses, workers dial back and
register, idle workers are popped to satisfy leases and pushed back on lease
return. Idle workers above the prestart floor are reaped after a timeout.

Worker stdout/stderr is captured raylet-side (reference: the per-node log
monitor, _private/log_monitor.py): each worker's output is pumped by a reader
thread into a per-worker file under the session log dir and, batched, into a
``log_sink`` callable that the raylet wires to the GCS "logs" pubsub channel
so drivers can echo worker output (ray.init(log_to_driver=True) semantics).
"""

from __future__ import annotations

import asyncio
import logging
import os
import subprocess
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from ..._internal.ids import NodeID, WorkerID

logger = logging.getLogger(__name__)


@dataclass
class WorkerHandle:
    worker_id: WorkerID
    address: tuple  # (host, port) of the worker's RPC server
    pid: int
    proc: Optional[subprocess.Popen] = None
    idle_since: float = field(default_factory=time.time)
    # env fingerprint for dedicated workers (runtime envs); "" = default
    env_key: str = ""


class WorkerPool:
    def __init__(
        self,
        node_id: NodeID,
        raylet_port_getter,
        gcs_address,
        session_id: str,
        max_workers: int,
        config_json: str,
        auth_token: str = "",
        log_dir: Optional[str] = None,
        log_sink: Optional[Callable[[dict], None]] = None,
    ):
        self._node_id = node_id
        self._raylet_port_getter = raylet_port_getter
        self._gcs_address = gcs_address
        self._session_id = session_id
        self._max_workers = max_workers
        self._config_json = config_json
        self._auth_token = auth_token
        self._log_dir = log_dir
        self._log_sink = log_sink
        self._idle: List[WorkerHandle] = []
        self._registered: Dict[WorkerID, WorkerHandle] = {}
        self._spawned_procs: Dict[int, subprocess.Popen] = {}  # pid -> proc
        # spawned but not yet registered: pid -> env_key (bounds spawning so
        # a lease-retry loop cannot stampede-fork workers; reference:
        # worker startup rate limiting in WorkerPool)
        self._pending_spawns: Dict[int, str] = {}
        # lease waiters keyed by runtime-env fingerprint (reference:
        # WorkerPool pops workers matching the lease's runtime env)
        self._waiters: Dict[str, List[asyncio.Future]] = {}
        self._stopped = False

    def _prune_dead_spawns(self):
        for pid in list(self._pending_spawns):
            proc = self._spawned_procs.get(pid)
            if proc is not None and proc.poll() is not None:
                del self._pending_spawns[pid]
                self._spawned_procs.pop(pid, None)

    def _num_starting(self, env_key: str) -> int:
        return sum(1 for k in self._pending_spawns.values() if k == env_key)

    @property
    def num_total(self) -> int:
        return len(self._registered) + len(self._pending_spawns)

    def _spawn(self, env_overrides: Optional[dict] = None,
               runtime_env: Optional[dict] = None, env_key: str = ""):
        """Start one worker subprocess; it will dial back and register."""
        env = dict(os.environ)
        env["RAY_TPU_NODE_ID"] = self._node_id.hex()
        if self._auth_token:
            # Config.__post_init__ picks this up (cluster_auth_token field)
            env["RAY_TPU_CLUSTER_AUTH_TOKEN"] = self._auth_token
        env.update(env_overrides or {})
        if runtime_env:
            import json as _json

            env["RAY_TPU_RUNTIME_ENV"] = _json.dumps(runtime_env)
            env["RAY_TPU_ENV_KEY"] = env_key
            # env_vars also applied at process start so they are visible to
            # module-level imports (reference: dedicated-worker env vars)
            env.update(runtime_env.get("env_vars") or {})
        repo_root = os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
        )
        # Ship the raylet process's import paths to workers so functions
        # pickled by module reference (driver-side modules, test files)
        # resolve in the worker (reference role: JobConfig code search path /
        # runtime_env py_modules).
        extra_paths = [p for p in sys.path if p and os.path.isdir(p)]
        env["PYTHONPATH"] = os.pathsep.join(
            p
            for p in [repo_root, *extra_paths, env.get("PYTHONPATH", "")]
            if p  # an empty entry would put the cwd on worker sys.path
        )
        cmd = [
            sys.executable,
            "-m",
            "ray_tpu.runtime.worker.worker_main",
            "--raylet-port", str(self._raylet_port_getter()),
            "--gcs-host", self._gcs_address[0],
            "--gcs-port", str(self._gcs_address[1]),
            "--node-id", self._node_id.hex(),
            "--session", self._session_id,
            "--config", self._config_json,
        ]
        if self._log_dir is not None:
            # capture into the session log dir + publish to the driver.
            # Unbuffered: piped stdout would otherwise block-buffer prints
            # and delay the driver echo by kilobytes.
            env["PYTHONUNBUFFERED"] = "1"
            proc = subprocess.Popen(
                cmd, env=env,
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            )
            threading.Thread(
                target=self._pump_logs, args=(proc, bool(env.get("RAY_TPU_WORKER_QUIET"))),
                name=f"log-pump-{proc.pid}", daemon=True,
            ).start()
        else:
            proc = subprocess.Popen(
                cmd,
                env=env,
                stdout=subprocess.DEVNULL if env.get("RAY_TPU_WORKER_QUIET") else None,
                stderr=None,
            )
        self._spawned_procs[proc.pid] = proc
        self._pending_spawns[proc.pid] = env_key
        logger.debug("spawned worker pid=%s", proc.pid)
        return proc

    def _pump_logs(self, proc: subprocess.Popen, quiet: bool):
        """Reader thread: tee one worker's merged stdout/stderr into its
        session log file and batch lines to the log sink (→ GCS "logs"
        channel). select() with a short timeout bounds both batch size and
        batch age, so a lone final line still reaches the driver promptly
        while chatty workers don't hammer the control plane per line."""
        import select

        path = os.path.join(self._log_dir, f"worker-{proc.pid}.log")
        fd = proc.stdout.fileno()
        batch: List[str] = []
        partial = b""
        last_flush = time.monotonic()

        def flush():
            nonlocal batch, last_flush
            if batch and self._log_sink is not None and not quiet:
                try:
                    self._log_sink({"pid": proc.pid, "lines": batch})
                except Exception:
                    pass  # sink failures must not kill the pump
            batch = []
            last_flush = time.monotonic()

        try:
            with open(path, "ab", buffering=0) as f:
                while True:
                    readable, _, _ = select.select([fd], [], [], 0.2)
                    if not readable:
                        flush()
                        continue
                    chunk = os.read(fd, 65536)
                    if not chunk:
                        break
                    f.write(chunk)
                    lines = (partial + chunk).split(b"\n")
                    partial = lines.pop()
                    batch.extend(
                        ln.decode("utf-8", errors="replace") for ln in lines
                    )
                    # size OR age: steady sub-0.2s output would otherwise
                    # keep select() readable and starve the idle flush
                    if len(batch) >= 200 or time.monotonic() - last_flush > 0.5:
                        flush()
                if partial:
                    f.write(b"\n")
                    batch.append(partial.decode("utf-8", errors="replace"))
        except (OSError, ValueError):
            pass
        finally:
            flush()
            try:
                proc.stdout.close()
            except Exception:
                pass

    def on_worker_registered(self, worker_id: WorkerID, address: tuple, pid: int,
                             env_key: str = ""):
        handle = WorkerHandle(worker_id, address, pid, env_key=env_key)
        self._registered[worker_id] = handle
        self._pending_spawns.pop(pid, None)
        # hand directly to a matching waiter if any, else park as idle
        for fut in self._waiters.get(env_key, []):
            if not fut.done():
                self._waiters[env_key].remove(fut)
                fut.set_result(handle)
                return
        self._idle.append(handle)

    def on_worker_dead(self, worker_id: WorkerID) -> Optional[WorkerHandle]:
        handle = self._registered.pop(worker_id, None)
        self._idle = [w for w in self._idle if w.worker_id != worker_id]
        return handle

    async def pop(self, timeout: float = 60.0, env_key: str = "",
                  runtime_env: Optional[dict] = None) -> Optional[WorkerHandle]:
        """Pop an idle worker whose runtime env matches, spawning a
        dedicated one if needed (reference: WorkerPool::PopWorker matching
        by runtime-env hash)."""
        for i, handle in enumerate(self._idle):
            if handle.env_key == env_key:
                return self._idle.pop(i)
        self._prune_dead_spawns()
        if self.num_total >= self._max_workers and self._idle:
            # pool full of other-env workers: evict the longest-idle one to
            # make room for the dedicated worker
            victim = min(self._idle, key=lambda h: h.idle_since)
            self._idle.remove(victim)
            self._kill(victim)
        # Spawn only when in-flight startups cannot cover queued demand —
        # a retrying lease must not fork a fresh worker per retry.
        pending_demand = len(self._waiters.get(env_key, [])) + 1
        if (
            self.num_total < self._max_workers
            and self._num_starting(env_key) < pending_demand
        ):
            self._spawn(runtime_env=runtime_env, env_key=env_key)
        fut: asyncio.Future = asyncio.get_event_loop().create_future()
        self._waiters.setdefault(env_key, []).append(fut)
        try:
            return await asyncio.wait_for(fut, timeout)
        except asyncio.TimeoutError:
            if fut in self._waiters.get(env_key, []):
                self._waiters[env_key].remove(fut)
            return None

    def push(self, handle: WorkerHandle):
        """Return a worker to the idle pool after its lease ends."""
        if handle.worker_id in self._registered:
            handle.idle_since = time.time()
            for fut in self._waiters.get(handle.env_key, []):
                if not fut.done():
                    self._waiters[handle.env_key].remove(fut)
                    fut.set_result(handle)
                    return
            self._idle.append(handle)

    def prestart(self, count: int):
        for _ in range(count):
            if self.num_total < self._max_workers:
                self._spawn()

    def reap_idle(self, keep: int, idle_kill_s: float):
        """Kill workers idle beyond the timeout, keeping a floor."""
        now = time.time()
        survivors = []
        for handle in self._idle:
            if (
                len(self._idle) - (len(self._idle) - len(survivors) - 1) > keep
                and now - handle.idle_since > idle_kill_s
            ):
                self._kill(handle)
            else:
                survivors.append(handle)
        self._idle = survivors

    def _kill(self, handle: WorkerHandle):
        self._registered.pop(handle.worker_id, None)
        try:
            os.kill(handle.pid, 15)
        except ProcessLookupError:
            pass

    def shutdown(self):
        self._stopped = True
        for handle in list(self._registered.values()):
            self._kill(handle)
        # also kill spawned-but-not-yet-registered workers
        for pid, proc in self._spawned_procs.items():
            if proc.poll() is None:
                try:
                    proc.terminate()
                except ProcessLookupError:
                    pass
        self._registered.clear()
        self._idle.clear()
        self._spawned_procs.clear()
