"""Raylet: the per-node daemon.

Role-equivalent of the reference's NodeManager (src/ray/raylet/node_manager.h:133)
plus the embedded object store and the two-level scheduler:

- worker-lease protocol: owners request a leased worker for a task; the raylet
  grants locally, queues, or replies with a spillback target chosen from its
  cluster resource view (reference: ClusterLeaseManager/LocalLeaseManager +
  hybrid_scheduling_policy.h)
- placement-group bundle prepare/commit/return (2-phase commit participant,
  reference: HandlePrepareBundleResources node_manager.h:584)
- node-local shared-memory object store service + node-to-node chunked object
  pulls (reference: ObjectManager/PullManager, object_manager.h:128)
- worker pool management and worker-death detection via connection loss
  (reference: HandleClientConnectionError node_manager.h:332)
- periodic resource-view reports to the GCS (role of RaySyncer)
"""

from __future__ import annotations

import asyncio
import itertools
import os
import logging
import time
from collections import defaultdict, deque
from typing import Dict, List, Optional, Tuple

from ..._internal.config import Config
from ..._internal.event_loop import BackgroundTasks, PeriodicRunner
from ..._internal.ids import NodeID, ObjectID, PlacementGroupID, UniqueID, WorkerID
from ..._internal.protocol import (
    label_match,
    NodeInfo,
    PlacementGroupSchedulingStrategy,
    NodeAffinitySchedulingStrategy,
    SpreadSchedulingStrategy,
    TaskSpec,
)
from ..._internal.rpc import ClientPool, RpcServer, retry_call
from ...exceptions import NodeFencedError, ObjectStoreFullError
from ...util import chaosnet
from ...util.events import NODE_FENCED, NODE_UNFENCED, record_event
from ..gcs.pubsub import SubscriberClient
from ..object_store import spill_storage
from ..object_store.native_store import create_object_store
from .memory_monitor import (
    GroupByOwnerWorkerKillingPolicy,
    KillCandidate,
    MemoryMonitor,
    RetriableLIFOWorkerKillingPolicy,
)
from .resources import Allocation, LocalResourceManager
from .worker_pool import WorkerHandle, WorkerPool

logger = logging.getLogger(__name__)

# Per-location connect bound for object pulls: long enough for a loaded
# peer to accept a TCP connection, short enough that a dead holder does
# not stall the get (the caller falls through to the next holder or to
# lineage reconstruction).
_PULL_CONNECT_PROBE_S = 2.0


class Lease:
    __slots__ = (
        "lease_id", "worker", "allocation", "spec", "granted_at",
        "reusable", "renewed_at",
    )

    def __init__(self, lease_id, worker: WorkerHandle, allocation: Allocation,
                 spec, reusable: bool = False):
        self.lease_id = lease_id
        self.worker = worker
        self.allocation = allocation
        self.spec = spec
        self.granted_at = time.time()
        # owner may cache this lease and reuse it across tasks; the raylet
        # can recall it with a revoke_lease RPC to the owner (TTL accounting
        # below; reference: worker lease reuse + lease reclamation)
        self.reusable = reusable
        self.renewed_at = self.granted_at


class Raylet:
    def __init__(
        self,
        config: Config,
        gcs_address: Tuple[str, int],
        resources: Dict[str, float],
        labels: Dict[str, str],
        session_id: str,
        is_head: bool = False,
        object_store_memory: Optional[int] = None,
    ):
        self.config = config
        self.node_id = NodeID.from_random()
        self.gcs_address = gcs_address
        self.session_id = session_id
        self.is_head = is_head
        self.server = RpcServer(f"raylet-{self.node_id.hex()[:6]}")
        # chaos_src tags every outgoing call with this node's identity so
        # directional partition rules (src=<node-hex>) can match
        self.client_pool = ClientPool(
            "raylet-out", chaos_src=self.node_id.hex()
        )
        self.resources = LocalResourceManager(resources, labels)
        self.store = create_object_store(
            object_store_memory or config.object_store_memory,
            f"{session_id}_{self.node_id.hex()[:6]}",
        )
        self.worker_pool: Optional[WorkerPool] = None
        self.address: Optional[Tuple[str, int]] = None

        self._leases: Dict[UniqueID, Lease] = {}
        # spilled primary copies: object id -> file path (reference: N14)
        self._spilled: Dict[ObjectID, str] = {}
        # owner-freed objects still pinned by zero-copy readers: freed for
        # real when the last reader releases (see handle_free_objects)
        self._deferred_frees: set = set()
        # unmet demands for the autoscaler: task_id -> (resources, selector, ts)
        self._infeasible_demands: Dict[TaskID, tuple] = {}
        self._restore_locks: Dict[ObjectID, asyncio.Lock] = {}
        # background spill deletions: the loop keeps only weak task refs,
        # so untracked fire-and-forget tasks can be GC'd mid-flight
        self._bg = BackgroundTasks()
        self._restore_lock_holds: Dict[ObjectID, int] = {}
        self._lease_seq = itertools.count()
        # scheduling-class FIFO queues of pending lease requests
        # (reference: scheduling classes, scheduling_class_util.h)
        self._queues: Dict[tuple, deque] = defaultdict(deque)
        self._dispatch_wakeup = asyncio.Event()
        self._dispatch_task: Optional[asyncio.Task] = None
        # cluster view for spillback: node_id -> NodeInfo / availability
        self._cluster_nodes: Dict[NodeID, NodeInfo] = {}
        self._cluster_available: Dict[NodeID, Dict[str, float]] = {}
        self._subscriber: Optional[SubscriberClient] = None
        self._runner: Optional[PeriodicRunner] = None
        # versioned delta sync state (reference: ray_syncer.h:89)
        self._sync_version = 0
        self._acked_avail: Optional[Dict[str, float]] = None
        self._acked_demands: Optional[list] = None
        self._needs_full_sync = True
        self._stopped = False
        # OOM defense (reference: MemoryMonitor + WorkerKillingPolicy)
        self.memory_monitor = MemoryMonitor(config.memory_usage_threshold)
        self._kill_policy = (
            RetriableLIFOWorkerKillingPolicy()
            if config.worker_killing_policy == "retriable_lifo"
            else GroupByOwnerWorkerKillingPolicy()
        )
        self._oom_kills = 0
        self._last_oom_kill_ts = 0.0
        # native transfer plane counters (observability + tests)
        self._native_pulls = 0
        # chunk-serve accounting for the weight-plane broadcast proofs:
        # object -> number of complete python-path transfers served FROM this
        # node (counted at offset 0), plus total payload bytes out. The O(1)
        # publisher-upload test reads these via the transfer_stats RPC.
        self._fetch_serves: Dict[ObjectID, int] = {}
        self._fetch_bytes_out = 0
        self._transfer_port: Optional[int] = None
        # peer address -> (port or None, probe-expiry timestamp)
        self._peer_transfer_ports: Dict[tuple, tuple] = {}
        self._pull_locks: Dict[ObjectID, asyncio.Lock] = {}
        self._pull_lock_holds: Dict[ObjectID, int] = {}
        # worker pid -> hex job id of its most recent lease (log attribution)
        self._worker_job: Dict[int, str] = {}
        # lease ids with a revoke_lease RPC in flight to their owner
        self._revoking: set = set()
        # split-brain fencing: set when GCS contact is lost past
        # fence_after_s — new leases are refused (NodeFencedError) and
        # resident workers are told to fence; cleared on the next
        # successful report
        self._fenced = False
        self._last_gcs_ok = time.time()

    # -- lifecycle ---------------------------------------------------------

    async def start(self, host: str = "127.0.0.1", port: int = 0):
        self.server.register_service(self)
        self.server.on_connection_lost(self._on_connection_lost)
        bound = await self.server.start(host, port)
        self.address = (host, bound)
        self._loop = asyncio.get_event_loop()
        # session log dir (reference: per-session /tmp/ray/session_*/logs)
        import tempfile

        self.log_dir = os.path.join(
            tempfile.gettempdir(), "ray_tpu",
            f"session_{self.session_id}", "logs",
        )
        os.makedirs(self.log_dir, exist_ok=True)
        # native transfer plane: serve this arena over TCP so peers pull
        # bulk bytes via the C++ path instead of chunked python RPC
        if hasattr(self.store, "transfer_serve"):
            self._transfer_port = self.store.transfer_serve(
                self.config.cluster_auth_token, host=host
            )
        # the auth token ships to workers via env, NOT the --config argv JSON
        # (argv is world-readable through /proc/<pid>/cmdline). The key is
        # OMITTED — an empty value would overwrite the env-provided token in
        # the worker's Config.from_json.
        import json as _json

        cfg_dict = _json.loads(self.config.to_json())
        cfg_dict.pop("cluster_auth_token", None)
        self.worker_pool = WorkerPool(
            self.node_id,
            lambda: self.address[1],
            self.gcs_address,
            self.session_id,
            self.config.max_workers_per_node,
            _json.dumps(cfg_dict),
            auth_token=self.config.cluster_auth_token,
            log_dir=self.log_dir,
            log_sink=self._worker_log_sink,
        )
        gcs = self.client_pool.get(*self.gcs_address)
        info = self._node_info()
        await retry_call(gcs, "register_node", info, attempts=3, timeout=10.0)
        self._last_gcs_ok = time.time()
        self._cluster_nodes[self.node_id] = info
        # cluster view subscription
        self._subscriber = SubscriberClient(
            self.client_pool.get(*self.gcs_address), f"raylet-{self.node_id.hex()}"
        )
        await self._subscriber.subscribe("node", self._on_node_event)
        await self._subscriber.subscribe("resource_view", self._on_resource_view)
        # periodic resource reports double as liveness heartbeats
        self._runner = PeriodicRunner(asyncio.get_event_loop())
        self._runner.run_every(
            max(self.config.health_check_period_s / 2, 0.1), self._report_resources
        )
        if self.config.chaos_poll_period_s > 0:
            self._runner.run_every(
                self.config.chaos_poll_period_s, self._poll_chaos
            )
        self._runner.run_every(5.0, self._reap_idle_workers)
        if self.config.lease_ttl_s > 0:
            self._runner.run_every(
                max(self.config.lease_ttl_s / 2, 1.0), self._check_lease_ttls
            )
        if self.config.memory_monitor_refresh_s > 0:
            self._runner.run_every(
                self.config.memory_monitor_refresh_s, self._check_memory
            )
        self._dispatch_task = asyncio.ensure_future(self._dispatch_loop())
        if self.config.prestart_workers:
            self.worker_pool.prestart(self.config.prestart_workers)
        logger.info("raylet %s on %s", self.node_id, self.address)
        return self.address

    async def stop(self):
        self._stopped = True
        if self._runner:
            self._runner.stop()
        if self._subscriber:
            await self._subscriber.close()
        if self._dispatch_task:
            self._dispatch_task.cancel()
        if self.worker_pool:
            self.worker_pool.shutdown()
        self.store.shutdown()
        await self.server.stop()
        await self.client_pool.close_all()

    async def _report_resources(self):
        """Versioned delta report (reference: RaySyncer ray_syncer.h:89):
        steady state sends an empty heartbeat against the acked version;
        changes send only the touched keys; registration/resync sends a full
        snapshot. The GCS acks the applied version — O(changes), not
        O(nodes x report rate), on the wire and in GCS work."""
        avail = self.resources.available_float()
        demands = self._pending_demands()
        gcs = self.client_pool.get(*self.gcs_address)
        if self._needs_full_sync or self._acked_avail is None:
            self._sync_version += 1
            payload = dict(
                version=self._sync_version, base_version=None,
                changed=avail, demands=demands,
            )
        else:
            changed = {
                k: v for k, v in avail.items()
                if self._acked_avail.get(k) != v
            }
            removed = [k for k in self._acked_avail if k not in avail]
            demands_changed = demands != self._acked_demands
            base = self._sync_version
            if changed or removed or demands_changed:
                self._sync_version += 1
            payload = dict(
                version=self._sync_version, base_version=base,
                changed=changed or None, removed=removed or None,
                demands=demands if demands_changed else None,
            )
        try:
            reply = await gcs.call(
                "report_resources_delta", self.node_id, timeout=5.0, **payload
            )
        except Exception:
            since_ok = time.time() - self._last_gcs_ok
            if (
                not self._fenced
                and self.config.fence_after_s > 0
                and since_ok > self.config.fence_after_s
            ):
                self._set_fenced(
                    True,
                    f"no successful GCS report for {since_ok:.1f}s",
                )
            return
        self._last_gcs_ok = time.time()
        if self._fenced:
            self._set_fenced(False, "")
        if reply == "unknown_node":
            # the GCS restarted and lost the node table: re-register,
            # reporting which workers are still alive so restored actor
            # records can be reconciled (reference: raylet reconnect on
            # NotifyGCSRestart, node_manager.proto:426)
            self._needs_full_sync = True
            await self._reregister_with_gcs()
            return
        if isinstance(reply, dict) and reply.get("resync"):
            self._needs_full_sync = True
            return
        self._needs_full_sync = False
        self._acked_avail = avail
        self._acked_demands = demands

    def _set_fenced(self, fenced: bool, reason: str):
        """Flip the split-brain fence. Fenced raylets refuse new leases and
        tell their resident workers to fence (replica admission and
        collective ticks read the worker-local flag); the GCS may already be
        restarting this node's actors elsewhere, so running new work here
        risks two live incarnations."""
        self._fenced = fenced
        if fenced:
            logger.warning("node %s FENCED: %s", self.node_id, reason)
            record_event(
                NODE_FENCED, node=self.node_id.hex(), reason=reason
            )
            try:
                from ...util.metrics import record_node_fenced

                record_node_fenced(self.node_id.hex())
            except Exception:
                pass
        else:
            logger.warning(
                "node %s unfenced: GCS contact restored", self.node_id
            )
            record_event(NODE_UNFENCED, node=self.node_id.hex())
        self._bg.spawn(self._notify_workers_fenced(fenced, reason))

    async def _notify_workers_fenced(self, fenced: bool, reason: str):
        if self.worker_pool is None:
            return
        for handle in list(self.worker_pool._registered.values()):
            try:
                worker = self.client_pool.get(*handle.address)
                await worker.call_oneway(
                    "set_fenced", fenced, self.node_id.hex(), reason
                )
            except Exception:
                pass  # best-effort; the worker may be mid-death

    async def _poll_chaos(self):
        """Pick up the cluster-wide chaos-mesh spec from the GCS KV. The
        fetch rides the chaos-EXEMPT chaos_fetch RPC so clearing a partition
        propagates through the partition it clears."""
        await chaosnet.poll_once(self.client_pool.get(*self.gcs_address))

    def _node_info(self) -> NodeInfo:
        return NodeInfo(
            node_id=self.node_id,
            address=self.address,
            object_store_address=self.store.session_id,
            resources_total=self.resources.total_float(),
            labels=dict(self.resources.labels),
            is_head=self.is_head,
        )

    async def _reregister_with_gcs(self):
        logger.warning(
            "GCS does not know node %s (restart?); re-registering", self.node_id
        )
        gcs = self.client_pool.get(*self.gcs_address)
        live_workers = (
            list(self.worker_pool._registered.keys())
            if self.worker_pool is not None
            else []
        )
        # which live workers host which actors: the restarted GCS reconciles
        # these against its restored directory and names the stale ones —
        # e.g. this node missed the re-registration grace window and its
        # actors were already restarted elsewhere; the old incarnations must
        # not keep running side effects
        actor_workers = {
            lease.worker.worker_id: lease.spec.actor_id
            for lease in self._leases.values()
            if getattr(lease.spec, "actor_id", None) is not None
        }
        try:
            reply = await retry_call(
                gcs, "register_node", self._node_info(), live_workers,
                actor_workers, attempts=3, timeout=10.0,
            )
        except Exception:
            logger.exception("re-registration with GCS failed; will retry")
            return
        stale = reply.get("stale_workers") if isinstance(reply, dict) else None
        for worker_id in stale or []:
            handle = (
                self.worker_pool._registered.get(worker_id)
                if self.worker_pool is not None
                else None
            )
            if handle is not None:
                logger.warning(
                    "killing stale actor worker %s (pid %s): its actor moved "
                    "on while this node was out of contact", worker_id,
                    handle.pid,
                )
                try:
                    os.kill(handle.pid, 9)
                except ProcessLookupError:
                    pass

    def _pending_demands(self) -> List[dict]:
        """Aggregate queued lease requests into resource-demand buckets for
        the autoscaler (reference: SchedulerResourceReporter feeding
        GcsAutoscalerStateManager's cluster resource state)."""
        buckets: Dict[tuple, dict] = {}

        def add(resources, selector):
            key = (
                tuple(sorted(resources.items())),
                tuple(sorted((selector or {}).items())),
            )
            entry = buckets.get(key)
            if entry is None:
                buckets[key] = entry = {
                    "resources": dict(resources),
                    "label_selector": dict(selector or {}),
                    "count": 0,
                }
            entry["count"] += 1

        for queue in self._queues.values():
            for spec, fut, _reusable in queue:
                if not fut.done():
                    add(spec.resources, spec.label_selector)
        now = time.time()
        for task_id, (resources, selector, ts) in list(
            self._infeasible_demands.items()
        ):
            if now - ts > 5.0:  # owner stopped retrying (done or gone)
                del self._infeasible_demands[task_id]
                continue
            add(resources, selector)
        return list(buckets.values())

    def _reap_idle_workers(self):
        self.worker_pool.reap_idle(
            keep=self.config.prestart_workers,
            idle_kill_s=self.config.idle_worker_kill_s,
        )

    # -- cluster view ------------------------------------------------------

    async def _check_memory(self):
        """OOM defense tick (reference: NodeManager memory-monitor callback
        + WorkerKillingPolicy): above the usage threshold, kill the leased
        worker the policy picks; the owner sees a worker crash and retries
        if the task is retriable."""
        if not self._leases or not self.memory_monitor.is_over_threshold():
            return
        # cooldown: reclaim after SIGKILL lags behind the next tick, and
        # back-to-back kills would drain the node before pressure clears
        # (reference: kill-in-progress gating in the memory-monitor callback)
        now = time.time()
        if now - self._last_oom_kill_ts < self.config.oom_kill_cooldown_s:
            return
        candidates = []
        for lease in self._leases.values():
            spec = lease.spec
            retriable = (
                spec.max_restarts != 0
                if spec.actor_id is not None
                else spec.max_retries > 0
            )
            candidates.append(
                KillCandidate(
                    lease_id=lease.lease_id,
                    worker_id=lease.worker.worker_id,
                    pid=lease.worker.pid,
                    owner_id=spec.owner_worker_id,
                    retriable=retriable,
                    started_at=lease.granted_at,
                )
            )
        victim = self._kill_policy.select(candidates)
        if victim is None:
            return
        used, total = self.memory_monitor.usage()
        self._oom_kills += 1
        self._last_oom_kill_ts = now
        logger.warning(
            "memory pressure (%.0f/%.0f MB): killing worker %s (pid %s, "
            "retriable=%s) to reclaim memory",
            used / 1e6, total / 1e6, victim.worker_id, victim.pid,
            victim.retriable,
        )
        handle = self.worker_pool.on_worker_dead(victim.worker_id)
        try:
            os.kill(victim.pid, 9)
        except ProcessLookupError:
            pass
        # free the lease now — the kill is deliberate, no need to wait for
        # the connection-loss callback (which becomes a no-op: the handle is
        # already deregistered)
        for lease_id, lease in list(self._leases.items()):
            if lease.worker.worker_id == victim.worker_id:
                self.resources.release(lease.allocation)
                del self._leases[lease_id]
                if lease.reusable:
                    # tell the owner its cached lease is gone so the cache
                    # drops it now instead of on the next failed push
                    try:
                        owner = self.client_pool.get(*lease.spec.owner_address)
                        self._bg.spawn(
                            owner.call_oneway("revoke_lease", lease_id)
                        )
                    except Exception:
                        pass
        self._dispatch_wakeup.set()
        if handle is not None:
            try:
                gcs = self.client_pool.get(*self.gcs_address)
                await gcs.call(
                    "report_worker_death",
                    victim.worker_id,
                    f"killed by memory monitor: node memory {used}/{total} "
                    f"exceeded threshold "
                    f"{self.memory_monitor.usage_threshold:.2f}",
                    timeout=5.0,
                )
            except Exception:
                pass

    def _on_node_event(self, channel, message):
        kind, info = message
        if kind == "alive":
            self._cluster_nodes[info.node_id] = info
        elif kind == "dead":
            self._cluster_nodes.pop(info.node_id, None)
            self._cluster_available.pop(info.node_id, None)
        # "suspect" keeps the node in the view: it may still recover, and
        # evicting it here would orphan its entry forever (no re-"alive"
        # publish follows a cleared suspicion)

    def _on_resource_view(self, channel, message):
        node_id, available = message
        self._cluster_available[node_id] = available
        self._dispatch_wakeup.set()  # infeasible tasks may now be spillable

    # -- worker registration / death --------------------------------------

    async def handle_register_worker(
        self, worker_id: WorkerID, address: Tuple[str, int], pid: int,
        env_key: str = ""
    ):
        self.worker_pool.on_worker_registered(worker_id, address, pid, env_key)
        return {"node_id": self.node_id, "store_session": self.store.session_id}

    async def _on_connection_lost(self, peer_meta):
        worker_id = peer_meta.get("worker_id")
        if worker_id is None:
            return
        handle = self.worker_pool.on_worker_dead(worker_id)
        if handle is None:
            return
        logger.warning("worker %s (pid %s) died", worker_id, handle.pid)
        # free any leases held by the dead worker
        for lease_id, lease in list(self._leases.items()):
            if lease.worker.worker_id == worker_id:
                self.resources.release(lease.allocation)
                del self._leases[lease_id]
                if lease.reusable:
                    # drop the owner's cached copy promptly (it would also
                    # self-heal on the next failed push)
                    try:
                        owner = self.client_pool.get(*lease.spec.owner_address)
                        self._bg.spawn(
                            owner.call_oneway("revoke_lease", lease_id)
                        )
                    except Exception:
                        pass
        self._dispatch_wakeup.set()
        try:
            gcs = self.client_pool.get(*self.gcs_address)
            await gcs.call(
                "report_worker_death", worker_id, "connection lost",
                timeout=5.0,
            )
        except Exception:
            pass

    # -- lease protocol ----------------------------------------------------

    async def handle_request_worker_lease(self, spec: TaskSpec,
                                          reusable: bool = False):
        """Grant a worker locally, queue, or spill to another node.
        ``reusable`` marks the grant as cacheable by the owner (lease reuse);
        the raylet may recall it later via revoke_lease."""
        if self._fenced:
            # split-brain guard: the GCS may be restarting this node's work
            # elsewhere — granting here could produce two live incarnations
            raise NodeFencedError(self.node_id.hex(), "raylet lost GCS contact")
        fut: asyncio.Future = asyncio.get_event_loop().create_future()
        self._queues[spec.scheduling_class()].append((spec, fut, reusable))
        self._dispatch_wakeup.set()
        return await fut

    async def handle_return_worker(self, lease_id, worker_failed: bool = False):
        lease = self._leases.pop(lease_id, None)
        if lease is None:
            return False
        self.resources.release(lease.allocation)
        if not worker_failed:
            self.worker_pool.push(lease.worker)
        self._dispatch_wakeup.set()
        return True

    # -- lease revocation (the raylet side of lease reuse: TTL accounting +
    # recall of owner-cached leases under resource pressure) ---------------

    def _maybe_revoke_idle_lease(self, lease: Optional[Lease] = None):
        """Fire one revoke_lease RPC at the owner of a reusable lease
        (oldest first when unspecified). The owner releases the lease if it
        is idle in its cache — its return_worker then frees the resources
        and wakes dispatch — or answers False (in use), which renews the
        lease's TTL clock."""
        if lease is None:
            candidates = [
                l for l in self._leases.values()
                if l.reusable and l.lease_id not in self._revoking
            ]
            if not candidates:
                return
            lease = min(candidates, key=lambda l: l.renewed_at)
        elif lease.lease_id in self._revoking:
            return
        self._revoking.add(lease.lease_id)
        self._bg.spawn(self._revoke_lease(lease))

    async def _revoke_lease(self, lease: Lease):
        try:
            owner = self.client_pool.get(*lease.spec.owner_address)
            released = await owner.call(
                "revoke_lease", lease.lease_id, timeout=5.0
            )
            if released:
                return  # owner's return_worker does the cleanup
            # in use: the owner is actively reusing it — renew the clock
            live = self._leases.get(lease.lease_id)
            if live is not None:
                live.renewed_at = time.time()
        except Exception:
            # owner unreachable (crashed / shut down): force-reclaim so a
            # dead owner can never pin a worker and its resources forever
            live = self._leases.pop(lease.lease_id, None)
            if live is not None:
                logger.warning(
                    "force-reclaiming lease %s from unreachable owner %s",
                    live.lease_id, live.spec.owner_address,
                )
                self.resources.release(live.allocation)
                self.worker_pool.push(live.worker)
                self._dispatch_wakeup.set()
        finally:
            self._revoking.discard(lease.lease_id)

    async def _check_lease_ttls(self):
        """Periodic TTL backstop: probe reusable leases older than
        lease_ttl_s. Owners actively reusing a lease answer the probe with
        "busy", which renews it; leaked leases (crashed or wedged owners)
        get reclaimed."""
        ttl = self.config.lease_ttl_s
        if ttl <= 0:
            return
        now = time.time()
        for lease in list(self._leases.values()):
            if lease.reusable and now - lease.renewed_at > ttl:
                self._maybe_revoke_idle_lease(lease)

    async def _dispatch_loop(self):
        """Single dispatch loop draining per-class FIFO queues (reference:
        ClusterLeaseManager::ScheduleAndGrantLeases)."""
        while not self._stopped:
            await self._dispatch_wakeup.wait()
            self._dispatch_wakeup.clear()
            progress = True
            while progress:
                progress = False
                for cls, queue in list(self._queues.items()):
                    if not queue:
                        del self._queues[cls]
                        continue
                    spec, fut, reusable = queue[0]
                    if fut.done():
                        queue.popleft()
                        progress = True
                        continue
                    decision = await self._try_dispatch(spec, reusable)
                    if decision is None:
                        continue  # head-of-line waits; other classes proceed
                    queue.popleft()
                    if not fut.done():
                        fut.set_result(decision)
                    progress = True

    async def _try_dispatch(self, spec: TaskSpec,
                            reusable: bool = False) -> Optional[dict]:
        """Returns a reply dict, or None to keep the request queued."""
        strategy = spec.scheduling_strategy
        bundle = None
        if isinstance(strategy, PlacementGroupSchedulingStrategy):
            pg_id = strategy.placement_group_id
            index = strategy.bundle_index
            if index == -1:
                index = self._find_bundle(pg_id, spec.resources)
                if index is None:
                    return {"granted": False, "reason": "no bundle with capacity"}
            if not self.resources.has_bundle(pg_id, index):
                return {"granted": False, "reason": "bundle not on this node"}
            if not self.resources.bundle_can_allocate(pg_id, index, spec.resources):
                return None  # wait for bundle capacity
            bundle = (pg_id, index)
        elif isinstance(strategy, NodeAffinitySchedulingStrategy):
            if strategy.node_id != self.node_id:
                target = self._cluster_nodes.get(strategy.node_id)
                if target is not None:
                    return {"granted": False, "spillback": (target.node_id, target.address)}
                if not strategy.soft:
                    return {"granted": False, "reason": "affinity node not alive"}
        else:
            if not self.resources.feasible(spec.resources, spec.label_selector):
                return self._spillback_or_reject(spec)
            if isinstance(strategy, SpreadSchedulingStrategy):
                target = self._pick_spread_target(spec)
                if target is not None and target[0] != self.node_id:
                    return {"granted": False, "spillback": target}
            if not self.resources.pool.can_allocate(spec.resources):
                # feasible but busy: hybrid policy — spill if a remote node
                # has free capacity now, else queue locally. Before queuing,
                # try to recall an owner-cached idle lease: its resources
                # may be all that stands between this request and a grant.
                target = self._pick_remote_with_capacity(spec)
                if target is not None:
                    return {"granted": False, "spillback": target}
                self._maybe_revoke_idle_lease()
                return None

        allocation = self.resources.allocate(spec.resources, bundle=bundle)
        if allocation is None:
            return None
        from ..._internal.runtime_env import env_key as _env_key

        worker = await self.worker_pool.pop(
            timeout=60.0,
            env_key=_env_key(spec.runtime_env),
            runtime_env=spec.runtime_env,
        )
        if worker is None:
            self.resources.release(allocation)
            return {"granted": False, "reason": "no worker available"}
        lease_id = UniqueID.from_random()
        self._leases[lease_id] = Lease(
            lease_id, worker, allocation, spec, reusable=reusable
        )
        # job attribution for the log plane: output from this worker belongs
        # to the leasing job from here on (reference: per-job workers)
        job = getattr(spec, "job_id", None)
        if job is not None:
            self._worker_job[worker.pid] = job.hex()
        return {
            "granted": True,
            "lease_id": lease_id,
            "worker_id": worker.worker_id,
            "worker_address": worker.address,
            "node_id": self.node_id,
            "instances": allocation.instance_ids,
        }

    def _find_bundle(self, pg_id: PlacementGroupID, demand) -> Optional[int]:
        for (bpg, index) in self.resources._committed:
            if bpg == pg_id and self.resources.bundle_can_allocate(bpg, index, demand):
                return index
        return None

    def _spillback_or_reject(self, spec: TaskSpec) -> dict:
        """Task infeasible on this node: find a feasible node in the cluster
        view (reference: spillback in ClusterLeaseManager)."""
        for node_id, info in self._cluster_nodes.items():
            if node_id == self.node_id or not info.alive:
                continue
            feasible = all(
                info.resources_total.get(k, 0.0) >= v - 1e-9
                for k, v in spec.resources.items()
            ) and label_match(info.labels, spec.label_selector)
            if feasible:
                return {"granted": False, "spillback": (node_id, info.address)}
        # Remember the unmet demand so the autoscaler sees it even though the
        # owner polls (each retry refreshes the TTL; reference: infeasible
        # tasks stay queued and are reported as pending demand).
        self._infeasible_demands[spec.task_id] = (
            dict(spec.resources),
            dict(spec.label_selector or {}),
            time.time(),
        )
        return {"granted": False, "infeasible": True,
                "reason": f"no node satisfies {spec.resources} {spec.label_selector}"}

    def _pick_remote_with_capacity(self, spec: TaskSpec) -> Optional[tuple]:
        best = None
        best_score = None
        for node_id, info in self._cluster_nodes.items():
            if node_id == self.node_id or not info.alive:
                continue
            if not label_match(info.labels, spec.label_selector):
                continue
            avail = self._cluster_available.get(node_id)
            if avail is None:
                continue
            if all(avail.get(k, 0.0) >= v - 1e-9 for k, v in spec.resources.items()):
                score = sum(avail.values())
                if best_score is None or score > best_score:
                    best, best_score = (node_id, info.address), score
        return best

    def _pick_spread_target(self, spec: TaskSpec) -> Optional[tuple]:
        """SPREAD strategy: round-robin over feasible nodes by least load."""
        candidates = []
        for node_id, info in self._cluster_nodes.items():
            if not info.alive:
                continue
            if not all(
                info.resources_total.get(k, 0.0) >= v - 1e-9
                for k, v in spec.resources.items()
            ):
                continue
            avail = self._cluster_available.get(node_id, info.resources_total)
            used = sum(
                info.resources_total.get(k, 0.0) - avail.get(k, 0.0)
                for k in info.resources_total
            )
            candidates.append((used, node_id, info.address))
        if not candidates:
            return None
        candidates.sort(key=lambda c: (c[0], c[1]))
        _, node_id, address = candidates[0]
        return (node_id, address)

    # -- placement group bundles ------------------------------------------

    async def handle_prepare_bundle(
        self, pg_id: PlacementGroupID, index: int, resources: Dict[str, float]
    ) -> bool:
        ok = self.resources.prepare_bundle(pg_id, index, resources)
        if not ok:
            # an owner-cached idle lease may be holding exactly the capacity
            # this bundle needs: recall one so the GCS's scheduling retry
            # (backoff loop in placement_groups.py) can succeed
            self._maybe_revoke_idle_lease()
        return ok

    async def handle_commit_bundle(self, pg_id: PlacementGroupID, index: int) -> bool:
        ok = self.resources.commit_bundle(pg_id, index)
        self._dispatch_wakeup.set()
        return ok

    async def handle_return_bundle(self, pg_id: PlacementGroupID, index: int):
        self.resources.return_bundle(pg_id, index)
        self._dispatch_wakeup.set()
        return True

    # -- object store service ---------------------------------------------

    async def handle_store_create(self, object_id: ObjectID, size: int):
        try:
            return {
                "ok": True,
                "segment": await self._create_with_spill(object_id, size),
            }
        except ObjectStoreFullError as e:
            return {"ok": False, "error": str(e)}

    # -- spilling (reference: LocalObjectManager::SpillObjects
    # raylet/local_object_manager.h:115 + external storage
    # _private/external_storage.py FileSystemStorage) -----------------------

    def _spill_dir(self) -> str:
        path = f"/tmp/ray_tpu_spill_{self.session_id}_{self.node_id.hex()[:6]}"
        os.makedirs(path, exist_ok=True)
        return path

    def _spill_ref(self, object_id: ObjectID) -> str:
        """Where a spilled copy lives: node-local disk by default, or an
        external object store when ``spill_storage_uri`` is configured
        (reference: _private/external_storage.py:399 — the S3/GCS tier)."""
        uri = self.config.spill_storage_uri
        if uri:
            return (
                f"{uri.rstrip('/')}/"
                f"{self.session_id}_{self.node_id.hex()[:6]}/{object_id.hex()}"
            )
        return os.path.join(self._spill_dir(), object_id.hex())

    async def _create_with_spill(self, object_id: ObjectID, size: int) -> str:
        """store.create, spilling LRU primary copies to disk under memory
        pressure instead of failing."""
        if size > self.store.capacity:
            # reject up front — spilling the whole store could never help
            raise ObjectStoreFullError(
                f"object of {size} bytes exceeds store capacity "
                f"{self.store.capacity}"
            )
        from ..object_store.native_store import FetchInFlightError

        tried: set = set()
        deadline = time.time() + 30.0
        while True:
            try:
                return self.store.create(object_id, size)
            except FetchInFlightError:
                # transient: a native pull of the same object is mid-stream;
                # once it adopts, create() dedups onto the landed copy.
                # Spilling could never help here.
                if time.time() > deadline:
                    raise
                await asyncio.sleep(0.02)
            except ObjectStoreFullError:
                victim = self.store.lru_spillable()
                if victim is None or victim == object_id or victim in tried:
                    raise
                tried.add(victim)
                await self._spill_object(victim)

    async def _spill_object(self, object_id: ObjectID):
        view = self.store.read_local(object_id)
        if view is None:
            return  # vanished (freed/evicted) — space may already be back
        path = self._spill_ref(object_id)
        # copy out, then write off-loop: disk/network I/O on the event loop
        # would stall heartbeats and lease dispatch (reference: spill
        # workers are separate IO processes, worker_pool.h io worker pool)
        data = bytes(view)
        del view
        try:
            await asyncio.to_thread(spill_storage.write, path, data)
        except Exception:
            logger.exception("spill write failed for %s; skipping", object_id)
            return
        # a reader may have pinned the object during the await; freeing then
        # would reallocate a block a live zero-copy view still aliases.
        # freed is None when the object vanished during the write (a
        # concurrent free already ran) — recording a spill copy then would
        # resurrect a freed object on a later stale get
        freed = self.store.free_if_unpinned(object_id)
        if freed is not True:
            await asyncio.to_thread(spill_storage.delete, path)
            return
        self._spilled[object_id] = path
        logger.info("spilled %s (%d bytes) to %s", object_id, len(data), path)

    async def _restore_spilled(self, object_id: ObjectID) -> bool:
        """Bring a spilled object back into the arena (reference:
        AsyncRestoreSpilledObject, local_object_manager.h:127).

        Restores are serialized per object id: two concurrent gets both see
        the id in _spilled, the first restore deletes the spill file, and an
        unserialized second restore would FileNotFoundError even though the
        object is now in the store."""
        lock = self._restore_locks.setdefault(object_id, asyncio.Lock())
        self._restore_lock_holds[object_id] = (
            self._restore_lock_holds.get(object_id, 0) + 1
        )
        try:
            async with lock:
                if self.store.contains(object_id):
                    return True  # a concurrent restore won
                path = self._spilled.get(object_id)
                if path is None:
                    return self.store.contains(object_id)
                try:
                    data = await asyncio.to_thread(spill_storage.read, path)
                except spill_storage.SpillStorageError:
                    # transient backend failure: the blob is still there —
                    # keep the pointer and let the caller retry
                    logger.warning("spill restore of %s failed transiently",
                                   object_id)
                    return False
                except OSError:
                    # copy vanished (concurrent free / external cleanup)
                    self._spilled.pop(object_id, None)
                    return self.store.contains(object_id)
                await self._create_with_spill(object_id, len(data))
                self.store.write_view(object_id)[: len(data)] = data
                self.store.seal(object_id)
                self.store.pin_primary(object_id)  # restored copy stays primary
                self._spilled.pop(object_id, None)
                await asyncio.to_thread(spill_storage.delete, path)
                return True
        finally:
            # drop the per-object lock only when no other coroutine is
            # holding or waiting on it, tracked with an explicit counter
            # (asyncio.Lock has no public waiter count)
            holds = self._restore_lock_holds.get(object_id, 1) - 1
            if holds <= 0:
                self._restore_lock_holds.pop(object_id, None)
                self._restore_locks.pop(object_id, None)
            else:
                self._restore_lock_holds[object_id] = holds

    async def handle_store_seal(self, object_id: ObjectID, is_primary: bool = False):
        self.store.seal(object_id)
        if is_primary:
            self.store.pin_primary(object_id)
        return True

    async def handle_store_contains(self, object_id: ObjectID):
        return self.store.contains(object_id)

    async def handle_store_get(
        self,
        object_id: ObjectID,
        owner_address: Optional[Tuple[str, int]] = None,
        timeout: Optional[float] = None,
        prefer_source: Optional[Tuple[str, int]] = None,
    ):
        """Local get; pulls from a remote node when the object isn't here
        (reference: PullManager). ``prefer_source`` names the peer to pull
        from first — the weight plane routes each node at its broadcast-tree
        parent so a shard leaves the publisher once, not once per node."""
        if self.store.contains(object_id):
            result = await self.store.get(object_id, timeout=0.1)
            if result is not None:
                return {"ok": True, "segment": result[0], "size": result[1]}
        if object_id in self._spilled:
            try:
                restored = await self._restore_spilled(object_id)
            except ObjectStoreFullError:
                restored = False
            if restored:
                result = await self.store.get(object_id, timeout=1.0)
                if result is not None:
                    return {"ok": True, "segment": result[0], "size": result[1]}
            else:
                # arena is full of pinned readers: serve the payload inline
                # from the spill file (a copy) rather than failing the get —
                # the object is durably here, only zero-copy is impossible
                path = self._spilled.get(object_id)
                if path is not None:
                    try:
                        data = await asyncio.to_thread(spill_storage.read, path)
                        return {"ok": True, "data": data}
                    except (OSError, spill_storage.SpillStorageError):
                        pass  # raced with restore, or transient backend error
        if owner_address is not None:
            pulled = await self._pull_object(
                object_id, owner_address, prefer_source
            )
            if pulled:
                result = await self.store.get(object_id, timeout=1.0)
                if result is not None:
                    return {"ok": True, "segment": result[0], "size": result[1]}
        result = await self.store.get(object_id, timeout=timeout)
        if result is None:
            return {"ok": False}
        return {"ok": True, "segment": result[0], "size": result[1]}

    async def handle_store_release(self, object_id: ObjectID):
        self.store.release(object_id)
        if object_id in self._deferred_frees:
            # the owner freed this object while a zero-copy reader held a
            # pin; now that the pin count may have dropped, retry
            if self.store.free_if_unpinned(object_id) is not False:
                self._deferred_frees.discard(object_id)
        return True

    async def handle_free_objects(self, object_ids: List[ObjectID]):
        for oid in object_ids:
            # NEVER free a block a concurrent zero-copy reader still pins —
            # the allocator would hand the space to the next create and the
            # reader's live numpy views would silently change contents.
            # Pinned objects free later, on the releasing store_release.
            if self.store.free_if_unpinned(oid) is False:
                self._deferred_frees.add(oid)
            path = self._spilled.pop(oid, None)
            if path is not None:
                self._bg.spawn(asyncio.to_thread(spill_storage.delete, path))
        return True

    async def handle_fetch_object(self, object_id: ObjectID, offset: int, length: int):
        """Serve one chunk of a local object to a pulling peer (reference:
        ObjectManager::Push chunking).

        A spilled primary copy is still durably here — the owner's location
        table lists this node — so serve chunks straight from the spill file
        rather than returning None (which would surface as ObjectLostError
        at the puller)."""
        view = self.store.read_local(object_id)
        if view is None:
            path = self._spilled.get(object_id)
            if path is not None:
                try:
                    total, chunk = await asyncio.to_thread(
                        spill_storage.read_range, path, offset, length
                    )
                    self._note_fetch_served(object_id, offset, len(chunk))
                    return {"total": total, "data": chunk}
                except (OSError, spill_storage.SpillStorageError):
                    pass  # spill copy raced with restore/free, or transient
            # a concurrent restore may have just completed (and popped the
            # _spilled entry + deleted the file): retry the store before
            # declaring the object absent
            view = self.store.read_local(object_id)
            if view is None:
                return None
        total = len(view)
        chunk = bytes(view[offset : offset + length])
        self._note_fetch_served(object_id, offset, len(chunk))
        return {"total": total, "data": chunk}

    def _note_fetch_served(self, object_id: ObjectID, offset: int, nbytes: int):
        if offset == 0:
            self._fetch_serves[object_id] = (
                self._fetch_serves.get(object_id, 0) + 1
            )
        self._fetch_bytes_out += nbytes

    async def handle_transfer_stats(self):
        """Per-node transfer accounting: python-path serves per object,
        payload bytes out, and native-plane pull count. The weight-plane
        multi-node test asserts each chunk is served from the publisher node
        at most once regardless of subscriber count."""
        return {
            "fetch_serves": {
                oid.hex(): n for oid, n in self._fetch_serves.items()
            },
            "fetch_bytes_out": self._fetch_bytes_out,
            "native_pulls": self._native_pulls,
        }

    async def handle_store_pin_weight(self, object_id: ObjectID) -> bool:
        """Weight-plane pin (refcounted): exempts a local chunk copy from
        eviction and spill selection until the matching unpin."""
        pin = getattr(self.store, "pin_weight", None)
        return bool(pin(object_id)) if pin is not None else False

    async def handle_store_unpin_weight(self, object_id: ObjectID) -> bool:
        unpin = getattr(self.store, "unpin_weight", None)
        if unpin is not None:
            unpin(object_id)
        return True

    async def handle_transfer_info(self):
        """Advertise the native transfer-plane port (None = python path)."""
        return {"port": self._transfer_port}

    async def _native_pull(self, object_id: ObjectID, node_address) -> bool:
        """Try the C++ transfer plane: one TCP stream straight into the
        local arena. False = not attempted / failed (caller falls back to
        the chunked-RPC pull)."""
        if not self.config.object_transfer_native_enabled:
            return False
        if self._transfer_port is None or not hasattr(
            self.store, "transfer_fetch_raw"
        ):
            return False
        key = tuple(node_address)
        cached = self._peer_transfer_ports.get(key)
        # a failed probe is retried after a grace period (the peer may have
        # just been starting up), not cached forever
        if cached is not None and (
            cached[0] is not None or time.time() < cached[1]
        ):
            port = cached[0]
        else:
            try:
                peer = self.client_pool.get(*node_address)
                info = await peer.call("transfer_info", timeout=5.0)
                port = (info or {}).get("port")
            except Exception:
                port = None
            self._peer_transfer_ports[key] = (port, time.time() + 30.0)
        if port is None:
            return False
        self.store.begin_fetch(object_id)
        try:
            rc, off, size = await asyncio.to_thread(
                self.store.transfer_fetch_raw,
                object_id, node_address[0], port,
                self.config.cluster_auth_token,
            )
            if rc == 0:
                self.store.adopt_fetched(object_id, off, size)
                self._native_pulls += 1
                return True
        finally:
            self.store.end_fetch(object_id)
        if rc == -4:  # already present (raced with another pull)
            return self.store.contains(object_id)
        if rc in (-1, -5):
            # connect/protocol/auth failure: the peer may have restarted on
            # a new port (or with a new token) — drop the cache entry so the
            # next pull re-probes instead of paying this again
            self._peer_transfer_ports.pop(key, None)
        return False

    async def _pull_object(
        self, object_id: ObjectID, owner_address, prefer_source=None
    ) -> bool:
        """Ask the owner where the object lives, then pull it — C++
        transfer plane first, chunked RPC as the fallback (reference:
        PullManager + ObjectManager::Push).

        Serialized per object: the native fetch creates the C++ arena entry
        before the python mirrors exist, so a concurrent pull of the SAME
        object would see an inconsistent half-created state (the chunked
        path's mirror-first ordering tolerated this; the native path does
        not)."""
        lock = self._pull_locks.setdefault(object_id, asyncio.Lock())
        # hold-counted cleanup: Lock.locked() is False the instant release()
        # runs even with waiters still queued, so a holder's `finally` could
        # delete the entry out from under them and a third pull would mint a
        # fresh lock — two pulls of the same object running "locked"
        self._pull_lock_holds[object_id] = (
            self._pull_lock_holds.get(object_id, 0) + 1
        )
        try:
            async with lock:
                if self.store.contains(object_id):
                    return True  # a concurrent pull already landed it
                return await self._pull_object_locked(
                    object_id, owner_address, prefer_source
                )
        finally:
            holds = self._pull_lock_holds[object_id] - 1
            if holds:
                self._pull_lock_holds[object_id] = holds
            else:
                del self._pull_lock_holds[object_id]
                if self._pull_locks.get(object_id) is lock:
                    del self._pull_locks[object_id]

    async def _pull_object_locked(
        self, object_id: ObjectID, owner_address, prefer_source=None
    ) -> bool:
        try:
            owner = self.client_pool.get(*owner_address)
            loc = await owner.call(
                "get_object_locations", object_id, timeout=10.0
            )
        except Exception as e:
            logger.debug("pull: owner lookup failed for %s: %s", object_id, e)
            return False
        if prefer_source is not None:
            # topology-aware pull (weight plane): try the named peer first
            # even if the owner's location table hasn't caught up with it yet
            # (the caller verified the peer holds the object; registration
            # with the owner is asynchronous). Other holders stay as
            # fallbacks so a dead parent cannot wedge the pull.
            prefer = tuple(prefer_source)
            loc = [prefer] + [
                n for n in (loc or ()) if tuple(n) != prefer
            ]
        if not loc:
            return False
        for node_address in loc:
            if tuple(node_address) == tuple(self.address):
                continue
            # Reachability gate: a dead holder refuses connects instantly,
            # but the client's connect-retry window would eat seconds per
            # attempt (native probe + chunked fallback) before the caller
            # can move on to reconstruction. Bound the connect here; the
            # transfer itself stays unbounded (big objects take long
            # legitimately).
            try:
                peer = self.client_pool.get(*node_address)
                await asyncio.wait_for(
                    peer._ensure_connected(), _PULL_CONNECT_PROBE_S
                )
            except Exception as e:
                logger.debug(
                    "pull of %s: holder %s unreachable (%s), trying next",
                    object_id, node_address, e,
                )
                continue
            try:
                if await self._native_pull(object_id, node_address):
                    try:
                        owner = self.client_pool.get(*owner_address)
                        await owner.call_oneway(
                            "add_object_location", object_id, self.address
                        )
                    except Exception:
                        pass
                    return True
            except Exception as e:
                logger.debug(
                    "native pull of %s failed: %s (falling back)",
                    object_id, e,
                )
            try:
                peer = self.client_pool.get(*node_address)
                chunk_size = self.config.object_transfer_chunk_size
                first = await peer.call(
                    "fetch_object", object_id, 0, chunk_size, timeout=30.0
                )
                if first is None:
                    continue
                total = first["total"]
                segment = await self._create_with_spill(object_id, total)
                view = self.store.write_view(object_id)
                view[: len(first["data"])] = first["data"]
                offset = len(first["data"])
                while offset < total:
                    part = await peer.call(
                        "fetch_object", object_id, offset, chunk_size,
                        timeout=30.0,
                    )
                    if part is None:
                        break
                    data = part["data"]
                    if not data:
                        # peer returned an empty chunk (e.g. a concurrent
                        # restore/re-spill rewrote the file under the read);
                        # looping again with the same offset would busy-spin
                        break
                    view[offset : offset + len(data)] = data
                    offset += len(data)
                if offset >= total:
                    self.store.seal(object_id)
                    # tell the owner this node now holds a copy
                    try:
                        owner = self.client_pool.get(*owner_address)
                        await owner.call_oneway(
                            "add_object_location", object_id, self.address
                        )
                    except Exception:
                        pass
                    return True
                self.store.free(object_id)
            except Exception as e:
                logger.debug("pull of %s from %s failed: %s", object_id, node_address, e)
        return False

    # -- worker logs (reference: log_monitor.py + `ray logs`) --------------

    def _worker_log_sink(self, record: dict):
        """Called from log-pump threads: ship a batch of worker output lines
        to the GCS "logs" pubsub channel for driver echo."""
        if self._stopped:
            return
        record = dict(
            record, ip=self.address[0], node_id=self.node_id.hex(),
            job_id=self._worker_job.get(record.get("pid"), ""),
        )
        asyncio.run_coroutine_threadsafe(self._publish_logs(record), self._loop)

    async def _publish_logs(self, record: dict):
        try:
            gcs = self.client_pool.get(*self.gcs_address)
            await gcs.call_oneway("publish", "logs", record)
        except Exception:
            pass  # log echo is best-effort; never destabilize the raylet

    async def handle_list_logs(self) -> List[str]:
        """List log files in this node's session log dir (`ray logs`)."""
        try:
            return sorted(os.listdir(self.log_dir))
        except OSError:
            return []

    async def handle_read_log(self, name: str, tail: int = 1000) -> str:
        """Return the last ``tail`` lines of one session log file. The name
        is basename-sanitized — this RPC must not become a file-read oracle."""
        path = os.path.join(self.log_dir, os.path.basename(name))
        try:
            with open(path, "rb") as f:
                f.seek(0, os.SEEK_END)
                size = f.tell()
                f.seek(max(0, size - 4 * 1024 * 1024))
                data = f.read()
        except OSError:
            return ""
        lines = data.decode("utf-8", errors="replace").splitlines()
        return "\n".join(lines[-tail:])

    # -- misc --------------------------------------------------------------

    async def handle_ping(self):
        return {"node_id": self.node_id, "time": time.time()}

    async def handle_get_node_info(self):
        return {
            "node_id": self.node_id,
            "address": self.address,
            "resources_total": self.resources.total_float(),
            "resources_available": self.resources.available_float(),
            "labels": dict(self.resources.labels),
            "store": self.store.stats(),
            "transfer_port": self._transfer_port,
            "native_pulls": self._native_pulls,
            "num_workers": self.worker_pool.num_total if self.worker_pool else 0,
        }

    async def handle_drain(self):
        """Graceful drain (reference: HandleDrainRaylet node_manager.h:313)."""
        gcs = self.client_pool.get(*self.gcs_address)
        await gcs.call("unregister_node", self.node_id, timeout=10.0)
        return True



