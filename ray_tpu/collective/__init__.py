"""ray_tpu.collective: collective communication.

Role-equivalent of ray.util.collective (util/collective/collective.py:182-752)
with the NCCL backend replaced by XLA collectives over ICI. Groups are
registered per process under a name; tasks/actors in the same group call the
module-level ops.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from ..exceptions import CollectiveAbortedError
from ..util import events as _events
from .base import BaseGroup, ReduceOp
from .bucketizer import DEFAULT_BUCKET_BYTES, BucketSpec, GradientBucketizer
from .cpu_group import GcsStoreGroup
from .hierarchical import HierarchicalGroup
from .scheduler import (
    AsyncHandle,
    GradientReduceScheduler,
    PendingReduce,
)
from .xla_group import XlaGroup

_groups: Dict[str, BaseGroup] = {}

_BACKENDS = {
    "gcs": GcsStoreGroup,  # host tensors through the GCS KV (gloo role)
    "cpu": GcsStoreGroup,
    "xla": XlaGroup,  # device tensors over ICI (nccl role)
    # two-tier intra-slice/inter-slice composition (requires slice_size=)
    "hier": HierarchicalGroup,
}


def init_collective_group(
    world_size: int,
    rank: int,
    backend: str = "xla",
    group_name: str = "default",
    **kwargs,
) -> BaseGroup:
    """Imperative group init, called by every member (reference:
    collective.py:182)."""
    if group_name in _groups:
        raise ValueError(f"collective group {group_name!r} already exists")
    cls = _BACKENDS[backend]
    group = cls(world_size, rank, group_name, **kwargs)
    _groups[group_name] = group
    _events.record_event(
        _events.COLLECTIVE_EPOCH,
        group=group_name, epoch=getattr(group, "epoch", 0),
        world_size=world_size, rank=rank, backend=backend, phase="formed",
    )
    return group


def create_collective_group(
    actors: List,
    world_size: int,
    ranks: List[int],
    backend: str = "gcs",
    group_name: str = "default",
    **kwargs,
):
    """Declarative init: make every actor in ``actors`` join the group
    (reference: collective.py:222). Uses the executor's reserved
    ``__init_collective__`` actor-task hook, so actor classes need no
    special method. Extra kwargs (``epoch=``, ``quantized=``,
    ``quant_block=``...) forward to every member's backend constructor —
    config like the quantized wire format must be group-uniform, so it is
    set here once rather than per member."""
    from .. import api
    from ..actor import ActorMethod

    refs = [
        ActorMethod(actor, "__init_collective__", {}).remote(
            world_size, rank, backend, group_name, **kwargs
        )
        for actor, rank in zip(actors, ranks)
    ]
    return api.get(refs)


def get_group(group_name: str = "default") -> BaseGroup:
    group = _groups.get(group_name)
    if group is None:
        raise ValueError(
            f"no collective group {group_name!r} in this process; call "
            "init_collective_group first"
        )
    return group


def is_group_initialized(group_name: str = "default") -> bool:
    return group_name in _groups


def destroy_collective_group(group_name: str = "default"):
    group = _groups.pop(group_name, None)
    if group is not None:
        group.destroy()


def abort_collective_group(
    group_name: str = "default", epoch: Optional[int] = None,
    reason: str = "explicit abort",
) -> bool:
    """Abort the group's in-flight ops cluster-wide: every member blocked in
    a rendezvous (any process) raises :class:`CollectiveAbortedError` within
    ~1 s. ``epoch`` defaults to the locally-registered group's epoch (0 if
    the group isn't local — the common case for a controller/CLI caller that
    knows the epoch and passes it explicitly)."""
    from .cpu_group import write_abort

    if epoch is None:
        local = _groups.get(group_name)
        epoch = local.epoch if local is not None else 0
    _events.record_event(
        _events.COLLECTIVE_EPOCH,
        group=group_name, epoch=epoch, phase="aborted", reason=reason,
    )
    return write_abort(group_name, epoch, reason)


def allreduce(tensor, group_name: str = "default", op: ReduceOp = ReduceOp.SUM):
    return get_group(group_name).allreduce(tensor, op)


def allgather(tensor, group_name: str = "default"):
    return get_group(group_name).allgather(tensor)


def reducescatter(tensor, group_name: str = "default", op: ReduceOp = ReduceOp.SUM):
    return get_group(group_name).reducescatter(tensor, op)


def broadcast(tensor, src_rank: int = 0, group_name: str = "default"):
    return get_group(group_name).broadcast(tensor, src_rank)


def send(tensor, dst_rank: int, group_name: str = "default"):
    return get_group(group_name).send(tensor, dst_rank)


def recv(src_rank: int, group_name: str = "default"):
    return get_group(group_name).recv(src_rank)


def barrier(group_name: str = "default"):
    return get_group(group_name).barrier()


__all__ = [
    "BaseGroup", "ReduceOp", "GcsStoreGroup", "XlaGroup",
    "HierarchicalGroup",
    "AsyncHandle", "PendingReduce", "GradientReduceScheduler",
    "GradientBucketizer", "BucketSpec", "DEFAULT_BUCKET_BYTES",
    "CollectiveAbortedError",
    "init_collective_group", "create_collective_group",
    "destroy_collective_group", "abort_collective_group",
    "get_group", "is_group_initialized",
    "allreduce", "allgather", "reducescatter", "broadcast",
    "send", "recv", "barrier",
]
