"""Hierarchical multi-slice collective group.

A TPU pod's network is not flat: ICI within a slice is an order of
magnitude faster than DCN between slices (the MLPerf-on-TPU-pods topology).
A flat W-rank reduce puts every rank's full payload on the slow tier;
the hierarchical schedule ships it twice over the fast tier and once over
the slow one:

    1. intra-slice reduce   (all `slice_size` members of each slice)
    2. inter-slice reduce   (one leader per slice, `num_slices` ranks)
    3. intra-slice broadcast (leader fans the global result back out)

:class:`HierarchicalGroup` composes those phases from the existing
backends — intra-slice ``XlaGroup`` psum (or ``GcsStoreGroup`` where no
per-slice device mesh exists, e.g. emulated topologies in tests) and
inter-slice ``GcsStoreGroup`` reduce — behind the unchanged
:class:`~ray_tpu.collective.base.BaseGroup` interface. The overlapped
scheduler (collective/scheduler.py) therefore drives it exactly like a flat
group: ``allreduce_async`` inherits the dispatcher-thread default, and each
bucket's three phases pipeline behind one another in FIFO order.

Naming/abort contract: sub-groups are ``<name>:s<slice>`` (intra),
``<name>:x`` (inter leaders) and ``<name>:p2p`` (flat point-to-point), all
carrying ``parent_group=<name>`` so an abort written against the logical
group name unblocks a member stuck in ANY phase. Metrics are recorded by
the sub-groups under their own names — the hierarchical wrapper records
nothing itself, so collective_seconds_total() never double-counts a phase.
"""

from __future__ import annotations

from typing import Any, List, Optional

import numpy as np

from .base import BaseGroup, ReduceOp
from .cpu_group import GcsStoreGroup

#: intra-slice backend choices; "xla" needs a per-slice device mesh
_INTRA_BACKENDS = ("gcs", "xla")


class HierarchicalGroup(BaseGroup):
    backend = "hier"

    def __init__(
        self,
        world_size: int,
        rank: int,
        group_name: str,
        *,
        slice_size: int,
        epoch: int = 0,
        quantized: bool = False,
        quant_block: int = 0,
        intra_backend: str = "gcs",
    ):
        super().__init__(world_size, rank, group_name, epoch=epoch,
                         quantized=quantized, quant_block=quant_block)
        if slice_size <= 0:
            raise ValueError(f"slice_size must be positive, got {slice_size}")
        if world_size % slice_size != 0:
            raise ValueError(
                f"world_size={world_size} not divisible by "
                f"slice_size={slice_size}"
            )
        if intra_backend not in _INTRA_BACKENDS:
            raise ValueError(
                f"intra_backend must be one of {_INTRA_BACKENDS}, "
                f"got {intra_backend!r}"
            )
        self.slice_size = slice_size
        self.num_slices = world_size // slice_size
        self.slice_id = rank // slice_size
        self.intra_rank = rank % slice_size
        self.is_leader = self.intra_rank == 0

        sub_kwargs = dict(
            epoch=epoch, quantized=quantized, quant_block=quant_block,
        )
        if intra_backend == "xla":
            from .xla_group import XlaGroup

            # device mesh fast path; its host fallbacks already rendezvous
            # under "<intra-name>:host"
            self._intra = XlaGroup(
                slice_size, self.intra_rank,
                f"{group_name}:s{self.slice_id}", **sub_kwargs,
            )
        else:
            self._intra = GcsStoreGroup(
                slice_size, self.intra_rank,
                f"{group_name}:s{self.slice_id}",
                parent_group=group_name, **sub_kwargs,
            )
        # the inter-slice tier is the slow/DCN tier: host rendezvous through
        # the GCS KV, leaders only (non-leaders never touch it)
        self._inter: Optional[GcsStoreGroup] = None
        if self.is_leader:
            self._inter = GcsStoreGroup(
                self.num_slices, self.slice_id, f"{group_name}:x",
                parent_group=group_name, **sub_kwargs,
            )
        self._p2p: Optional[GcsStoreGroup] = None

    # -- phase composition -------------------------------------------------

    def allreduce(self, tensor, op: ReduceOp = ReduceOp.SUM):
        """reduce-within, reduce-across, fan back out. Only the slice sums
        (num_slices contributions, not world_size) cross the slow tier."""
        partial = self._intra.allreduce(tensor, op)
        if self.num_slices == 1:
            return partial
        if self.is_leader:
            total = self._inter.allreduce(partial, op)
        else:
            total = partial  # placeholder; overwritten by the fan-out
        return self._intra.broadcast(total, src_rank=0)

    def allgather(self, tensor) -> List[Any]:
        """Gather within the slice, concatenate slice lists across leaders,
        fan the world-ordered list back out (global rank order: slices by
        slice_id, members by intra rank — exactly rank = slice*size+intra)."""
        local = self._intra.allgather(tensor)
        if self.num_slices == 1:
            return list(local)
        if self.is_leader:
            nested = self._inter.allgather(list(local))
            flat = [item for slice_items in nested for item in slice_items]
        else:
            flat = None
        return list(self._intra.broadcast(flat, src_rank=0))

    def reducescatter(self, tensor, op: ReduceOp = ReduceOp.SUM):
        reduced = self.allreduce(tensor, op)
        shards = np.array_split(np.asarray(reduced), self.world_size, axis=0)
        return shards[self.rank]

    def broadcast(self, tensor, src_rank: int = 0):
        src_slice, src_intra = divmod(src_rank, self.slice_size)
        value = tensor
        if self.slice_id == src_slice and src_intra != 0:
            # hoist the payload to the source slice's leader first
            value = self._intra.broadcast(value, src_rank=src_intra)
        if self.num_slices > 1 and self.is_leader:
            value = self._inter.broadcast(value, src_rank=src_slice)
        if self.slice_size > 1:
            value = self._intra.broadcast(value, src_rank=0)
        return value

    def barrier(self):
        self._intra.barrier()
        if self.num_slices > 1:
            if self.is_leader:
                self._inter.barrier()
            # second intra pass so non-leaders also wait out the slow tier
            self._intra.barrier()

    # -- point-to-point ----------------------------------------------------

    def _p2p_group(self) -> GcsStoreGroup:
        """Flat world-spanning sub-group for send/recv: point-to-point has
        no hierarchy to exploit, and a dedicated group keeps its sequence
        numbers out of the phase groups' rendezvous."""
        if self._p2p is None:
            self._p2p = GcsStoreGroup(
                self.world_size, self.rank, f"{self.group_name}:p2p",
                parent_group=self.group_name, epoch=self.epoch,
            )
        return self._p2p

    def send(self, tensor, dst_rank: int):
        return self._p2p_group().send(tensor, dst_rank)

    def recv(self, src_rank: int):
        return self._p2p_group().recv(src_rank)

    def destroy(self):
        self._shutdown_async()
        for sub in (self._intra, self._inter, self._p2p):
            if sub is not None:
                sub.destroy()
        self._inter = None
        self._p2p = None
