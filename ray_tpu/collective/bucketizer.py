"""Gradient bucketizer: deterministic leaf->bucket assignment over a pytree.

The overlapped-reduction scheduler (collective/scheduler.py) ships gradients
bucket-by-bucket so the first buckets' allreduce runs while the rest of the
backward (or the host-side tail of the step) is still producing values — the
pipelining the TPU-concurrency paper attributes pod-scale efficiency to.
Buckets must satisfy two contracts:

1. **Deterministic across ranks.** Every rank concatenates the same leaves
   into the same bucket in the same order, or the allreduce sums garbage.
   Assignment therefore depends only on the tree's *structure* (sorted leaf
   paths + shapes + dtypes), never on dict insertion order, rank, or any
   per-process state. An elastic re-form at epoch+1 rebuilds byte-identical
   buckets from the same model for the same reason.

2. **Size-targeted.** ``bucket_bytes`` balances dispatch overhead (too many
   tiny collectives) against lost overlap (one giant collective can't start
   until the last leaf exists). Leaves are greedily packed in sorted-path
   order until a bucket reaches the target; a single leaf at or above the
   target gets its own bucket. Buckets are dtype-homogeneous so each packs
   into ONE flat array with no casting on the wire.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, List, Sequence, Tuple

import numpy as np

#: default size target — big enough to amortize rendezvous/dispatch
#: overhead, small enough that early buckets reduce well before the step's
#: tail compute finishes (same order as torch DDP's 25MB, scaled down for
#: the model sizes this repo's smokes run)
DEFAULT_BUCKET_BYTES = 4 << 20


@dataclass(frozen=True)
class BucketSpec:
    """One bucket's immutable assignment (identical on every rank)."""

    index: int
    #: leaf path strings, in pack order
    paths: Tuple[str, ...]
    #: per-leaf shapes/sizes, in pack order (unpack splits by these)
    shapes: Tuple[Tuple[int, ...], ...]
    dtype: str
    nbytes: int


def _path_str(key_path) -> str:
    """Render a jax KeyPath deterministically ('layer0/kernel' style)."""
    parts = []
    for entry in key_path:
        # DictKey('a') -> 'a', SequenceKey(0) -> '0', GetAttrKey(x) -> 'x'
        for attr in ("key", "idx", "name"):
            if hasattr(entry, attr):
                parts.append(str(getattr(entry, attr)))
                break
        else:
            parts.append(str(entry))
    return "/".join(parts)


class GradientBucketizer:
    """Assign a pytree's leaves to size-targeted buckets; pack/unpack trees.

    Built once per (tree structure, bucket_bytes); ``pack`` turns a
    same-structured tree into one flat array per bucket and ``unpack``
    inverts it. The assignment is a pure function of the sorted leaf paths,
    shapes, and dtypes — see the module docstring for why.
    """

    def __init__(self, tree: Any, bucket_bytes: int = DEFAULT_BUCKET_BYTES):
        import jax

        if bucket_bytes <= 0:
            raise ValueError(f"bucket_bytes must be positive, got {bucket_bytes}")
        self.bucket_bytes = int(bucket_bytes)
        leaves_with_path, self._treedef = jax.tree_util.tree_flatten_with_path(
            tree
        )
        infos = []
        for flat_idx, (key_path, leaf) in enumerate(leaves_with_path):
            arr = np.asarray(leaf) if not hasattr(leaf, "dtype") else leaf
            infos.append(
                (
                    _path_str(key_path),
                    flat_idx,
                    tuple(int(d) for d in arr.shape),
                    str(arr.dtype),
                    int(np.prod(arr.shape, dtype=np.int64))
                    * np.dtype(str(arr.dtype)).itemsize,
                )
            )
        # sorted-path order IS the pack order: stable under dict insertion
        # order, rank, and re-forms (jax already sorts dict keys, this makes
        # the contract explicit and covers registered custom nodes too)
        infos.sort(key=lambda t: t[0])
        #: flat-leaf index (tree_flatten order) per sorted position
        self._flat_order: List[int] = [t[1] for t in infos]
        self._num_leaves = len(infos)

        self.buckets: List[BucketSpec] = []
        #: per-bucket list of sorted positions (indices into _flat_order)
        self._bucket_members: List[List[int]] = []
        current: List[int] = []
        cur_bytes = 0
        cur_dtype = None

        def _close():
            nonlocal current, cur_bytes, cur_dtype
            if not current:
                return
            self.buckets.append(
                BucketSpec(
                    index=len(self.buckets),
                    paths=tuple(infos[i][0] for i in current),
                    shapes=tuple(infos[i][2] for i in current),
                    dtype=cur_dtype,
                    nbytes=cur_bytes,
                )
            )
            self._bucket_members.append(list(current))
            current, cur_bytes, cur_dtype = [], 0, None

        for pos, (_path, _flat, _shape, dtype, nbytes) in enumerate(infos):
            if current and (dtype != cur_dtype or cur_bytes >= self.bucket_bytes):
                _close()
            current.append(pos)
            cur_bytes += nbytes
            cur_dtype = dtype
            if cur_bytes >= self.bucket_bytes:
                _close()
        _close()

    # -- identity ----------------------------------------------------------

    def signature(self) -> tuple:
        """Structure fingerprint: two trees with equal signatures get the
        identical bucket assignment (the elastic re-form invariant)."""
        return tuple(
            (b.paths, b.shapes, b.dtype) for b in self.buckets
        ) + (self.bucket_bytes,)

    @property
    def num_buckets(self) -> int:
        return len(self.buckets)

    # -- pack / unpack -----------------------------------------------------

    def pack(self, tree: Any) -> List[Any]:
        """One flat 1-D array per bucket, concatenating the bucket's leaves
        in assignment order. jax-array leaves concatenate with jnp (staying
        on device for the XLA dispatch path); host leaves with numpy."""
        import jax

        leaves = jax.tree_util.tree_leaves(tree)
        if len(leaves) != self._num_leaves:
            raise ValueError(
                f"tree has {len(leaves)} leaves, bucketizer was built for "
                f"{self._num_leaves}"
            )
        out = []
        for members in self._bucket_members:
            parts = [leaves[self._flat_order[pos]] for pos in members]
            if any(isinstance(p, jax.Array) for p in parts):
                import jax.numpy as jnp

                out.append(jnp.concatenate([jnp.ravel(p) for p in parts]))
            else:
                out.append(
                    np.concatenate([np.ravel(np.asarray(p)) for p in parts])
                )
        return out

    def unpack(self, bucket_arrays: Sequence[Any]) -> Any:
        """Invert ``pack``: split each flat bucket back into its leaves and
        rebuild the original tree structure."""
        import jax

        if len(bucket_arrays) != len(self.buckets):
            raise ValueError(
                f"got {len(bucket_arrays)} bucket arrays for "
                f"{len(self.buckets)} buckets"
            )
        flat: List[Any] = [None] * self._num_leaves
        for spec, members, arr in zip(
            self.buckets, self._bucket_members, bucket_arrays
        ):
            offset = 0
            for shape, pos in zip(spec.shapes, members):
                size = int(np.prod(shape, dtype=np.int64)) if shape else 1
                leaf = arr[offset:offset + size].reshape(shape)
                flat[self._flat_order[pos]] = leaf
                offset += size
        return jax.tree_util.tree_unflatten(self._treedef, flat)
