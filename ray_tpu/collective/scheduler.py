"""Overlapped gradient-reduction scheduler: the ONE scheduling layer both
collective backends sit behind.

Synchronous ``group.allreduce(grads)`` at the step boundary exposes the
whole collective on the critical path — exactly the time StepBreakdown's
compute/collective split measures being lost. This module hides it:

- :class:`AsyncHandle` — the completion handle ``allreduce_async`` returns.
  Dispatch never blocks; ``wait()`` does, and raises
  :class:`~ray_tpu.exceptions.CollectiveAbortedError` if the group was
  aborted while the op was in flight (a mid-flight bucket must fail fast,
  not hang the survivor).
- :class:`OpDispatcher` — one background rendezvous thread per group for
  backends whose ops are host-blocking (the GCS path). A FIFO queue keeps
  the group's op sequence identical on every rank, which is the GCS
  backend's correctness contract. The XLA path doesn't need it: jit
  dispatch is already asynchronous, so its handles wrap the not-yet-ready
  device array directly (see ``XlaGroup.allreduce_async``).
- :class:`GradientReduceScheduler` — bucketizes a gradient pytree
  (collective/bucketizer.py) and dispatches one async allreduce per bucket,
  so early buckets reduce while the caller computes the rest of the step.
  ``stale_grad=1`` goes further: ``step()`` returns the *previous* step's
  reduced gradients immediately, letting step N+1's forward overlap step
  N's tail reduce (one-step-delayed update — safe for SGD-family
  optimizers at small staleness; see docs/ARCHITECTURE.md §17).

Every wait records the exposed-vs-overlapped split into util/metrics, which
is what makes the win measurable rather than asserted.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Callable, List, Optional

from .bucketizer import DEFAULT_BUCKET_BYTES, GradientBucketizer

#: upper bound on one bucket's completion wait — comfortably above the
#: backends' own 120 s rendezvous timeout so the underlying op (or the
#: abort plane) always fires first
_HANDLE_TIMEOUT_S = 180.0


class AsyncHandle:
    """Completion handle for one async-dispatched collective op.

    After ``wait()`` returns (or raises), ``exposed_s`` is the wall time the
    caller actually blocked and ``overlapped_s`` the part of the op's
    latency that ran under the caller's compute — the two halves of the
    StepBreakdown split.
    """

    def __init__(self):
        self.dispatched_at = time.perf_counter()
        self.completed_at: Optional[float] = None
        self.exposed_s = 0.0
        self.overlapped_s = 0.0

    def done(self) -> bool:
        raise NotImplementedError

    def wait(self, timeout: float = _HANDLE_TIMEOUT_S):
        raise NotImplementedError

    def _split(self, wait_start: float, wait_end: float):
        """Attribute this op's latency: blocked wait = exposed, the rest of
        dispatch->completion ran under compute = overlapped."""
        self.exposed_s = max(0.0, wait_end - wait_start)
        total = (self.completed_at or wait_end) - self.dispatched_at
        self.overlapped_s = max(0.0, total - self.exposed_s)


class CompletedHandle(AsyncHandle):
    """Pre-completed op (the non-overlapped fallback path): the blocking
    call already happened at dispatch, so its whole duration is exposed."""

    def __init__(self, result: Any, blocked_s: float):
        super().__init__()
        self._result = result
        self.completed_at = self.dispatched_at
        self.exposed_s = max(0.0, blocked_s)
        self.overlapped_s = 0.0

    def done(self) -> bool:
        return True

    def wait(self, timeout: float = _HANDLE_TIMEOUT_S):
        return self._result


class FutureHandle(AsyncHandle):
    """Thread-completed op (OpDispatcher / the GCS backend)."""

    def __init__(self):
        super().__init__()
        self._event = threading.Event()
        self._result: Any = None
        self._exception: Optional[BaseException] = None

    def done(self) -> bool:
        return self._event.is_set()

    def _complete(self, result: Any = None,
                  exception: Optional[BaseException] = None):
        self._result = result
        self._exception = exception
        self.completed_at = time.perf_counter()
        self._event.set()

    def wait(self, timeout: float = _HANDLE_TIMEOUT_S):
        start = time.perf_counter()
        if not self._event.wait(timeout):
            raise TimeoutError("async collective op did not complete")
        self._split(start, time.perf_counter())
        if self._exception is not None:
            raise self._exception
        return self._result


class DeviceHandle(AsyncHandle):
    """XLA-dispatched op: the program is already in flight on the device
    stream; ``wait`` is block_until_ready plus the deferred metrics record
    (the dispatch path must not block, so the op's bytes/latency sample is
    recorded here, at completion)."""

    def __init__(self, value: Any,
                 on_ready: Optional[Callable[[float], None]] = None):
        super().__init__()
        self._value = value
        self._on_ready = on_ready
        self._waited = False

    def done(self) -> bool:
        if self._waited:
            return True
        is_ready = getattr(self._value, "is_ready", None)
        try:
            return bool(is_ready()) if callable(is_ready) else False
        except Exception:
            return False

    def wait(self, timeout: float = _HANDLE_TIMEOUT_S):
        import jax

        start = time.perf_counter()
        out = jax.block_until_ready(self._value)
        end = time.perf_counter()
        if not self._waited:
            self._waited = True
            self.completed_at = end
            self._split(start, end)
            if self._on_ready is not None:
                self._on_ready(end - self.dispatched_at)
        return out


class OpDispatcher:
    """One background rendezvous thread per group.

    Ops submitted here run strictly FIFO: as long as every rank dispatches
    its buckets in the same (deterministic, bucketizer-given) order, the
    group's rendezvous sequence numbers stay aligned across ranks — the
    same contract the synchronous path gets for free from the caller's
    program order. An exception (including CollectiveAbortedError from the
    abort plane) completes the handle exceptionally and the thread moves
    on; once a group is poisoned every queued op fails fast the same way.
    """

    def __init__(self, name: str):
        self._queue: "queue.Queue" = queue.Queue()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name=f"col-dispatch:{name}"
        )
        self._thread.start()

    def submit(self, fn: Callable[[], Any]) -> FutureHandle:
        handle = FutureHandle()
        self._queue.put((fn, handle))
        return handle

    def _run(self):
        while True:
            item = self._queue.get()
            if item is None:
                return
            fn, handle = item
            try:
                handle._complete(result=fn())
            except BaseException as e:  # noqa: BLE001 — handed to waiter
                handle._complete(exception=e)

    def shutdown(self, timeout: float = 2.0):
        self._queue.put(None)
        self._thread.join(timeout=timeout)


class PendingReduce:
    """All of one gradient tree's in-flight buckets; ``wait`` returns the
    reduced tree and records the exposed/overlapped split."""

    def __init__(self, handles: List[AsyncHandle],
                 bucketizer: GradientBucketizer, group_name: str,
                 epoch: int = 0):
        self._handles = handles
        self._bucketizer = bucketizer
        self._group_name = group_name
        self._epoch = epoch

    def done(self) -> bool:
        return all(h.done() for h in self._handles)

    def wait(self) -> Any:
        from ..util import metrics

        results = []
        error: Optional[BaseException] = None
        for h in self._handles:
            try:
                results.append(h.wait())
            except BaseException as e:  # noqa: BLE001
                # drain the remaining handles (they fail fast once the
                # group is poisoned) so no dispatcher state leaks, then
                # surface the first failure
                if error is None:
                    error = e
        exposed = sum(h.exposed_s for h in self._handles)
        overlapped = sum(h.overlapped_s for h in self._handles)
        metrics.record_collective_overlap(self._group_name, exposed, overlapped)
        self._record_series(exposed, overlapped)
        if error is not None:
            raise error
        return self._bucketizer.unpack(results)

    def _record_series(self, exposed: float, overlapped: float):
        """Per-reduce exposed-fraction history, tagged with the group and
        its rendezvous epoch so a resize shows up as a labeled regime
        change in `/api/timeseries` instead of a mystery step."""
        total = exposed + overlapped
        if total <= 0:
            return
        try:
            from ..util import timeseries as _ts

            _ts.register_series(
                _ts.EXPOSED_COLLECTIVE_FRACTION,
                labels={
                    "group": self._group_name,
                    "epoch": str(self._epoch),
                },
            ).record(exposed / total)
        except Exception:
            pass  # telemetry is best-effort; never fail a reduce


class GradientReduceScheduler:
    """Bucketized, overlap-capable gradient allreduce over ANY BaseGroup.

    ``reduce(tree)`` dispatches one async allreduce per bucket and returns a
    :class:`PendingReduce` immediately — call ``.wait()`` after the step's
    remaining compute. ``step(tree)`` is the drop-in loop API honoring
    ``stale_grad``:

    - ``stale_grad=0``: dispatch + wait (still overlapped bucket-to-bucket:
      bucket k reduces while bucket k+1 packs/dispatches); result is
      bit-identical to the synchronous path.
    - ``stale_grad=1``: returns the PREVIOUS step's reduced tree (None on
      the first call) and leaves this step's buckets reducing under the
      next step's forward.

    ``overlap=False`` degrades to eager blocking per-bucket ops (the sync
    A/B baseline) without changing the call surface.
    """

    def __init__(
        self,
        group,
        bucket_bytes: int = DEFAULT_BUCKET_BYTES,
        overlap: bool = True,
        stale_grad: int = 0,
    ):
        if stale_grad not in (0, 1):
            raise ValueError(f"stale_grad must be 0 or 1, got {stale_grad}")
        self.group = group
        self.bucket_bytes = int(bucket_bytes)
        self.overlap = bool(overlap)
        self.stale_grad = int(stale_grad)
        self._bucketizer: Optional[GradientBucketizer] = None
        self._structure_key: Optional[tuple] = None
        self._pending: Optional[PendingReduce] = None

    # -- bucketizer lifecycle ---------------------------------------------

    def _structure_of(self, tree: Any) -> tuple:
        import jax

        leaves, treedef = jax.tree_util.tree_flatten(tree)
        return (
            treedef,
            tuple(
                (tuple(getattr(v, "shape", ())), str(getattr(v, "dtype", "")))
                for v in leaves
            ),
        )

    def bucketizer_for(self, tree: Any) -> GradientBucketizer:
        """The (cached) deterministic assignment for this tree structure;
        rebuilt only when the structure changes — an elastic re-form with
        the same model reuses (or rebuilds identically) the same buckets."""
        key = self._structure_of(tree)
        if self._bucketizer is None or key != self._structure_key:
            self._bucketizer = GradientBucketizer(tree, self.bucket_bytes)
            self._structure_key = key
        return self._bucketizer

    # -- reduce ------------------------------------------------------------

    def reduce(self, tree: Any, op=None) -> PendingReduce:
        """Dispatch every bucket's allreduce without blocking."""
        from .base import ReduceOp

        reduce_op = op if op is not None else ReduceOp.SUM
        bucketizer = self.bucketizer_for(tree)
        handles: List[AsyncHandle] = []
        for flat in bucketizer.pack(tree):
            if self.overlap:
                handles.append(self.group.allreduce_async(flat, reduce_op))
            else:
                t0 = time.perf_counter()
                out = self.group.allreduce(flat, reduce_op)
                handles.append(
                    CompletedHandle(out, time.perf_counter() - t0)
                )
        return PendingReduce(
            handles, bucketizer, self.group.group_name,
            epoch=getattr(self.group, "epoch", 0),
        )

    def step(self, tree: Any) -> Optional[Any]:
        """Loop API: reduced gradients for this step, or — at
        ``stale_grad=1`` — the previous step's (None on the first call,
        where the caller skips the update)."""
        pending = self.reduce(tree)
        if self.stale_grad == 0:
            return pending.wait()
        prev, self._pending = self._pending, pending
        return prev.wait() if prev is not None else None

    def flush(self) -> Optional[Any]:
        """Wait out the delayed tail (the stale_grad pipeline's last step);
        returns its reduced tree, or None if nothing was pending."""
        prev, self._pending = self._pending, None
        return prev.wait() if prev is not None else None
