"""Collective group ABC.

Role-equivalent of the reference's BaseGroup
(util/collective/collective_group/base_collective_group.py:16) with the same
five-op surface plus send/recv/barrier. Backends: the GCS-KV CPU group
(tests, control-plane tensors — the gloo role) and the XLA/ICI group (device
tensors lowering to jax.lax collectives — the NCCL role).
"""

from __future__ import annotations

import enum
from abc import ABC, abstractmethod
from typing import Any, List


class ReduceOp(enum.Enum):
    SUM = "sum"
    PRODUCT = "prod"
    MIN = "min"
    MAX = "max"


class BaseGroup(ABC):
    def __init__(self, world_size: int, rank: int, group_name: str):
        self.world_size = world_size
        self.rank = rank
        self.group_name = group_name

    @abstractmethod
    def allreduce(self, tensor, op: ReduceOp = ReduceOp.SUM):
        ...

    @abstractmethod
    def allgather(self, tensor) -> List[Any]:
        ...

    @abstractmethod
    def reducescatter(self, tensor, op: ReduceOp = ReduceOp.SUM):
        """Input: full tensor on each rank; returns this rank's reduced shard."""

    @abstractmethod
    def broadcast(self, tensor, src_rank: int = 0):
        ...

    @abstractmethod
    def send(self, tensor, dst_rank: int):
        ...

    @abstractmethod
    def recv(self, src_rank: int):
        ...

    @abstractmethod
    def barrier(self):
        ...

    def destroy(self):
        pass
