"""Collective group ABC.

Role-equivalent of the reference's BaseGroup
(util/collective/collective_group/base_collective_group.py:16) with the same
five-op surface plus send/recv/barrier. Backends: the GCS-KV CPU group
(tests, control-plane tensors — the gloo role) and the XLA/ICI group (device
tensors lowering to jax.lax collectives — the NCCL role).
"""

from __future__ import annotations

import enum
import time
from abc import ABC, abstractmethod
from typing import Any, List, Optional


class ReduceOp(enum.Enum):
    SUM = "sum"
    PRODUCT = "prod"
    MIN = "min"
    MAX = "max"


def tensor_nbytes(tensor) -> int:
    """Payload size of a collective operand: numpy/jax arrays expose
    nbytes; arbitrary control-plane objects (cpu allgather) fall back to a
    cheap estimate rather than a serialization pass."""
    nbytes = getattr(tensor, "nbytes", None)
    if nbytes is not None:
        return int(nbytes)
    if isinstance(tensor, (bytes, bytearray, memoryview)):
        return len(tensor)
    if isinstance(tensor, (int, float, bool, complex)):
        return 8
    try:
        import numpy as np

        return int(np.asarray(tensor).nbytes)
    except Exception:
        return 0


class BaseGroup(ABC):
    #: backend tag on every recorded metric ("xla" = ICI fast path,
    #: "gcs_store" = host/control-plane fallback)
    backend = "base"

    def __init__(self, world_size: int, rank: int, group_name: str,
                 epoch: int = 0, quantized: bool = False,
                 quant_block: int = 0):
        self.world_size = world_size
        self.rank = rank
        self.group_name = group_name
        # group epoch: bumped every time the gang re-forms after a member
        # loss (elastic resize). Rendezvous state is epoch-scoped so a
        # re-formed group never reads an aborted epoch's keys, and an abort
        # signal targets every epoch <= its value.
        self.epoch = epoch
        # int8 transport: float payloads of allreduce/allgather/
        # reducescatter ship as per-block int8 + f32 scales
        # (_internal/quantization.py); reductions carry an error-feedback
        # residual per (op, shape, dtype) so the accumulated quantization
        # error stays bounded across rounds. Must be set identically on
        # every member — the wire format is part of the group contract.
        from .._internal.quantization import DEFAULT_BLOCK

        self.quantized = quantized
        self.quant_block = quant_block or DEFAULT_BLOCK
        self._ef_residuals: dict = {}
        self._async_dispatcher = None

    def _record_op(self, op: str, nbytes: int, start: float,
                   wire_nbytes: Optional[int] = None):
        """Record one finished op into the collective bytes/latency/
        bandwidth metrics (util/metrics); ``start`` is the perf_counter
        taken before the op. ``nbytes`` is the logical payload size;
        ``wire_nbytes`` the encoded on-the-wire size when they differ
        (quantized transport) — None means wire == logical."""
        from ..util import metrics

        metrics.record_collective(
            op, self.backend, self.group_name, nbytes,
            time.perf_counter() - start, wire_nbytes=wire_nbytes,
        )

    @abstractmethod
    def allreduce(self, tensor, op: ReduceOp = ReduceOp.SUM):
        ...

    def allreduce_async(self, tensor, op: ReduceOp = ReduceOp.SUM):
        """Dispatch an allreduce without blocking; returns an
        :class:`~ray_tpu.collective.scheduler.AsyncHandle` whose ``wait()``
        yields the reduced tensor (or raises CollectiveAbortedError if the
        group was aborted mid-flight).

        Default implementation runs the blocking ``allreduce`` on the
        group's single background dispatcher thread — FIFO, so every rank's
        async ops hit the rendezvous in submission order and sequence
        numbers stay aligned (the host-backend correctness contract).
        Backends with natively asynchronous dispatch (XLA) override this.
        """
        return self._dispatcher().submit(lambda: self.allreduce(tensor, op))

    def _dispatcher(self):
        if self._async_dispatcher is None:
            from .scheduler import OpDispatcher

            self._async_dispatcher = OpDispatcher(self.group_name)
        return self._async_dispatcher

    def _shutdown_async(self):
        """Stop the background dispatcher, if one was ever started.
        Subclass ``destroy`` overrides don't all chain to super, so group
        teardown paths call this explicitly."""
        if self._async_dispatcher is not None:
            self._async_dispatcher.shutdown()
            self._async_dispatcher = None

    @abstractmethod
    def allgather(self, tensor) -> List[Any]:
        ...

    @abstractmethod
    def reducescatter(self, tensor, op: ReduceOp = ReduceOp.SUM):
        """Input: full tensor on each rank; returns this rank's reduced shard."""

    @abstractmethod
    def broadcast(self, tensor, src_rank: int = 0):
        ...

    @abstractmethod
    def send(self, tensor, dst_rank: int):
        ...

    @abstractmethod
    def recv(self, src_rank: int):
        ...

    @abstractmethod
    def barrier(self):
        ...

    def destroy(self):
        self._shutdown_async()
