"""Host-tensor collective backend over the GCS KV store.

Role-equivalent of the reference's TorchGLOOGroup (util/collective — the CPU
fallback backend): correct, dependency-free collectives for numpy/host
arrays, rendezvoused and transported through the GCS internal KV (the same
rendezvous channel the reference uses for NCCL unique ids,
nccl_collective_group.py:29). Suitable for control-plane payloads and tests,
not the tensor fast path — that's the XLA group.

Protocol: every op gets a monotonically increasing sequence number agreed by
construction order; rank r writes ``col:<group>:<seq>:<phase>:<r>`` and polls
for peers. Keys from finished ops are deleted by rank 0 two ops later.
"""

from __future__ import annotations

import time
from typing import Any, List

import numpy as np

from .. import _worker_api
from .._internal import serialization
from .base import BaseGroup, ReduceOp, tensor_nbytes

_REDUCERS = {
    ReduceOp.SUM: lambda arrs: np.sum(arrs, axis=0),
    ReduceOp.PRODUCT: lambda arrs: np.prod(arrs, axis=0),
    ReduceOp.MIN: lambda arrs: np.min(arrs, axis=0),
    ReduceOp.MAX: lambda arrs: np.max(arrs, axis=0),
}


def _kv_call(method, *args):
    worker = _worker_api.get_core_worker()
    client = worker.client_pool.get(*worker.gcs_address)
    return _worker_api.run_on_worker_loop(client.call(method, *args))


class GcsStoreGroup(BaseGroup):
    backend = "gcs_store"

    def __init__(self, world_size: int, rank: int, group_name: str):
        super().__init__(world_size, rank, group_name)
        self._seq = 0
        # point-to-point ops use per-(src,dst) counters so they don't
        # desynchronize the group-wide collective sequence
        self._p2p_seq = {}

    def _key(self, seq: int, phase: str, rank: int) -> str:
        return f"col:{self.group_name}:{seq}:{phase}:{rank}"

    def _put(self, seq: int, phase: str, value: Any):
        _kv_call("kv_put", self._key(seq, phase, self.rank),
                 serialization.pack(value), True)

    def _get_blocking(self, seq: int, phase: str, rank: int, timeout=120.0):
        key = self._key(seq, phase, rank)
        deadline = time.time() + timeout
        delay = 0.002
        while time.time() < deadline:
            raw = _kv_call("kv_get", key)
            if raw is not None:
                return serialization.unpack(raw)
            time.sleep(delay)
            delay = min(delay * 1.5, 0.1)
        raise TimeoutError(f"collective {self.group_name} seq={seq} rank={rank}")

    def _gather_all(self, seq: int, phase: str) -> List[Any]:
        return [
            self._get_blocking(seq, phase, r) for r in range(self.world_size)
        ]

    def _cleanup(self, seq: int):
        if self.rank == 0 and seq >= 2:
            old = seq - 2
            for phase in ("d", "s"):
                for r in range(self.world_size):
                    _kv_call("kv_del", self._key(old, phase, r))

    def _next_seq(self) -> int:
        seq = self._seq
        self._seq += 1
        self._cleanup(seq)
        return seq

    # -- ops ---------------------------------------------------------------

    def _allreduce_impl(self, tensor, op: ReduceOp):
        seq = self._next_seq()
        arr = np.asarray(tensor)
        self._put(seq, "d", arr)
        return _REDUCERS[op](self._gather_all(seq, "d"))

    def allreduce(self, tensor, op: ReduceOp = ReduceOp.SUM):
        start = time.perf_counter()
        out = self._allreduce_impl(tensor, op)
        self._record_op("allreduce", tensor_nbytes(out), start)
        return out

    def allgather(self, tensor) -> List[Any]:
        # arbitrary python objects allowed (control-plane data), not just
        # tensors — objects round-trip unchanged
        start = time.perf_counter()
        seq = self._next_seq()
        self._put(seq, "d", tensor)
        out = self._gather_all(seq, "d")
        self._record_op("allgather", tensor_nbytes(tensor), start)
        return out

    def reducescatter(self, tensor, op: ReduceOp = ReduceOp.SUM):
        start = time.perf_counter()
        # inner impl, not allreduce(): one op records one metric sample
        reduced = self._allreduce_impl(tensor, op)
        shards = np.array_split(reduced, self.world_size, axis=0)
        out = shards[self.rank]
        self._record_op("reducescatter", tensor_nbytes(reduced), start)
        return out

    def broadcast(self, tensor, src_rank: int = 0):
        # The src must not return until every receiver has read the payload:
        # rank 0's _cleanup(seq-2) assumes all ranks completed seq-2, which
        # gather-style ops guarantee but a fire-and-forget broadcast would
        # not — a racing src could let cleanup delete a payload a slow rank
        # never read. The ack phase makes broadcast synchronizing.
        start = time.perf_counter()
        seq = self._next_seq()
        if self.rank == src_rank:
            self._put(seq, "d", tensor)
            out = tensor
        else:
            out = self._get_blocking(seq, "d", src_rank)
        self._put(seq, "s", 1)
        self._gather_all(seq, "s")
        self._record_op("broadcast", tensor_nbytes(out), start)
        return out

    def _p2p_key(self, src: int, dst: int) -> tuple:
        n = self._p2p_seq.get((src, dst), 0)
        self._p2p_seq[(src, dst)] = n + 1
        return n

    def send(self, tensor, dst_rank: int):
        start = time.perf_counter()
        n = self._p2p_key(self.rank, dst_rank)
        key = f"col:{self.group_name}:p2p:{self.rank}:{dst_rank}:{n}"
        _kv_call("kv_put", key, serialization.pack(tensor), True)
        self._record_op("send", tensor_nbytes(tensor), start)

    def recv(self, src_rank: int):
        start = time.perf_counter()
        n = self._p2p_key(src_rank, self.rank)
        key = f"col:{self.group_name}:p2p:{src_rank}:{self.rank}:{n}"
        deadline = time.time() + 120.0
        delay = 0.002
        while time.time() < deadline:
            raw = _kv_call("kv_get", key)
            if raw is not None:
                _kv_call("kv_del", key)
                out = serialization.unpack(raw)
                self._record_op("recv", len(raw), start)
                return out
            time.sleep(delay)
            delay = min(delay * 1.5, 0.1)
        raise TimeoutError(
            f"recv from rank {src_rank} in group {self.group_name}"
        )

    def barrier(self):
        start = time.perf_counter()
        seq = self._next_seq()
        self._put(seq, "s", 1)
        self._gather_all(seq, "s")
        self._record_op("barrier", 0, start)

    def destroy(self):
        for seq in range(max(0, self._seq - 2), self._seq):
            for phase in ("d", "s"):
                for r in range(self.world_size):
                    try:
                        _kv_call("kv_del", self._key(seq, phase, r))
                    except Exception:
                        pass
