"""Host-tensor collective backend over the GCS KV store.

Role-equivalent of the reference's TorchGLOOGroup (util/collective — the CPU
fallback backend): correct, dependency-free collectives for numpy/host
arrays, rendezvoused and transported through the GCS internal KV (the same
rendezvous channel the reference uses for NCCL unique ids,
nccl_collective_group.py:29). Suitable for control-plane payloads and tests,
not the tensor fast path — that's the XLA group.

Protocol: every op gets a monotonically increasing sequence number agreed by
construction order; rank r writes ``col:<group>:<epoch>:<seq>:<phase>:<r>``
and polls for peers. Keys from finished ops are deleted by rank 0 two ops
later.

Abort plane: each member registers ``colmember:<group>:<epoch>:<rank>`` with
its worker/node identity at init. When any member dies, the GCS death path
(report_worker_death / node-death) — or the controller explicitly — writes
``colabort:<group>`` holding the aborted epoch as an ascii int. Every
blocking poll loop checks that key at ~0.25 s cadence, so survivors stuck in
an allreduce raise :class:`CollectiveAbortedError` within ~1 s of the death
instead of burning the full rendezvous timeout. The re-formed gang comes
back at a higher epoch, whose keys the abort does not poison; rank 0 sweeps
the dead epochs' leaked rendezvous keys at init.
"""

from __future__ import annotations

import json
import time
from typing import Any, List, Optional

import numpy as np

from .. import _worker_api
from .._internal import serialization
from .._internal.quantization import (
    QuantizedArray,
    dequantize_np,
    ef_quantize_np,
    is_quantizable,
    quantize_np,
)
from ..exceptions import CollectiveAbortedError
from ..runtime.gcs import keys as gcs_keys
from .base import BaseGroup, ReduceOp, tensor_nbytes

_REDUCERS = {
    ReduceOp.SUM: lambda arrs: np.sum(arrs, axis=0),
    ReduceOp.PRODUCT: lambda arrs: np.prod(arrs, axis=0),
    ReduceOp.MIN: lambda arrs: np.min(arrs, axis=0),
    ReduceOp.MAX: lambda arrs: np.max(arrs, axis=0),
}

#: how often a blocking poll re-reads the abort key (the bound on how long a
#: survivor keeps spinning after a member death is roughly this + one GCS RTT)
_ABORT_CHECK_INTERVAL_S = 0.25
#: how long a read of the chaos delay key is trusted before re-reading
_DELAY_TTL_S = 2.0


def _kv_call(method, *args):
    worker = _worker_api.get_core_worker()
    client = worker.client_pool.get(*worker.gcs_address)
    return _worker_api.run_on_worker_loop(client.call(method, *args))


def abort_key(group_name: str) -> str:
    return gcs_keys.COLLECTIVE_ABORT.key(group_name)


def member_key(group_name: str, epoch: int, rank: int) -> str:
    return gcs_keys.COLLECTIVE_MEMBER.key(group_name, epoch, rank)


def read_abort_epoch(group_name: str) -> int:
    """Latest aborted epoch for the group, or -1 if never aborted."""
    raw = _kv_call("kv_get", abort_key(group_name))
    if raw is None:
        return -1
    try:
        return int(bytes(raw).decode())
    except (ValueError, UnicodeDecodeError):
        return -1


def write_abort(group_name: str, epoch: int, reason: str = "") -> bool:
    """Abort every collective epoch <= ``epoch`` of the group. Monotonic:
    never lowers an already-written abort epoch. Returns True if this call
    advanced the abort mark."""
    if read_abort_epoch(group_name) >= epoch:
        return False
    _kv_call("kv_put", abort_key(group_name), str(epoch).encode(), True)
    return True


class GcsStoreGroup(BaseGroup):
    backend = "gcs_store"

    def __init__(self, world_size: int, rank: int, group_name: str, *,
                 epoch: int = 0, quantized: bool = False,
                 quant_block: int = 0, parent_group: Optional[str] = None):
        super().__init__(world_size, rank, group_name, epoch=epoch,
                         quantized=quantized, quant_block=quant_block)
        # sub-groups of a HierarchicalGroup also honor the PARENT's abort
        # key: an abort targets the logical group name the controller knows,
        # and must unblock members stuck in any constituent sub-group poll
        self._parent_group = parent_group
        self._seq = 0
        # point-to-point ops use per-(src,dst) counters so they don't
        # desynchronize the group-wide collective sequence
        self._p2p_seq = {}
        self._aborted = False
        self._last_abort_check = 0.0
        self._delay_read_at = 0.0
        self._delay_s = 0.0
        if _worker_api.is_initialized():
            self._register_member()
            if rank == 0:
                self._sweep_stale_epochs()

    # -- abort plane -------------------------------------------------------

    def _register_member(self):
        """Advertise this member's worker/node identity so the GCS death
        path can abort the group when the process or its node dies."""
        try:
            from ..runtime_context import get_runtime_context

            rc = get_runtime_context()
            payload = json.dumps(
                {"worker_id": rc.get_worker_id(), "node_id": rc.get_node_id()}
            ).encode()
            _kv_call(
                "kv_put", member_key(self.group_name, self.epoch, self.rank),
                payload, True,
            )
        except Exception:
            # membership is an optimization (fast abort); a failed
            # registration must not fail group construction
            pass

    def _sweep_stale_epochs(self):
        """Delete rendezvous/member keys left behind by dead epochs of this
        group — aborted ops never reach the happy-path cleanup, so without
        this sweep every abnormal exit leaks its in-flight keys forever."""
        try:
            for prefix in (gcs_keys.COLLECTIVE.key(self.group_name) + ":",
                           gcs_keys.COLLECTIVE_MEMBER.key(self.group_name) + ":"):
                for key in _kv_call("kv_keys", prefix) or []:
                    head = key[len(prefix):].split(":", 1)[0]
                    try:
                        key_epoch = int(head)
                    except ValueError:
                        # not this group's key (e.g. a sibling group whose
                        # name extends ours, like "<group>:host")
                        continue
                    if key_epoch < self.epoch:
                        _kv_call("kv_del", key)
        except Exception:
            pass

    def _raise_aborted(self):
        self._aborted = True
        from ..util import metrics

        metrics.record_collective_abort(self.group_name)
        raise CollectiveAbortedError(self.group_name, self.epoch)

    def _check_abort(self, force: bool = False):
        """Raise CollectiveAbortedError if this epoch has been aborted.
        Rate-limited to one KV read per _ABORT_CHECK_INTERVAL_S unless
        forced; an aborted group stays poisoned (fails fast forever)."""
        if self._aborted:
            raise CollectiveAbortedError(self.group_name, self.epoch)
        # fence check FIRST (a process-local flag, no KV read): a fenced
        # node's member can't reach the abort key anyway — blocking on the
        # rate-limited KV poll would just burn the rendezvous timeout
        from ..util import fencing

        if fencing.is_fenced():
            self._raise_aborted()
        now = time.monotonic()
        if not force and now - self._last_abort_check < _ABORT_CHECK_INTERVAL_S:
            return
        self._last_abort_check = now
        if read_abort_epoch(self.group_name) >= self.epoch:
            self._raise_aborted()
        if (
            self._parent_group is not None
            and read_abort_epoch(self._parent_group) >= self.epoch
        ):
            self._raise_aborted()

    def _maybe_delay(self):
        """Chaos hook: ``coldelay:<group>`` holds an ascii float; every op
        start sleeps that long. Cached so the hot path adds one KV read per
        _DELAY_TTL_S, not per op."""
        now = time.monotonic()
        if now - self._delay_read_at >= _DELAY_TTL_S:
            self._delay_read_at = now
            raw = _kv_call(
                "kv_get", gcs_keys.COLLECTIVE_DELAY.key(self.group_name)
            )
            try:
                self._delay_s = float(bytes(raw).decode()) if raw else 0.0
            except (ValueError, UnicodeDecodeError):
                self._delay_s = 0.0
        if self._delay_s > 0:
            time.sleep(self._delay_s)

    # -- rendezvous --------------------------------------------------------

    def _key(self, seq: int, phase: str, rank: int) -> str:
        return gcs_keys.COLLECTIVE.key(
            self.group_name, self.epoch, seq, phase, rank
        )

    def _put(self, seq: int, phase: str, value: Any):
        _kv_call("kv_put", self._key(seq, phase, self.rank),
                 serialization.pack(value), True)

    def _get_blocking(self, seq: int, phase: str, rank: int, timeout=120.0):
        key = self._key(seq, phase, rank)
        deadline = time.time() + timeout
        delay = 0.002
        while time.time() < deadline:
            raw = _kv_call("kv_get", key)
            if raw is not None:
                return serialization.unpack(raw)
            self._check_abort()
            time.sleep(delay)
            delay = min(delay * 1.5, 0.1)
        raise TimeoutError(f"collective {self.group_name} seq={seq} rank={rank}")

    def _gather_all(self, seq: int, phase: str) -> List[Any]:
        return [
            self._get_blocking(seq, phase, r) for r in range(self.world_size)
        ]

    def _cleanup(self, seq: int):
        if self.rank == 0 and seq >= 2:
            old = seq - 2
            for phase in ("d", "s"):
                for r in range(self.world_size):
                    _kv_call("kv_del", self._key(old, phase, r))

    def _next_seq(self) -> int:
        self._check_abort()
        self._maybe_delay()
        seq = self._seq
        self._seq += 1
        self._cleanup(seq)
        return seq

    # -- ops ---------------------------------------------------------------

    def _allreduce_impl(self, tensor, op: ReduceOp, ef_op: str = ""):
        """Exchange + reduce; returns (reduced, wire_nbytes) where
        wire_nbytes is None on the full-width path. Quantized mode ships
        float payloads as int8+scales and reduces over the dequantized
        f32 contributions; SUM additionally carries the error-feedback
        residual (keyed per op/shape/dtype) into the next round so the
        accumulated error stays bounded — MIN/MAX/PRODUCT are order
        statistics/products where additive compensation is meaningless,
        so they quantize without feedback."""
        seq = self._next_seq()
        arr = np.asarray(tensor)
        if self.quantized and is_quantizable(arr):
            if op is ReduceOp.SUM and ef_op:
                key = (ef_op, arr.shape, str(arr.dtype))
                qa, self._ef_residuals[key] = ef_quantize_np(
                    arr, self._ef_residuals.get(key), self.quant_block
                )
            else:
                qa = quantize_np(arr, self.quant_block)
            self._put(seq, "d", qa)
            gathered = [
                dequantize_np(v, dtype="float32")
                if isinstance(v, QuantizedArray) else np.asarray(v)
                for v in self._gather_all(seq, "d")
            ]
            return _REDUCERS[op](gathered).astype(arr.dtype), qa.wire_nbytes
        self._put(seq, "d", arr)
        return _REDUCERS[op](self._gather_all(seq, "d")), None

    def allreduce(self, tensor, op: ReduceOp = ReduceOp.SUM):
        start = time.perf_counter()
        out, wire = self._allreduce_impl(tensor, op, ef_op="allreduce")
        self._record_op("allreduce", tensor_nbytes(out), start,
                        wire_nbytes=wire)
        return out

    def allgather(self, tensor) -> List[Any]:
        # arbitrary python objects allowed (control-plane data), not just
        # tensors — objects round-trip unchanged. Quantized mode encodes
        # float arrays (no error feedback: allgather replicates values,
        # nothing accumulates) and decodes every gathered entry.
        start = time.perf_counter()
        seq = self._next_seq()
        wire = None
        payload = tensor
        if self.quantized and is_quantizable(tensor):
            payload = quantize_np(np.asarray(tensor), self.quant_block)
            wire = payload.wire_nbytes
        self._put(seq, "d", payload)
        out = [
            dequantize_np(v) if isinstance(v, QuantizedArray) else v
            for v in self._gather_all(seq, "d")
        ]
        self._record_op("allgather", tensor_nbytes(tensor), start,
                        wire_nbytes=wire)
        return out

    def reducescatter(self, tensor, op: ReduceOp = ReduceOp.SUM):
        start = time.perf_counter()
        # inner impl, not allreduce(): one op records one metric sample
        reduced, wire = self._allreduce_impl(
            tensor, op, ef_op="reducescatter"
        )
        shards = np.array_split(reduced, self.world_size, axis=0)
        out = shards[self.rank]
        self._record_op("reducescatter", tensor_nbytes(reduced), start,
                        wire_nbytes=wire)
        return out

    def broadcast(self, tensor, src_rank: int = 0):
        # The src must not return until every receiver has read the payload:
        # rank 0's _cleanup(seq-2) assumes all ranks completed seq-2, which
        # gather-style ops guarantee but a fire-and-forget broadcast would
        # not — a racing src could let cleanup delete a payload a slow rank
        # never read. The ack phase makes broadcast synchronizing.
        start = time.perf_counter()
        seq = self._next_seq()
        if self.rank == src_rank:
            self._put(seq, "d", tensor)
            out = tensor
        else:
            out = self._get_blocking(seq, "d", src_rank)
        self._put(seq, "s", 1)
        self._gather_all(seq, "s")
        self._record_op("broadcast", tensor_nbytes(out), start)
        return out

    def _p2p_key(self, src: int, dst: int) -> tuple:
        n = self._p2p_seq.get((src, dst), 0)
        self._p2p_seq[(src, dst)] = n + 1
        return n

    def send(self, tensor, dst_rank: int):
        self._check_abort()
        start = time.perf_counter()
        n = self._p2p_key(self.rank, dst_rank)
        key = gcs_keys.COLLECTIVE.key(
            self.group_name, self.epoch, "p2p", self.rank, dst_rank, n
        )
        _kv_call("kv_put", key, serialization.pack(tensor), True)
        self._record_op("send", tensor_nbytes(tensor), start)

    def recv(self, src_rank: int):
        self._check_abort()
        start = time.perf_counter()
        n = self._p2p_key(src_rank, self.rank)
        key = gcs_keys.COLLECTIVE.key(
            self.group_name, self.epoch, "p2p", src_rank, self.rank, n
        )
        deadline = time.time() + 120.0
        delay = 0.002
        while time.time() < deadline:
            raw = _kv_call("kv_get", key)
            if raw is not None:
                _kv_call("kv_del", key)
                out = serialization.unpack(raw)
                self._record_op("recv", len(raw), start)
                return out
            self._check_abort()
            time.sleep(delay)
            delay = min(delay * 1.5, 0.1)
        raise TimeoutError(
            f"recv from rank {src_rank} in group {self.group_name}"
        )

    def barrier(self):
        start = time.perf_counter()
        seq = self._next_seq()
        self._put(seq, "s", 1)
        self._gather_all(seq, "s")
        self._record_op("barrier", 0, start)

    def destroy(self):
        self._shutdown_async()
        try:
            _kv_call(
                "kv_del", member_key(self.group_name, self.epoch, self.rank)
            )
        except Exception:
            pass
        if self.rank == 0:
            # full-epoch sweep (covers keys the seq-window cleanup missed,
            # including p2p counters and abort leftovers)
            try:
                for key in _kv_call(
                    "kv_keys",
                    gcs_keys.COLLECTIVE.key(self.group_name, self.epoch) + ":",
                ) or []:
                    _kv_call("kv_del", key)
                return
            except Exception:
                pass
        for seq in range(max(0, self._seq - 2), self._seq):
            for phase in ("d", "s"):
                for r in range(self.world_size):
                    try:
                        _kv_call("kv_del", self._key(seq, phase, r))
                    except Exception:
                        pass
