"""XLA/ICI collective backend — the tensor fast path.

Role-equivalent of the reference's NCCLGroup
(util/collective/collective_group/nccl_collective_group.py:121), redesigned
for TPU: instead of NCCL communicators, ops lower to XLA collectives
(jax.lax.psum / all_gather / psum_scatter / ppermute) over ICI.

Two regimes:

1. **In-graph (preferred)**: training code runs under jit on a Mesh; the
   "collective" is just the lax op and XLA schedules it on ICI. This class's
   static helpers expose that surface for shard_map code.

2. **Out-of-graph**: `allreduce(array)` etc. called between jit programs,
   matching the reference's eager `col.allreduce(tensor, group)` API. Within
   one process the ops run as a jitted shard_map over this host's devices.
   Across hosts the group bootstraps the jax.distributed runtime — the
   coordinator address rendezvouses through the GCS KV, mirroring the NCCL
   unique-id flow (nccl_collective_group.py:29) — after which jax sees the
   global device set and the same jitted collectives span hosts over ICI/DCN.
"""

from __future__ import annotations

import time
from functools import partial
from typing import Any, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..runtime.gcs import keys as gcs_keys
from .base import BaseGroup, ReduceOp, tensor_nbytes
from .._internal.jax_compat import shard_map
from .._internal.quantization import (
    dequantize_jax,
    quantize_jax,
    quantized_wire_nbytes,
)

_LAX_REDUCERS = {
    ReduceOp.SUM: jax.lax.psum,
    ReduceOp.MAX: jax.lax.pmax,
    ReduceOp.MIN: jax.lax.pmin,
    # PRODUCT deliberately absent: XLA has no pprod collective
}


def _rendezvous_coordinator(group_name: str, rank: int, world_size: int,
                            timeout: float = 60.0) -> Optional[str]:
    """Agree on a jax.distributed coordinator address through the GCS KV
    (reference: NCCL unique-id rendezvous through internal KV)."""
    from .. import _worker_api

    if not _worker_api.is_initialized():
        return None
    worker = _worker_api.get_core_worker()
    client = worker.client_pool.get(*worker.gcs_address)
    key = gcs_keys.XLA_COORD.key(group_name)
    if rank == 0:
        import socket

        host = socket.gethostbyname(socket.gethostname())
        # deterministic port per group in the dynamic range (stable_hash:
        # builtin hash() is per-process randomized, ranks would disagree)
        from .._internal.hashing import stable_hash

        port = 20000 + (stable_hash(group_name) % 20000)
        addr = f"{host}:{port}"
        _worker_api.run_on_worker_loop(client.call("kv_put", key, addr.encode(), True))
        return addr
    deadline = time.time() + timeout
    while time.time() < deadline:
        raw = _worker_api.run_on_worker_loop(client.call("kv_get", key))
        if raw:
            return raw.decode()
        time.sleep(0.05)
    raise TimeoutError(f"no coordinator for group {group_name}")


class XlaGroup(BaseGroup):
    """Out-of-graph collective group over this process's jax devices (and,
    multi-host, the global device set after jax.distributed bootstrap)."""

    def __init__(
        self,
        world_size: int,
        rank: int,
        group_name: str,
        *,
        bootstrap_distributed: bool = False,
        devices: Optional[List] = None,
        epoch: int = 0,
        quantized: bool = False,
        quant_block: int = 0,
    ):
        super().__init__(world_size, rank, group_name, epoch=epoch,
                         quantized=quantized, quant_block=quant_block)
        self._host = None
        if bootstrap_distributed and world_size > 1:
            coord = _rendezvous_coordinator(group_name, rank, world_size)
            jax.distributed.initialize(
                coordinator_address=coord,
                num_processes=world_size,
                process_id=rank,
            )
        elif world_size > 1 and jax.process_count() < world_size:
            # without the distributed runtime each process would reduce over
            # its local devices only — numerically wrong results with no
            # error. Refuse instead.
            raise ValueError(
                f"XlaGroup world_size={world_size} but this jax runtime spans "
                f"{jax.process_count()} process(es); pass "
                f"bootstrap_distributed=True (or bootstrap jax.distributed "
                f"yourself) so collectives span all ranks"
            )
        self.devices = list(devices if devices is not None else jax.devices())
        self.mesh = Mesh(np.array(self.devices), ("g",))
        n = len(self.devices)

        spec = P("g")
        rep = P()

        @partial(jax.jit, static_argnums=(1,))
        def _reduce(x, op_name):
            fn = {
                "sum": jax.lax.psum, "max": jax.lax.pmax, "min": jax.lax.pmin,
            }[op_name]
            return shard_map(
                lambda s: fn(s, "g"),
                mesh=self.mesh, in_specs=spec, out_specs=rep, check_vma=False,
            )(x)

        self._reduce = _reduce

        @jax.jit
        def _allgather(x):
            return shard_map(
                lambda s: jax.lax.all_gather(s, "g", axis=0, tiled=True),
                mesh=self.mesh, in_specs=spec, out_specs=rep, check_vma=False,
            )(x)

        self._allgather = _allgather

        @jax.jit
        def _reducescatter(x):
            return shard_map(
                lambda s: jax.lax.psum_scatter(s, "g", scatter_dimension=0, tiled=True),
                mesh=self.mesh, in_specs=rep, out_specs=spec, check_vma=False,
            )(x)

        self._reducescatter = _reducescatter

        # -- quantized programs (EQuARX-style): quantize → exchange int8 +
        # scales → dequantize → reduce is ONE jitted computation per input
        # aval — the compressed payload is what crosses ICI, and nothing
        # round-trips through the host between the encode and the reduce.
        # The error-feedback residual rides as a device-array input/output
        # of the same program (f32, sharded like the operand), so carrying
        # it costs no extra transfer either.
        block = self.quant_block

        @jax.jit
        def _qallreduce(x, residual):
            def body(s, r):
                comp = s.astype(jnp.float32) + r
                q, scales = quantize_jax(comp, block)
                qg = jax.lax.all_gather(q, "g")
                sg = jax.lax.all_gather(scales, "g")
                total = dequantize_jax(
                    qg, sg, comp.shape, jnp.float32
                ).sum(axis=0)
                own = dequantize_jax(q, scales, comp.shape, jnp.float32)
                return total.astype(s.dtype), comp - own

            return shard_map(
                body, mesh=self.mesh, in_specs=(spec, spec),
                out_specs=(rep, spec), check_vma=False,
            )(x, residual)

        self._qallreduce = _qallreduce

        @jax.jit
        def _qallgather(x):
            def body(s):
                q, scales = quantize_jax(s, block)
                qg = jax.lax.all_gather(q, "g")
                sg = jax.lax.all_gather(scales, "g")
                out = dequantize_jax(qg, sg, s.shape, s.dtype)
                # tiled concat along the shard axis, like the fp program
                return out.reshape((-1,) + s.shape[1:])

            return shard_map(
                body, mesh=self.mesh, in_specs=spec, out_specs=rep,
                check_vma=False,
            )(x)

        self._qallgather = _qallgather

        @jax.jit
        def _qreducescatter(x, residual):
            def body(xfull, r):
                comp = xfull.astype(jnp.float32) + r
                q, scales = quantize_jax(comp, block)
                qg = jax.lax.all_gather(q, "g")
                sg = jax.lax.all_gather(scales, "g")
                total = dequantize_jax(
                    qg, sg, comp.shape, jnp.float32
                ).sum(axis=0)
                own = dequantize_jax(q, scales, comp.shape, jnp.float32)
                idx = jax.lax.axis_index("g")
                shard_len = total.shape[0] // n
                shard = jax.lax.dynamic_slice_in_dim(
                    total, idx * shard_len, shard_len, 0
                )
                return shard.astype(xfull.dtype), comp - own

            return shard_map(
                body, mesh=self.mesh, in_specs=(rep, rep),
                out_specs=(spec, rep), check_vma=False,
            )(x, residual)

        self._qreducescatter = _qreducescatter

    def _device_shard(self, tensor):
        """Shard a host array over the group axis (leading dim)."""
        return jax.device_put(tensor, NamedSharding(self.mesh, P("g")))

    backend = "xla"

    def _timed(self, op_name: str, tensor, fn, wire_nbytes=None):
        """Run an eager collective under the bytes/latency instrumentation;
        block_until_ready so the recorded latency covers the ICI transfer,
        not just the async dispatch (the eager surface is synchronizing
        anyway — in-graph lax collectives stay untouched)."""
        start = time.perf_counter()
        out = jax.block_until_ready(fn())
        self._record_op(op_name, tensor_nbytes(tensor), start,
                        wire_nbytes=wire_nbytes)
        return out

    def _use_quantized(self, x, op: Optional[ReduceOp] = None) -> bool:
        """Quantized transport applies to float operands; reductions only
        for SUM (MIN/MAX order statistics have no meaningful additive
        error feedback, and their fp programs stay exact)."""
        from .._internal.quantization import is_quantizable

        return (
            self.quantized
            and is_quantizable(x)
            and (op is None or op is ReduceOp.SUM)
        )

    def _residual_for(self, op_name: str, x, replicated: bool = False):
        """The carried error-feedback residual for this (op, aval) —
        an f32 device array born zero, sharded like the operand so the
        jitted program consumes it without a relayout."""
        key = (op_name, tuple(x.shape), str(x.dtype))
        res = self._ef_residuals.get(key)
        if res is None or res.shape != x.shape:
            res = jax.device_put(
                jnp.zeros(x.shape, jnp.float32),
                NamedSharding(self.mesh, P() if replicated else P("g")),
            )
        return key, res

    def allreduce(self, tensor, op: ReduceOp = ReduceOp.SUM):
        # each device's shard is summed: for the eager API the input is the
        # per-rank contribution replicated per device slot
        if op == ReduceOp.PRODUCT:
            raise NotImplementedError(
                "PRODUCT has no XLA collective; use the cpu backend"
            )
        x = self._device_shard(tensor)
        if self._use_quantized(x, op):
            key, res = self._residual_for("allreduce", x)

            def run():
                out, self._ef_residuals[key] = self._qallreduce(x, res)
                return out

            return self._timed(
                "allreduce", x, run,
                wire_nbytes=quantized_wire_nbytes(x.size, self.quant_block),
            )
        return self._timed("allreduce", x, lambda: self._reduce(x, op.value))

    def allreduce_async(self, tensor, op: ReduceOp = ReduceOp.SUM):
        """Dispatch-without-block: launch the jitted (possibly quantized)
        reduce program and hand back the not-yet-ready device array. jit
        dispatch is asynchronous, so no helper thread is needed — the
        program runs on the device stream while the caller keeps going;
        the handle's ``wait`` is block_until_ready. Metrics for the op are
        recorded at completion (on_ready), not dispatch."""
        from .scheduler import DeviceHandle

        if op == ReduceOp.PRODUCT:
            raise NotImplementedError(
                "PRODUCT has no XLA collective; use the cpu backend"
            )
        x = self._device_shard(tensor)
        nbytes = tensor_nbytes(x)
        if self._use_quantized(x, op):
            key, res = self._residual_for("allreduce", x)
            out, self._ef_residuals[key] = self._qallreduce(x, res)
            wire = quantized_wire_nbytes(x.size, self.quant_block)
        else:
            out = self._reduce(x, op.value)
            wire = None

        def on_ready(latency_s: float):
            from ..util import metrics

            metrics.record_collective(
                "allreduce", self.backend, self.group_name, nbytes,
                latency_s, wire_nbytes=wire,
            )

        return DeviceHandle(out, on_ready=on_ready)

    def allgather(self, tensor) -> Any:
        x = self._device_shard(tensor)
        if self._use_quantized(x):
            return self._timed(
                "allgather", x, lambda: self._qallgather(x),
                wire_nbytes=quantized_wire_nbytes(x.size, self.quant_block),
            )
        return self._timed("allgather", x, lambda: self._allgather(x))

    def reducescatter(self, tensor, op: ReduceOp = ReduceOp.SUM):
        if op != ReduceOp.SUM:
            raise NotImplementedError(
                "XLA psum_scatter only reduces with SUM; use the cpu backend"
            )
        x = jnp.asarray(tensor)
        if self._use_quantized(x, op) and x.shape[0] % len(self.devices) == 0:
            key, res = self._residual_for(
                "reducescatter", x, replicated=True
            )

            def run():
                out, self._ef_residuals[key] = self._qreducescatter(x, res)
                return out

            return self._timed(
                "reducescatter", x, run,
                wire_nbytes=quantized_wire_nbytes(x.size, self.quant_block),
            )
        return self._timed("reducescatter", x, lambda: self._reducescatter(x))

    def _host_group(self):
        # host-side control ops (broadcast/send/recv across processes)
        # delegate to the GCS-KV backend; device meshes have no eager
        # cross-process point-to-point path
        if self._host is None:
            from .cpu_group import GcsStoreGroup

            self._host = GcsStoreGroup(
                self.world_size, self.rank, f"{self.group_name}:host",
                epoch=self.epoch,
            )
        return self._host

    def broadcast(self, tensor, src_rank: int = 0):
        if self.world_size == 1:
            return jax.device_put(tensor, NamedSharding(self.mesh, P()))
        start = time.perf_counter()
        value = self._host_group().broadcast(tensor, src_rank)
        out = jax.device_put(value, NamedSharding(self.mesh, P()))
        self._record_op("broadcast", tensor_nbytes(out), start)
        return out

    def send(self, tensor, dst_rank: int):
        if self.world_size == 1:
            raise ValueError("send in a single-process group has no peer")
        return self._host_group().send(tensor, dst_rank)

    def recv(self, src_rank: int):
        if self.world_size == 1:
            raise ValueError("recv in a single-process group has no peer")
        return self._host_group().recv(src_rank)

    def barrier(self):
        start = time.perf_counter()
        x = jnp.zeros((len(self.devices),), jnp.int32)
        jax.block_until_ready(self._reduce(self._device_shard(x), "sum"))
        self._record_op("barrier", 0, start)

    def destroy(self):
        self._shutdown_async()
        if self._host is not None:
            self._host.destroy()
            self._host = None

    # -- in-graph surface (use inside shard_map/jit) ------------------------

    @staticmethod
    def lax_allreduce(x, axis_name: str, op: ReduceOp = ReduceOp.SUM):
        fn = _LAX_REDUCERS.get(op)
        if fn is None:
            raise NotImplementedError(f"{op} has no XLA collective")
        return fn(x, axis_name)

    @staticmethod
    def lax_allgather(x, axis_name: str, axis: int = 0):
        return jax.lax.all_gather(x, axis_name, axis=axis, tiled=True)

    @staticmethod
    def lax_reducescatter(x, axis_name: str, axis: int = 0):
        return jax.lax.psum_scatter(x, axis_name, scatter_dimension=axis, tiled=True)

    @staticmethod
    def lax_ppermute(x, axis_name: str, perm):
        return jax.lax.ppermute(x, axis_name, perm)

    @staticmethod
    def lax_all_to_all(x, axis_name: str, split_axis: int, concat_axis: int):
        return jax.lax.all_to_all(
            x, axis_name, split_axis=split_axis, concat_axis=concat_axis, tiled=True
        )
