"""Job submission SDK.

Role-equivalent of the reference's JobSubmissionClient
(python/ray/dashboard/modules/job/sdk.py): a thin HTTP client against the
dashboard's job REST endpoints. The entrypoint runs as a driver subprocess
on the head with RAY_TPU_ADDRESS set, exactly like `ray job submit`.
"""

from __future__ import annotations

import json
import time
import urllib.error
import urllib.request
from typing import Any, Dict, List, Optional

from .dashboard.job_manager import JobStatus

__all__ = ["JobSubmissionClient", "JobStatus"]


class JobSubmissionClient:
    def __init__(self, address: str):
        """``address`` is the dashboard URL, e.g. http://127.0.0.1:8265."""
        self._base = address.rstrip("/")

    def _request(self, verb: str, path: str, body: Optional[dict] = None):
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(
            self._base + path,
            data=data,
            method=verb,
            headers={"Content-Type": "application/json"},
        )
        try:
            with urllib.request.urlopen(req, timeout=30.0) as resp:
                return json.loads(resp.read())
        except urllib.error.HTTPError as e:
            detail = e.read().decode(errors="replace")
            raise RuntimeError(f"{verb} {path} -> {e.code}: {detail}") from None

    def submit_job(
        self,
        *,
        entrypoint: str,
        submission_id: Optional[str] = None,
        runtime_env: Optional[dict] = None,
        metadata: Optional[dict] = None,
    ) -> str:
        reply = self._request(
            "POST",
            "/api/jobs",
            {
                "entrypoint": entrypoint,
                "submission_id": submission_id,
                "runtime_env": runtime_env,
                "metadata": metadata,
            },
        )
        return reply["submission_id"]

    def get_job_status(self, submission_id: str) -> str:
        return self._request("GET", f"/api/jobs/{submission_id}")["status"]

    def get_job_info(self, submission_id: str) -> Dict[str, Any]:
        return self._request("GET", f"/api/jobs/{submission_id}")

    def get_job_logs(self, submission_id: str) -> str:
        return self._request("GET", f"/api/jobs/{submission_id}/logs")["logs"]

    def stop_job(self, submission_id: str) -> bool:
        return self._request("POST", f"/api/jobs/{submission_id}/stop")["stopped"]

    def list_jobs(self) -> List[Dict[str, Any]]:
        return self._request("GET", "/api/jobs")

    def wait_until_finished(
        self, submission_id: str, timeout: float = 300.0, poll_s: float = 0.5
    ) -> str:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            status = self.get_job_status(submission_id)
            if status in JobStatus.TERMINAL:
                return status
            time.sleep(poll_s)
        raise TimeoutError(f"job {submission_id} still running after {timeout}s")
