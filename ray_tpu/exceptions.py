"""User-visible exceptions.

Parity with the reference's python/ray/exceptions.py: RayError hierarchy with
task/actor/object failure causes that travel through object values — a failed
task stores its exception as the object value, so ``get`` re-raises at the
caller with the remote traceback attached.
"""

from __future__ import annotations

import traceback


class RayTpuError(Exception):
    """Base class for all framework errors."""


class TaskError(RayTpuError):
    """A task raised an exception during execution.

    Stored as the value of all of the task's return objects; re-raised by
    ``get`` at the caller (reference: exceptions.py RayTaskError which wraps
    the cause and remote traceback).
    """

    def __init__(self, function_name: str, traceback_str: str, cause: Exception | None = None):
        self.function_name = function_name
        self.traceback_str = traceback_str
        self.cause = cause
        super().__init__(f"Task {function_name} failed:\n{traceback_str}")

    @classmethod
    def from_exception(cls, function_name: str, exc: Exception) -> "TaskError":
        tb = "".join(traceback.format_exception(type(exc), exc, exc.__traceback__))
        return cls(function_name, tb, exc)

    def __reduce__(self):
        # custom __init__ signature needs explicit reconstruction args; the
        # cause travels too so callers can except the original type
        return (_rebuild_task_error, (self.function_name, self.traceback_str, self.cause))


def _rebuild_task_error(function_name, traceback_str, cause):
    return TaskError(function_name, traceback_str, cause)


class ActorError(RayTpuError):
    """Base for actor-related failures."""


class ActorDiedError(ActorError):
    """The actor died before or while executing the task (reference:
    exceptions.py RayActorError)."""

    def __init__(self, actor_id=None, reason: str = "actor died"):
        self.actor_id = actor_id
        self.reason = reason
        super().__init__(f"Actor {actor_id} unavailable: {reason}")

    def __reduce__(self):
        return (type(self), (self.actor_id, self.reason))


class ActorUnschedulableError(ActorError):
    pass


class WorkerCrashedError(RayTpuError):
    """The worker process executing the task died (reference:
    exceptions.py WorkerCrashedError). Retriable."""


class NodeDiedError(RayTpuError):
    pass


class ObjectLostError(RayTpuError):
    """Object's value was lost (all copies gone / owner died) and could not be
    reconstructed from lineage (reference: exceptions.py ObjectLostError)."""

    def __init__(self, object_id=None, reason: str = "object lost"):
        self.object_id = object_id
        self.reason = reason
        super().__init__(f"Object {object_id} lost: {reason}")

    def __reduce__(self):
        return (type(self), (self.object_id, self.reason))


class OwnerDiedError(ObjectLostError):
    pass


class ObjectStoreFullError(RayTpuError):
    pass


class OutOfMemoryError(RayTpuError):
    pass


class TaskCancelledError(RayTpuError):
    def __init__(self, task_id=None):
        self.task_id = task_id
        super().__init__(f"Task {task_id} was cancelled")

    def __reduce__(self):
        return (type(self), (self.task_id,))


class GetTimeoutError(RayTpuError, TimeoutError):
    pass


class RuntimeEnvSetupError(RayTpuError):
    pass


class PlacementGroupSchedulingError(RayTpuError):
    pass


class CollectiveAbortedError(RayTpuError):
    """An in-flight collective op was aborted because a group member died
    (or the group was explicitly aborted). Retryable: the gang re-forms at a
    new group epoch and the caller re-enters the op from its last published
    training state."""

    def __init__(self, group_name: str = "", epoch: int = 0,
                 reason: str = "group member died"):
        self.group_name = group_name
        self.epoch = epoch
        self.reason = reason
        super().__init__(
            f"collective group {group_name!r} epoch {epoch} aborted: {reason}"
        )

    def __reduce__(self):
        return (type(self), (self.group_name, self.epoch, self.reason))


class BackPressureError(RayTpuError):
    """A replica refused a request because its admission queue is full
    (reference: serve/exceptions.py BackPressureError). Raised fast —
    before the request is accepted — so callers get a typed 503-style
    rejection in milliseconds instead of a 60 s timeout pileup. Retryable
    on another replica (subject to RequestRouterConfig.retry_backpressure)."""

    def __init__(self, replica_id: str = "", ongoing: int = 0,
                 queued: int = 0, retry_after_s: float = 0.1):
        self.replica_id = replica_id
        self.ongoing = ongoing
        self.queued = queued
        self.retry_after_s = retry_after_s
        super().__init__(
            f"replica {replica_id!r} shed request: {ongoing} ongoing, "
            f"{queued} queued (queue cap reached); retry after "
            f"{retry_after_s}s"
        )

    def __reduce__(self):
        return (type(self), (self.replica_id, self.ongoing, self.queued,
                             self.retry_after_s))


class DeadlineExceededError(RayTpuError, TimeoutError):
    """The request's end-to-end deadline passed. Raised by the replica for
    dead-on-arrival work (deadline already expired when the request was
    admitted) and by the handle when the retry budget runs out. Not
    retryable: the caller has already stopped waiting."""

    def __init__(self, deployment: str = "", elapsed_s: float = 0.0,
                 timeout_s: float = 0.0, where: str = "replica"):
        self.deployment = deployment
        self.elapsed_s = elapsed_s
        self.timeout_s = timeout_s
        self.where = where
        super().__init__(
            f"request to {deployment!r} exceeded its {timeout_s}s deadline "
            f"({elapsed_s:.3f}s elapsed, detected at {where})"
        )

    def __reduce__(self):
        return (type(self), (self.deployment, self.elapsed_s,
                             self.timeout_s, self.where))


class ReplicaDrainingError(RayTpuError):
    """The target replica is DRAINING and no longer admits new requests
    (the routing table was stale). Retryable: the handle force-refreshes
    and resubmits to a replica that is still RUNNING."""

    def __init__(self, replica_id: str = ""):
        self.replica_id = replica_id
        super().__init__(
            f"replica {replica_id!r} is draining and rejects new requests"
        )

    def __reduce__(self):
        return (type(self), (self.replica_id,))


class NodeFencedError(RayTpuError):
    """The node is fenced: its raylet lost contact with the GCS for longer
    than the liveness window and stopped granting leases / admitting serve
    work, so the cluster's view (which may have replaced this node's
    actors/replicas elsewhere) cannot split-brain against local execution.
    Retryable: the handle fails over to a replica on a healthy node, and the
    node unfences itself when GCS contact resumes."""

    def __init__(self, node_id: str = "", reason: str = "gcs unreachable"):
        self.node_id = node_id
        self.reason = reason
        super().__init__(
            f"node {node_id!r} is fenced ({reason}); rejecting new work"
        )

    def __reduce__(self):
        return (type(self), (self.node_id, self.reason))


class MeshValidationError(RayTpuError, ValueError):
    """A replica's parallelism config cannot map onto its devices or its
    model: ``tensor_parallel_size`` not dividing the local device count or
    the model's (kv-)head count, or a partition-rule table with no rule for
    a parameter. Raised at deployment/validation time — before any jit —
    so the operator sees the constraint instead of an opaque XLA shape
    error from deep inside the first sharded prefill."""


class RpcError(RayTpuError):
    """Transport-level RPC failure."""


class PendingCallsLimitExceeded(RayTpuError):
    pass
