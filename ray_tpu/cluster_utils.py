"""Multi-node clusters inside one host process, for tests and development.

Role-equivalent of the reference's ray.cluster_utils.Cluster
(python/ray/cluster_utils.py:135): N raylets (each with its own object store
and worker pool) run against one GCS in a single process tree; nodes can be
added and removed at runtime, which is how distributed scheduling and fault
tolerance are tested without real machines (reference: add_node :202,
remove_node :286).
"""

from __future__ import annotations

from typing import Dict, List, Optional

from ._internal.config import Config
from .runtime.node import Node


class Cluster:
    def __init__(
        self,
        initialize_head: bool = True,
        head_node_args: Optional[dict] = None,
        _system_config: Optional[dict] = None,
    ):
        self.config = Config()
        self.config.apply_overrides(_system_config)
        self._nodes: List[Node] = []
        self.head_node: Optional[Node] = None
        if initialize_head:
            self.head_node = self.add_node(**(head_node_args or {}))

    @property
    def gcs_address(self):
        return self.head_node.gcs_address if self.head_node else None

    @property
    def address(self) -> str:
        host, port = self.gcs_address
        return f"{host}:{port}"

    def add_node(
        self,
        num_cpus: float = 1,
        num_tpus: float = 0,
        resources: Optional[Dict[str, float]] = None,
        labels: Optional[Dict[str, str]] = None,
        object_store_memory: Optional[int] = None,
    ) -> Node:
        res = dict(resources or {})
        res.setdefault("CPU", float(num_cpus))
        if num_tpus:
            res["TPU"] = float(num_tpus)
        head = self.head_node is None
        node = Node(
            self.config,
            head=head,
            gcs_address=None if head else self.gcs_address,
            resources=res,
            labels=labels,
            object_store_memory=object_store_memory,
        )
        self._nodes.append(node)
        return node

    def remove_node(self, node: Node, graceful: bool = True):
        """Take a node down; with graceful=False the raylet just vanishes and
        the GCS health check discovers the death (crash simulation)."""
        if graceful:
            try:
                node.loop_thread.run(node.raylet.handle_drain(), timeout=10)
            except Exception:
                pass
        node.stop()
        if node in self._nodes:
            self._nodes.remove(node)

    def list_nodes(self) -> List[Node]:
        return list(self._nodes)

    def connect(self, **init_kwargs):
        """Attach the current process as a driver to this cluster."""
        from . import api

        return api.init(address=self.address, **init_kwargs)

    def shutdown(self):
        for node in list(reversed(self._nodes)):
            node.stop()
        self._nodes.clear()
        self.head_node = None


class AutoscalingCluster:
    """A head node plus a real autoscaler driving a fake node provider
    (reference: cluster_utils.py:26 AutoscalingCluster over
    FakeMultiNodeProvider) — worker nodes appear and disappear based on
    resource demand, all inside one host process."""

    def __init__(
        self,
        head_resources: Optional[Dict[str, float]] = None,
        worker_node_types: Optional[list] = None,
        idle_timeout_s: float = 60.0,
        update_interval_s: float = 0.25,
        max_workers: int = 20,
        provider_cls=None,
    ):
        from .autoscaler import (
            AutoscalerMonitor,
            AutoscalingConfig,
            FakeMultiNodeProvider,
            NodeTypeConfig,
        )

        self.cluster = Cluster(
            initialize_head=True,
            head_node_args={"resources": dict(head_resources or {"CPU": 1})},
        )
        node_types = [
            t if isinstance(t, NodeTypeConfig) else NodeTypeConfig(**t)
            for t in (worker_node_types or [])
        ]
        self.config = AutoscalingConfig(
            node_types=node_types,
            idle_timeout_s=idle_timeout_s,
            update_interval_s=update_interval_s,
            max_workers=max_workers,
        )
        provider_cls = provider_cls or FakeMultiNodeProvider
        self.provider = provider_cls(self.cluster, self.config)
        self.monitor = AutoscalerMonitor(
            self.config, self.provider, self.cluster.gcs_address
        )

    def start(self):
        self.monitor.start()

    @property
    def address(self) -> str:
        return self.cluster.address

    def connect(self, **init_kwargs):
        return self.cluster.connect(**init_kwargs)

    def shutdown(self):
        self.monitor.stop()
        self.cluster.shutdown()
