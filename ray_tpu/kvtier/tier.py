"""KVTierClient: replica-side access to the cluster KV prefix tier.

Holder side: ``export_and_register`` encodes a committed prefix payload,
stores the chunks as pinned plasma objects through the shared transfer
layer, and registers the fingerprint chain with the GCS tier registry.
The client holds the chunk refs until the registry's LRU evicts the entry
(notice drained on the next register/collect) — the weight-publisher
held-refs contract, applied to KV.

Puller side: ``pull`` resolves a prompt's fingerprint chain longest-first,
leases the winning entry against eviction, probes the holder's
reachability (2 s bound — a SIGKILLed holder costs the probe, not the
10 s connect window) and fetches the payload with ``prefer_source``
pinned at the holder. Every failure mode — resolve miss, lease conflict,
dead holder, vanished chunks — degrades to ``None``, which the engine
treats as *recompute*; a tier problem can slow a request but never fail
one.

Two backends: :class:`GcsTierBackend` (cluster mode — GCS registry +
plasma chunks) and :class:`LocalTierBackend` (clusterless tests/bench —
the REAL :class:`~ray_tpu.runtime.gcs.kvtier_registry.GcsKVTierRegistry`
logic over an in-process shim, with an inline chunk store and a
``kill_holder`` switch that simulates a SIGKILLed peer).
"""

from __future__ import annotations

import dataclasses
import threading
import uuid
from typing import Any, Dict, List, Optional, Tuple

from .._internal.transfer import DeadHolderError
from .fingerprint import block_fingerprints
from .shipping import (
    DEFAULT_CHUNK_SIZE,
    KVShipment,
    decode_payload,
    encode_payload,
)


def _record_outcome(outcome: str) -> None:
    try:
        from ..util.metrics import record_kvtier

        record_kvtier(outcome)
    except Exception:
        pass


def _record_transfer(logical: int, wire: int) -> None:
    try:
        from ..util.metrics import record_kvtier_transfer

        record_kvtier_transfer(logical, wire)
    except Exception:
        pass


@dataclasses.dataclass
class PulledPrefix:
    """Result of a successful peer pull: the decoded payload plus how much
    of OUR prompt it covers. ``exact`` means the shipment covers the whole
    prompt token-for-token and carries the first sampled token — the
    zero-prefill fast path."""

    shipment: KVShipment
    payload: Any
    matched_blocks: int
    exact: bool


class KVTierClient:
    def __init__(self, model: str, backend, block_size: int,
                 codec: str = "raw",
                 chunk_size: int = DEFAULT_CHUNK_SIZE,
                 holder_id: Optional[str] = None):
        self.model = model
        self.backend = backend
        self.block_size = int(block_size)
        self.codec = codec
        self.chunk_size = chunk_size
        self.holder_id = holder_id or uuid.uuid4().hex[:12]
        # tail fingerprint -> entry_id: what this replica already shipped
        # (re-registering an identical prefix would churn the registry)
        self._registered: Dict[str, int] = {}
        self._exports: Dict[int, Any] = {}  # entry_id -> backend handle
        # unregistered directed-handoff exports: a bounded FIFO so the
        # chunks outlive the prefill->decode fetch without an extra
        # release RPC; overflow drops the oldest (the fetch happens
        # immediately after the handoff, so the window is generous)
        self._direct: List[Any] = []
        self._direct_max = 64

    # -- holder side -------------------------------------------------------

    def should_export(self, token_ids, nblocks: int) -> bool:
        """Cheap pre-check: would export_and_register register anything?
        Lets the engine skip the device->host extraction for prefixes this
        replica already shipped."""
        if nblocks <= 0:
            return False
        fps = block_fingerprints(token_ids, self.block_size)[:nblocks]
        return bool(fps) and fps[-1] not in self._registered

    def _encode(self, token_ids, payload, nblocks: int,
                first_token: Optional[int]):
        covered = [int(t) for t in token_ids]
        treedef_blob, values, logical, wire = encode_payload(
            payload, self.codec, self.chunk_size
        )
        shipment = KVShipment(
            model=self.model,
            token_ids=covered,
            block_size=self.block_size,
            nblocks=nblocks,
            codec=self.codec,
            treedef_blob=treedef_blob,
            chunks=[],
            first_token=first_token,
            logical_bytes=logical,
            wire_bytes=wire,
        )
        return shipment, values

    def _register(self, shipment: KVShipment, handle, tail_fp: str) -> None:
        reply = self.backend.register(shipment, self.holder_id)
        entry_id = int(reply["entry_id"])
        self._registered[tail_fp] = entry_id
        self._exports[entry_id] = handle
        try:
            from ..util import events

            events.record_event(
                events.KV_SHIPPED,
                model=self.model, entry_id=entry_id,
                nblocks=shipment.nblocks, ntokens=shipment.ntokens,
                codec=self.codec,
                logical_bytes=shipment.logical_bytes,
                wire_bytes=shipment.wire_bytes,
                first_token=shipment.first_token is not None,
            )
        except Exception:
            pass
        self._drain(reply.get("released") or ())

    def export_and_register(self, token_ids, payload, nblocks: int,
                            first_token: Optional[int] = None
                            ) -> Optional[KVShipment]:
        """Ship a committed prefix into the tier; returns the shipment, or
        None when nothing registrable (no full blocks / already shipped)."""
        if nblocks <= 0:
            return None
        fps = block_fingerprints(token_ids, self.block_size)[:nblocks]
        if not fps or fps[-1] in self._registered:
            return None
        shipment, values = self._encode(
            token_ids, payload, nblocks, first_token
        )
        shipment, handle = self.backend.export(
            shipment, values, self.holder_id
        )
        self._register(shipment, handle, fps[-1])
        return shipment

    def ship_direct(self, token_ids, payload, nblocks: int,
                    first_token: Optional[int] = None) -> KVShipment:
        """Directed prefill->decode handoff: ALWAYS export (the consumer
        needs this request's tail + first token even when the prefix entry
        already exists); register as a tier entry too when the fingerprint
        chain is new, otherwise park the chunks in the bounded direct
        FIFO."""
        shipment, values = self._encode(
            token_ids, payload, nblocks, first_token
        )
        shipment, handle = self.backend.export(
            shipment, values, self.holder_id
        )
        fps = shipment.fingerprints()
        if fps and fps[-1] not in self._registered:
            self._register(shipment, handle, fps[-1])
        else:
            self._direct.append(handle)
            while len(self._direct) > self._direct_max:
                self.backend.drop(self._direct.pop(0))
        return shipment

    def collect(self) -> int:
        """Drain pending eviction notices (register also drains); returns
        the number of entries dropped."""
        reply = self.backend.collect(self.holder_id)
        return self._drain(reply.get("released") or ())

    def _drain(self, released) -> int:
        n = 0
        for entry_id in released:
            handle = self._exports.pop(int(entry_id), None)
            if handle is None:
                continue
            self.backend.drop(handle)
            n += 1
            try:
                from ..util import events

                events.record_event(
                    events.KVTIER_EVICT,
                    model=self.model, entry_id=int(entry_id),
                    holder_id=self.holder_id,
                )
            except Exception:
                pass
        if n:
            self._registered = {
                fp: eid for fp, eid in self._registered.items()
                if eid in self._exports
            }
        return n

    def close(self):
        """Deregister + free everything this replica shipped."""
        if self._exports:
            try:
                self.backend.evict(list(self._exports), self.holder_id)
            except Exception:
                pass
            for handle in self._exports.values():
                try:
                    self.backend.drop(handle)
                except Exception:
                    pass
            self._exports.clear()
            self._registered.clear()
        while self._direct:
            try:
                self.backend.drop(self._direct.pop())
            except Exception:
                pass

    # -- puller side -------------------------------------------------------

    def pull(self, token_ids,
             min_blocks: int = 0) -> Optional[PulledPrefix]:
        """local-miss path: resolve → lease → probe+fetch → decode.
        ``min_blocks`` is how many leading blocks the caller's LOCAL index
        already covers — an entry no deeper than that is a local hit, not
        a tier event, so it is skipped without counters or transfer.
        None == serve locally / recompute (every failure mode lands here;
        the counters record which)."""
        fps = block_fingerprints(token_ids, self.block_size)
        if not fps:
            return None
        resolved = self.backend.resolve(self.model, list(reversed(fps)))
        if resolved is None:
            _record_outcome("recompute")
            return None
        if resolved.get("holder_id") == self.holder_id:
            # our own entry: the local radix index is the fast path for
            # these; a pull through the store would be a pointless copy
            return None
        matched = fps.index(resolved["fp"]) + 1
        if matched <= min_blocks:
            return None  # local index already covers it: not a tier event
        _record_outcome("hit")
        shipment = KVShipment.from_blob(resolved["blob"])
        lease_id = uuid.uuid4().hex[:12]
        entry_id = int(resolved["entry_id"])
        if not self.backend.lease(entry_id, lease_id):
            _record_outcome("recompute")
            return None
        try:
            payload = self.backend.fetch_payload(
                shipment, tuple(resolved["holder"])
            )
        except DeadHolderError:
            _record_outcome("recompute")
            return None
        except Exception:
            _record_outcome("recompute")
            return None
        finally:
            try:
                self.backend.release_lease(entry_id, lease_id)
            except Exception:
                pass
        _record_outcome("peer_pull")
        _record_transfer(shipment.logical_bytes, shipment.wire_bytes)
        prompt = [int(t) for t in token_ids]
        exact = (
            matched == len(fps)
            and shipment.first_token is not None
            and shipment.ntokens == len(prompt)
            and list(shipment.token_ids) == prompt
        )
        return PulledPrefix(
            shipment=shipment, payload=payload,
            matched_blocks=matched, exact=exact,
        )

    def fetch_shipment(self, shipment: KVShipment) -> Optional[Any]:
        """Directed handoff (ingress prefill→decode): fetch a known
        shipment's payload from its holder. None == recompute."""
        try:
            payload = self.backend.fetch_payload(
                shipment, self.backend.holder_of(shipment)
            )
        except Exception:
            _record_outcome("recompute")
            return None
        _record_outcome("peer_pull")
        _record_transfer(shipment.logical_bytes, shipment.wire_bytes)
        return payload

    def stats(self) -> dict:
        out = {
            "holder_id": self.holder_id,
            "exported_entries": len(self._exports),
        }
        try:
            out["registry"] = self.backend.stats()
        except Exception:
            pass
        return out


# -- backends ----------------------------------------------------------------


class GcsTierBackend:
    """Cluster backend: GCS registry RPCs + plasma chunks through the
    shared transfer layer. Must run inside a worker process."""

    def _worker(self):
        from .. import _worker_api

        return _worker_api.get_core_worker()

    def _call(self, method: str, *args):
        from .. import _worker_api

        worker = self._worker()
        return _worker_api.run_on_worker_loop(
            worker.client_pool.get(*worker.gcs_address).call(method, *args)
        )

    def export(self, shipment: KVShipment, chunk_values: List[list],
               holder_id: str) -> Tuple[KVShipment, Any]:
        from .. import _worker_api
        from .._internal import transfer
        from ..object_ref import ObjectRef
        from ..weights.manifest import ChunkInfo, chunk_logical_bytes

        worker = self._worker()

        async def _store():
            return await transfer.put_chunks(worker, chunk_values, pin=True)

        stored = _worker_api.run_on_worker_loop(_store())
        infos, refs, oids = [], [], []
        for value, (oid, size) in zip(chunk_values, stored):
            refs.append(ObjectRef(oid, worker.address))
            oids.append(oid)
            infos.append(ChunkInfo(
                object_id=oid,
                owner_address=tuple(worker.address),
                size=size,
                num_leaves=len(value),
                codec=shipment.codec,
                logical_size=chunk_logical_bytes(value),
            ))
        shipment.chunks = infos
        return shipment, (refs, oids)

    def register(self, shipment: KVShipment, holder_id: str) -> dict:
        worker = self._worker()
        return self._call(
            "kvtier_register", shipment.model, shipment.fingerprints(),
            holder_id, tuple(worker.raylet_address), shipment.to_blob(),
            {
                "nblocks": shipment.nblocks,
                "wire_bytes": shipment.wire_bytes,
                "logical_bytes": shipment.logical_bytes,
            },
        )

    def resolve(self, model: str, fps: List[str]) -> Optional[dict]:
        return self._call("kvtier_resolve", model, fps)

    def lease(self, entry_id: int, lease_id: str) -> bool:
        return bool(self._call("kvtier_lease", entry_id, lease_id))

    def release_lease(self, entry_id: int, lease_id: str) -> None:
        self._call("kvtier_release", entry_id, lease_id)

    def evict(self, entry_ids: List[int], holder_id: str) -> None:
        self._call("kvtier_evict", list(entry_ids), holder_id)

    def collect(self, holder_id: str) -> dict:
        return self._call("kvtier_collect", holder_id)

    def fetch_payload(self, shipment: KVShipment, holder) -> Any:
        from .. import _worker_api
        from .._internal import transfer

        worker = self._worker()

        async def _fetch():
            import asyncio

            return list(await asyncio.gather(*[
                transfer.fetch_chunk(
                    worker, chunk, tuple(holder),
                    probe_source=True, require_source=True,
                )
                for chunk in shipment.chunks
            ]))

        values = _worker_api.run_on_worker_loop(_fetch())
        return decode_payload(shipment.treedef_blob, values)

    def holder_of(self, shipment: KVShipment):
        # chunk owner == the exporting worker; its raylet serves the pull
        return tuple(shipment.chunks[0].owner_address) if shipment.chunks \
            else None

    def drop(self, handle) -> None:
        from .. import _worker_api
        from .._internal import transfer

        refs, oids = handle
        worker = self._worker()
        try:
            _worker_api.run_on_worker_loop(
                transfer.unpin_chunks(worker, oids)
            )
        except Exception:
            pass
        refs.clear()  # dropping the refs is the actual free

    def stats(self) -> dict:
        return self._call("kvtier_stats")


class _LocalGcsShim:
    """Just enough of GcsServer for GcsKVTierRegistry to run in-process."""

    class _NullPublisher:
        def publish(self, *_a, **_k):
            pass

    def __init__(self, max_entries: int, lease_s: float):
        import types

        self._kv: Dict[str, bytes] = {}
        self.config = types.SimpleNamespace(
            kvtier_max_entries=max_entries, kvtier_lease_s=lease_s
        )
        self.publisher = self._NullPublisher()


class LocalTierBackend:
    """Clusterless backend: the real registry logic + an inline chunk
    store. Shared by every engine in one process (tests, bench), so two
    in-proc "replicas" exercise the identical register/resolve/lease/evict
    protocol the cluster runs — only the byte transport is inline."""

    def __init__(self, max_entries: int = 4096, lease_s: float = 60.0):
        from ..runtime.gcs.kvtier_registry import GcsKVTierRegistry

        self._lock = threading.Lock()
        self.registry = GcsKVTierRegistry(
            _LocalGcsShim(max_entries, lease_s)
        )
        self._store: Dict[bytes, list] = {}  # oid -> chunk leaf values
        self._chunk_holder: Dict[bytes, str] = {}
        self._dead: set = set()  # holder_ids "SIGKILLed" by the test

    def kill_holder(self, holder_id: str) -> None:
        """Simulate a SIGKILLed holder: its chunks vanish, its registry
        entries remain (stale — exactly the state a real kill leaves until
        the death sweep runs), so pulls hit the dead-holder path."""
        with self._lock:
            self._dead.add(holder_id)
            for oid, hid in list(self._chunk_holder.items()):
                if hid == holder_id:
                    self._store.pop(oid, None)

    def export(self, shipment: KVShipment, chunk_values: List[list],
               holder_id: str) -> Tuple[KVShipment, Any]:
        from ..weights.manifest import ChunkInfo, chunk_logical_bytes

        infos, oids = [], []
        with self._lock:
            for value in chunk_values:
                oid = uuid.uuid4().bytes[:8]
                self._store[oid] = value
                self._chunk_holder[oid] = holder_id
                oids.append(oid)
                infos.append(ChunkInfo(
                    object_id=oid,
                    owner_address=("local", 0),
                    size=chunk_logical_bytes(value),
                    num_leaves=len(value),
                    codec=shipment.codec,
                    logical_size=chunk_logical_bytes(value),
                ))
        shipment.chunks = infos
        return shipment, oids

    def register(self, shipment: KVShipment, holder_id: str) -> dict:
        with self._lock:
            return self.registry.register(
                shipment.model, shipment.fingerprints(), holder_id,
                ("local", 0), shipment.to_blob(),
                {
                    "nblocks": shipment.nblocks,
                    "wire_bytes": shipment.wire_bytes,
                    "logical_bytes": shipment.logical_bytes,
                },
            )

    def resolve(self, model: str, fps: List[str]) -> Optional[dict]:
        with self._lock:
            return self.registry.resolve(model, fps)

    def lease(self, entry_id: int, lease_id: str) -> bool:
        with self._lock:
            return self.registry.lease(entry_id, lease_id)

    def release_lease(self, entry_id: int, lease_id: str) -> None:
        with self._lock:
            self.registry.release(entry_id, lease_id)

    def evict(self, entry_ids: List[int], holder_id: str) -> None:
        with self._lock:
            self.registry.evict(list(entry_ids), holder_id)

    def collect(self, holder_id: str) -> dict:
        with self._lock:
            return self.registry.collect(holder_id)

    def fetch_payload(self, shipment: KVShipment, holder) -> Any:
        with self._lock:
            values = []
            for chunk in shipment.chunks:
                hid = self._chunk_holder.get(chunk.object_id)
                if hid in self._dead or chunk.object_id not in self._store:
                    raise DeadHolderError(
                        f"holder of chunk {chunk.object_id!r} is gone"
                    )
                values.append(self._store[chunk.object_id])
        return decode_payload(shipment.treedef_blob, values)

    def holder_of(self, shipment: KVShipment):
        return ("local", 0)

    def drop(self, handle) -> None:
        with self._lock:
            for oid in handle:
                self._store.pop(oid, None)
                self._chunk_holder.pop(oid, None)

    def stats(self) -> dict:
        with self._lock:
            return self.registry.stats()
