"""Cluster-wide KV prefix tier: ship committed KV blocks between replicas.

The per-replica radix :class:`~ray_tpu.kvcache.prefix_index.PrefixIndex`
makes repeated prefixes cheap *on one replica*; this package makes them
cheap on EVERY replica. A replica that commits a cacheable prefix exports
the blocks through the shared pinned-buffer transfer layer
(``_internal/transfer.py``) and registers a fingerprint chain with the GCS
tier registry; any replica — including a fresh autoscale scale-up that has
computed nothing — resolves a warm prefix **local-hit → peer-pull →
recompute**, in that order. The same shipment machinery carries the
directed prefill→decode handoff of disaggregated serving, where the decode
replica adopts the shipped blocks (plus the tail fragment and the first
sampled token) and starts decoding with zero prefill-computed tokens.
"""

from .fingerprint import block_fingerprints
from .shipping import KVShipment, decode_payload, encode_payload
from .tier import (
    GcsTierBackend,
    KVTierClient,
    LocalTierBackend,
    PulledPrefix,
)

__all__ = [
    "KVShipment",
    "KVTierClient",
    "GcsTierBackend",
    "LocalTierBackend",
    "PulledPrefix",
    "block_fingerprints",
    "encode_payload",
    "decode_payload",
]
