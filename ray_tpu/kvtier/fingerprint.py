"""Prefix-block fingerprints: the tier's lookup keys.

A prompt's cacheable identity is its sequence of *full* KV blocks, so the
fingerprint chain is a running hash over block-sized token groups:
``fps[i]`` commits blocks ``0..i`` inclusive. Chaining means equality of
``fps[i]`` implies equality of the entire leading ``(i+1)`` blocks — one
string compare replaces a token-by-token prefix walk, and the registry
can index every prefix length of an entry under its own fingerprint
without storing any tokens.

Fingerprints are deliberately content-only (no model name): the registry
scopes every lookup by model id, and keeping the hash content-pure lets a
replica precompute chains before it knows which tier it will consult.
"""

from __future__ import annotations

import hashlib
from typing import List, Sequence

#: hex chars kept per fingerprint: 128 bits — collision-safe at any
#: realistic entry count while keeping GCS keys short
_FP_HEX = 32


def block_fingerprints(
    token_ids: Sequence[int], block_size: int
) -> List[str]:
    """Running fingerprint per full block of ``token_ids``; ``fps[i]``
    covers tokens ``[0, (i+1) * block_size)``. Trailing partial blocks
    contribute nothing (only full blocks are ever committed/shipped)."""
    if block_size <= 0:
        raise ValueError(f"block_size must be positive, got {block_size}")
    h = hashlib.sha256()
    fps: List[str] = []
    for i in range(len(token_ids) // block_size):
        block = token_ids[i * block_size : (i + 1) * block_size]
        h.update(b"|".join(str(int(t)).encode() for t in block))
        h.update(b";")
        fps.append(h.hexdigest()[:_FP_HEX])
    return fps
