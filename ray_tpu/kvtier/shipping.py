"""KV shipment encoding: committed prefix blocks as transferable chunks.

A shipment's payload is a plain pytree — ``{"blocks": [per-KV-leaf stacked
block arrays of shape (nblocks, ..., block_size, head_dim)], "tail":
[per-KV-leaf fragments for the tokens past the last full block] or None}``
— encoded with the SAME chunk machinery as the weight plane
(``weights/manifest.chunk_pytree``): greedy wire-byte packing, the PR 14
int8 per-block codec with dequantize-on-assemble, and logical-vs-wire
accounting. That is the point of the transfer-layer extraction: KV blocks
in flight are just another chunked pytree, so ``codec="int8"`` halves the
prefill→decode bytes exactly the way it halves a weight broadcast.

The :class:`KVShipment` descriptor is what crosses the control plane (the
GCS tier registry blob, or the ingress prefill→decode handoff): token
coverage, block geometry, the first sampled token (what lets a decode
replica start with **zero** prefill-computed tokens), and the chunk
records pointing at the holder's pinned plasma objects. The payload bytes
themselves only ever move through ``_internal/transfer.py`` (RT011).
"""

from __future__ import annotations

import dataclasses
from typing import Any, List, Optional

from .._internal import serialization
from ..weights.manifest import (
    CODEC_INT8,
    CODEC_RAW,
    ChunkInfo,
    assemble_pytree,
    chunk_pytree,
)
from .fingerprint import block_fingerprints

#: default target size of one shipment chunk (small prefixes ship as one)
DEFAULT_CHUNK_SIZE = 4 * 1024 * 1024


@dataclasses.dataclass
class KVShipment:
    """Descriptor of one shipped prefix: geometry + chunk pointers.

    ``token_ids`` are the tokens whose K/V the payload covers —
    ``nblocks * block_size`` full-block tokens plus the tail fragment.
    ``first_token`` is the token sampled from the prefill logits (present
    on directed/full shipments; ``None`` on blocks-only tier entries), so
    an exact-prompt consumer skips prefill entirely.
    """

    model: str
    token_ids: List[int]
    block_size: int
    nblocks: int
    codec: str
    treedef_blob: bytes
    chunks: List[ChunkInfo]
    first_token: Optional[int] = None
    logical_bytes: int = 0
    wire_bytes: int = 0

    @property
    def ntokens(self) -> int:
        return len(self.token_ids)

    @property
    def tail_len(self) -> int:
        return self.ntokens - self.nblocks * self.block_size

    def fingerprints(self) -> List[str]:
        """Fingerprint chain of the covered full blocks (what the holder
        registers: every prefix length points at this shipment)."""
        return block_fingerprints(
            self.token_ids[: self.nblocks * self.block_size],
            self.block_size,
        )

    def to_blob(self) -> bytes:
        return serialization.dumps(self)

    @staticmethod
    def from_blob(blob: bytes) -> "KVShipment":
        return serialization.loads(blob)


def encode_payload(payload: Any, codec: str = CODEC_RAW,
                   chunk_size: int = DEFAULT_CHUNK_SIZE):
    """Chunk a shipment payload pytree for transfer. Returns
    ``(treedef_blob, chunk_values, logical_bytes, wire_bytes)`` — identical
    contract to a weight publish, so the int8 codec and the greedy packing
    ride along unchanged."""
    if codec not in (CODEC_RAW, CODEC_INT8):
        raise ValueError(f"unknown KV ship codec {codec!r}")
    treedef_blob, chunk_values, logical = chunk_pytree(
        payload, chunk_size, codec=codec
    )
    from ..weights.manifest import leaf_wire_nbytes

    wire = sum(
        leaf_wire_nbytes(v) for chunk in chunk_values for v in chunk
    )
    return treedef_blob, chunk_values, logical, wire


def decode_payload(treedef_blob: bytes, chunk_values: List[list]) -> Any:
    """Inverse of :func:`encode_payload`: dequantize-on-assemble back into
    the ``{"blocks": ..., "tail": ...}`` pytree of host arrays."""
    return assemble_pytree(treedef_blob, chunk_values)
