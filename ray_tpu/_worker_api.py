"""Process-global core-worker access.

Equivalent of the reference's global_worker (_private/worker.py): the one
CoreWorker instance of this process, plus the sync bridge used by the public
API. In the driver the CoreWorker runs on a dedicated LoopThread; in worker
processes it runs on the process main loop and this module is populated by
worker_main.
"""

from __future__ import annotations

import threading
from typing import Optional

_lock = threading.Lock()
_core_worker = None
_config = None
_loop_thread = None  # LoopThread when we own the loop (driver mode)
_node = None  # in-process Node (driver started a local cluster)


def set_core_worker(worker, config, loop_thread=None, node=None):
    global _core_worker, _config, _loop_thread, _node
    with _lock:
        _core_worker = worker
        _config = config
        _loop_thread = loop_thread
        _node = node


def clear():
    global _core_worker, _config, _loop_thread, _node
    with _lock:
        _core_worker = None
        _config = None
        _loop_thread = None
        _node = None


def get_loop_thread():
    return _loop_thread


def maybe_get_core_worker():
    return _core_worker


def get_core_worker():
    if _core_worker is None:
        raise RuntimeError(
            "ray_tpu has not been initialized — call ray_tpu.init() first"
        )
    return _core_worker


def get_config():
    return _config


def get_node():
    return _node


def is_initialized() -> bool:
    return _core_worker is not None


def run_on_worker_loop(coro, timeout=None):
    """Run a coroutine on the core worker's loop from sync code."""
    worker = get_core_worker()
    if _loop_thread is not None:
        return _loop_thread.run(coro, timeout)
    import asyncio
    import concurrent.futures

    loop = worker.loop
    try:
        running = asyncio.get_running_loop()
    except RuntimeError:
        running = None
    if running is loop:
        raise RuntimeError(
            "blocking API called from the worker event loop; use the async API"
        )
    fut = asyncio.run_coroutine_threadsafe(coro, loop)
    try:
        return fut.result(timeout)
    except concurrent.futures.TimeoutError:
        fut.cancel()
        raise TimeoutError("operation timed out")
