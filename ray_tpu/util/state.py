"""Cluster state API: list live tasks/actors/nodes/objects programmatically.

Role-equivalent of the reference's state API (python/ray/util/state/api.py —
list_tasks/list_actors/list_nodes/... backed by StateAggregator +
GcsTaskManager). Queries go straight to the GCS; task rows come from the
task-event store fed by every worker's event buffer.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from .. import _worker_api
from ..runtime.gcs import keys as gcs_keys


def _gcs_call(method: str, *args):
    worker = _worker_api.get_core_worker()
    return _worker_api.run_on_worker_loop(
        worker.client_pool.get(*worker.gcs_address).call(method, *args)
    )


def list_nodes() -> List[Dict[str, Any]]:
    try:
        states = _gcs_call("get_node_states") or {}
    except Exception:
        states = {}  # older GCS: fall back to the boolean alive flag
    return [
        {
            "node_id": n.node_id.hex(),
            "state": states.get(
                n.node_id.hex(), "ALIVE" if n.alive else "DEAD"
            ),
            "address": f"{n.address[0]}:{n.address[1]}",
            "resources_total": n.resources_total,
            "labels": n.labels,
            "is_head_node": n.is_head,
        }
        for n in _gcs_call("get_all_nodes")
    ]


def list_actors() -> List[Dict[str, Any]]:
    return [
        {
            "actor_id": a.actor_id.hex(),
            "state": a.state.name if hasattr(a.state, "name") else str(a.state),
            "name": a.name,
            "class_name": (
                a.creation_spec.function.qualname if a.creation_spec else ""
            ),
            "node_address": f"{a.address[0]}:{a.address[1]}" if a.address else "",
            "restarts": a.num_restarts,
            "max_restarts": a.max_restarts,
        }
        for a in _gcs_call("list_actors")
    ]


def list_jobs() -> List[Dict[str, Any]]:
    return _gcs_call("list_jobs")


def list_placement_groups() -> List[Dict[str, Any]]:
    return [
        {
            "placement_group_id": pg.placement_group_id.hex(),
            "name": pg.name,
            "state": pg.state.name
            if hasattr(pg.state, "name")
            else str(pg.state),
            "strategy": pg.strategy.name
            if hasattr(pg.strategy, "name")
            else str(pg.strategy),
            "bundles": [getattr(b, "resources", b) for b in pg.bundles],
        }
        for pg in _gcs_call("list_placement_groups")
    ]


def list_tasks(
    filters: Optional[Dict[str, Any]] = None, limit: int = 1000
) -> List[Dict[str, Any]]:
    return _gcs_call("list_task_events", filters, limit)


def summarize_tasks() -> Dict[str, int]:
    """state -> count (reference: `ray summary tasks`)."""
    out: Dict[str, int] = {}
    for ev in list_tasks(limit=100000):
        out[ev.get("state", "UNKNOWN")] = out.get(ev.get("state", "UNKNOWN"), 0) + 1
    return out


def list_objects() -> List[Dict[str, Any]]:
    """Objects in the local node's store (reference: `ray list objects` is
    cluster-wide via object locations; store-level view here)."""
    node = _worker_api.get_node()
    if node is None:
        return []
    store = node.raylet.store
    stats = store.stats()
    return [
        {
            "store": stats,
            "spilled": {
                oid.hex(): path
                for oid, path in getattr(node.raylet, "_spilled", {}).items()
            },
        }
    ]


def metrics_summary() -> Dict[str, Any]:
    """Cluster telemetry rollup from every worker's pushed metrics
    snapshot: collective traffic (bytes/ops/mean latency/achieved
    bandwidth per op), per-role step breakdowns with the
    scaling-efficiency gauge, and per-device HBM usage (reference
    analogue: `ray status -v` + the metrics agent's aggregation)."""
    import json as _json

    from .metrics import (
        adapter_summary,
        autoscale_summary,
        device_rows,
        fetch_metric_payloads,
        ingress_summary,
        kvcache_summary,
        kvtier_summary,
        llm_summary,
        partition_summary,
        serve_ft_summary,
        serve_latency_summary,
        train_ft_summary,
        weights_summary,
    )

    payloads = fetch_metric_payloads(_gcs_call)
    collective: Dict[str, Dict[str, float]] = {}
    latency_sums: Dict[str, float] = {}
    steps: Dict[str, Dict[str, float]] = {}
    efficiency: Dict[str, float] = {}
    for payload in payloads:
        for snap in payload.get("metrics", []):
            name = snap["name"]
            if name == "collective_bytes_total":
                for tag_json, value in snap["values"].items():
                    tags = dict(zip(snap["tag_keys"], _json.loads(tag_json)))
                    row = collective.setdefault(
                        tags.get("op", "?"), {"bytes": 0.0, "ops": 0.0}
                    )
                    row["bytes"] += value
            elif name == "collective_wire_bytes_total":
                # encoded bytes the links actually carried (== "bytes"
                # unless the group runs the int8 quantized transport)
                for tag_json, value in snap["values"].items():
                    tags = dict(zip(snap["tag_keys"], _json.loads(tag_json)))
                    row = collective.setdefault(
                        tags.get("op", "?"), {"bytes": 0.0, "ops": 0.0}
                    )
                    row["wire_bytes"] = row.get("wire_bytes", 0.0) + value
            elif name == "collective_op_latency_ms":
                for tag_json, counts in snap.get("counts", {}).items():
                    tags = dict(zip(snap["tag_keys"], _json.loads(tag_json)))
                    op = tags.get("op", "?")
                    row = collective.setdefault(
                        op, {"bytes": 0.0, "ops": 0.0}
                    )
                    # accumulate sum and count across ALL workers' payloads;
                    # the cluster-wide mean is computed once after the loop
                    row["ops"] += float(sum(counts))
                    latency_sums[op] = latency_sums.get(op, 0.0) + snap[
                        "values"
                    ].get(tag_json, 0.0)
            elif name == "collective_bandwidth_gb_s":
                for tag_json, value in snap["values"].items():
                    tags = dict(zip(snap["tag_keys"], _json.loads(tag_json)))
                    collective.setdefault(
                        tags.get("op", "?"), {"bytes": 0.0, "ops": 0.0}
                    )["bandwidth_gb_s"] = value
            elif name == "step_time_seconds":
                for tag_json, value in snap["values"].items():
                    tags = dict(zip(snap["tag_keys"], _json.loads(tag_json)))
                    steps.setdefault(tags.get("role", "?"), {})[
                        tags.get("component", "?")
                    ] = value
            elif name == "scaling_efficiency_ratio":
                for tag_json, value in snap["values"].items():
                    tags = dict(zip(snap["tag_keys"], _json.loads(tag_json)))
                    efficiency[tags.get("role", "?")] = value
    for op, total_ms in latency_sums.items():
        if collective[op]["ops"]:
            collective[op]["mean_ms"] = total_ms / collective[op]["ops"]
    return {
        "collective": collective,
        "step_breakdown": steps,
        "scaling_efficiency": efficiency,
        "devices": device_rows(payloads),
        "kvcache": kvcache_summary(payloads),
        "kvtier": kvtier_summary(payloads),
        "train_ft": train_ft_summary(payloads, stragglers=_stragglers()),
        "serve_ft": serve_ft_summary(payloads),
        "serve_latency": serve_latency_summary(payloads),
        "llm": llm_summary(payloads),
        "adapters": adapter_summary(payloads),
        "autoscale": autoscale_summary(payloads),
        "partition": partition_summary(payloads),
        "ingress": ingress_summary(payloads),
        "weights": weights_summary(payloads),
    }


def list_train_runs() -> List[Dict[str, Any]]:
    """Live train-run records published by TrainController (``trainrun:*``
    KV keys): state, collective group+epoch, and per-rank worker identity —
    the index the chaos CLI uses to target a specific run/rank."""
    import json as _json

    out = []
    for key in _gcs_call("kv_keys", gcs_keys.TRAIN_RUN.scan) or []:
        raw = _gcs_call("kv_get", key)
        if not raw:
            continue
        try:
            rec = _json.loads(bytes(raw).decode())
        except Exception:
            continue
        rec["name"] = gcs_keys.TRAIN_RUN.strip(key)
        out.append(rec)
    return out


def list_proxies() -> List[Dict[str, Any]]:
    """Live ingress-proxy registry (``proxy:*`` KV keys written by the
    serve controller): kind, host:port, pid, node — the index `ray_tpu
    proxies`, the dashboard and chaos kill-proxy use. Works from any
    connected process without a controller actor handle."""
    import json as _json

    out = []
    for key in _gcs_call("kv_keys", gcs_keys.SERVE_PROXY.scan) or []:
        raw = _gcs_call("kv_get", key)
        if not raw:
            continue
        try:
            rec = _json.loads(bytes(raw).decode())
        except Exception:
            continue
        rec.setdefault("proxy_id", gcs_keys.SERVE_PROXY.strip(key))
        out.append(rec)
    return sorted(out, key=lambda r: str(r.get("proxy_id")))


def list_replicas(app: Optional[str] = None) -> List[Dict[str, Any]]:
    """Serve replica inventory rows from the controller's GCS KV mirror
    (``serve:replicas``, refreshed every reconcile tick): app, replica id,
    state, node, and — for sharded LLM replicas — mesh ownership plus
    per-device HBM/KV-pool accounting. Works from any connected process
    without a controller actor handle (`ray_tpu list replicas`,
    dashboard ``/api/serve``)."""
    import json as _json

    raw = _gcs_call("kv_get", gcs_keys.SERVE_REPLICAS)
    if not raw:
        return []
    try:
        rows = _json.loads(bytes(raw).decode()).get("replicas", [])
    except Exception:
        return []
    if app is not None:
        rows = [r for r in rows if r.get("app") == app]
    return sorted(
        rows, key=lambda r: (str(r.get("app")), str(r.get("replica_id")))
    )


def autoscale_log(limit: int = 100) -> List[Dict[str, Any]]:
    """Most recent SLO-autoscaler decision events, oldest first, read from
    the controller's GCS KV mirror (``serve:autoscale_log``) — works from
    any connected process without a controller actor handle (`ray_tpu
    autoscale log`, dashboard)."""
    import json as _json

    raw = _gcs_call("kv_get", gcs_keys.SERVE_AUTOSCALE_LOG)
    if not raw:
        return []
    try:
        events = _json.loads(bytes(raw).decode())
    except Exception:
        return []
    return events[-max(0, limit):]


def list_events(
    limit: int = 1000, name: Optional[str] = None,
    since: Optional[float] = None,
) -> List[Dict[str, Any]]:
    """Most recent flight-recorder events from the GCS event store, oldest
    first, optionally filtered by event name and/or a ``ts >= since``
    floor (`ray_tpu events`, ``/api/events``). Because every process
    streams its ring continuously, this works for SIGKILLed processes
    too — the post-mortem path."""
    return _gcs_call("list_events", limit, name, since)


def events_stats() -> Dict[str, Any]:
    """GCS event-store truncation accounting (stored / cap / dropped)."""
    return _gcs_call("events_stats")


def _stragglers() -> Optional[List[Dict[str, Any]]]:
    """Best-effort straggler verdicts for the train_ft join — None when
    the GCS predates the timeseries plane or the call fails."""
    try:
        return _gcs_call("straggler_verdicts")
    except Exception:
        return None


def query_timeseries(
    name: Optional[str] = None,
    labels: Optional[Dict[str, str]] = None,
    since: Optional[float] = None,
    worker_id: Optional[str] = None,
    limit_points: int = 500,
) -> List[Dict[str, Any]]:
    """Series entries (with points) from the GCS timeseries store
    (``ray_tpu top``, ``/api/timeseries``)."""
    return _gcs_call(
        "ts_query", name, labels, since, worker_id, limit_points
    )


def list_timeseries() -> List[Dict[str, Any]]:
    """Series index (no points) from the GCS timeseries store."""
    return _gcs_call("ts_list")


def alerts_snapshot() -> Dict[str, Any]:
    """Active alerts + rules + recent transitions + straggler verdicts
    in one round-trip (``ray_tpu alerts``, ``/api/alerts``)."""
    return _gcs_call("alerts_snapshot")


def set_alert_rule(rule: Dict[str, Any]) -> Dict[str, Any]:
    return _gcs_call("alerts_set_rule", rule)


def delete_alert_rule(name: str) -> bool:
    return _gcs_call("alerts_delete_rule", name)


def straggler_verdicts() -> List[Dict[str, Any]]:
    """Per-worker step-time deviation rows, sorted worst-first."""
    return _gcs_call("straggler_verdicts")


def list_weights() -> List[Dict[str, Any]]:
    """Weight-plane registry rows: every published model with its head
    version, resident/pinned versions, tombstone count, and broadcast-tree
    shape (reference analogue: `ray list objects` for the model-state
    subsystem)."""
    return _gcs_call("weights_list")


def _raylet_call(address, method: str, *args, **kwargs):
    worker = _worker_api.get_core_worker()
    return _worker_api.run_on_worker_loop(
        worker.client_pool.get(*tuple(address)).call(method, *args, **kwargs)
    )


def list_logs(node_id: Optional[str] = None) -> Dict[str, List[str]]:
    """Per-node listing of session log files (reference: `ray logs` backed
    by the per-node log dirs). ``node_id`` may be a hex prefix."""
    out: Dict[str, List[str]] = {}
    for n in _gcs_call("get_all_nodes"):
        nid = n.node_id.hex()
        if not n.alive or (node_id and not nid.startswith(node_id)):
            continue
        try:
            out[nid] = _raylet_call(n.address, "list_logs")
        except Exception:
            out[nid] = []
    return out


def get_log(
    filename: str, node_id: Optional[str] = None, tail: int = 1000
) -> str:
    """Fetch the tail of one log file, searching nodes (hex-prefix filtered)
    until a node that has it responds."""
    for n in _gcs_call("get_all_nodes"):
        nid = n.node_id.hex()
        if not n.alive or (node_id and not nid.startswith(node_id)):
            continue
        try:
            text = _raylet_call(n.address, "read_log", filename, tail)
        except Exception:
            continue
        if text:
            return text
    return ""


def cluster_summary() -> Dict[str, Any]:
    nodes = list_nodes()
    return {
        "nodes": len(nodes),
        "alive_nodes": sum(1 for n in nodes if n["state"] == "ALIVE"),
        "actors": len(list_actors()),
        "placement_groups": len(list_placement_groups()),
        "tasks": summarize_tasks(),
    }
