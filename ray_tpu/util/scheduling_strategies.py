"""User-facing scheduling strategies.

Role-equivalent of the reference's ray.util.scheduling_strategies
(util/scheduling_strategies.py:17,43,164): strategy objects passed as
``scheduling_strategy=`` to task/actor options. Each converts to the internal
protocol representation at submission time.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from .._internal import protocol
from .._internal.ids import NodeID
from .placement_group import PlacementGroup


class PlacementGroupSchedulingStrategy:
    """Pin a task/actor into a placement group bundle."""

    def __init__(
        self,
        placement_group: PlacementGroup,
        placement_group_bundle_index: int = -1,
        placement_group_capture_child_tasks: bool = False,
    ):
        self.placement_group = placement_group
        self.placement_group_bundle_index = placement_group_bundle_index
        self.placement_group_capture_child_tasks = placement_group_capture_child_tasks

    def _to_protocol(self) -> protocol.PlacementGroupSchedulingStrategy:
        return protocol.PlacementGroupSchedulingStrategy(
            placement_group_id=self.placement_group.id,
            bundle_index=self.placement_group_bundle_index,
            capture_child_tasks=self.placement_group_capture_child_tasks,
        )


class NodeAffinitySchedulingStrategy:
    """Pin to a specific node by id (hex string from ray_tpu.nodes())."""

    def __init__(self, node_id: str, soft: bool = False):
        self.node_id = node_id
        self.soft = soft

    def _to_protocol(self) -> protocol.NodeAffinitySchedulingStrategy:
        return protocol.NodeAffinitySchedulingStrategy(
            node_id=NodeID.from_hex(self.node_id), soft=self.soft
        )


class NodeLabelSchedulingStrategy:
    """Schedule onto nodes matching label constraints (reference:
    util/scheduling_strategies.py:164; used for TPU slice targeting)."""

    def __init__(
        self,
        hard: Optional[Dict[str, List[str]]] = None,
        soft: Optional[Dict[str, List[str]]] = None,
    ):
        self.hard = hard or {}
        self.soft = soft or {}

    def _to_protocol(self) -> protocol.NodeLabelSchedulingStrategy:
        return protocol.NodeLabelSchedulingStrategy(
            hard=dict(self.hard), soft=dict(self.soft)
        )


def SPREAD() -> protocol.SpreadSchedulingStrategy:
    return protocol.SpreadSchedulingStrategy()


def to_protocol_strategy(strategy):
    """Normalize a user-supplied strategy for a TaskSpec."""
    if strategy is None or isinstance(strategy, str):
        if strategy == "SPREAD":
            return protocol.SpreadSchedulingStrategy()
        return protocol.DefaultSchedulingStrategy()
    if hasattr(strategy, "_to_protocol"):
        return strategy._to_protocol()
    return strategy
