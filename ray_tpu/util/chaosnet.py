"""Cluster-wide network chaos-mesh distribution.

The mesh spec (see ``_internal.rpc.set_rpc_chaos`` structured format) is a
JSON document stored under :data:`keys.CHAOS_NET_SPEC` in the GCS KV —
written by ``ray_tpu chaos net`` / ``testing.set_network_chaos`` and polled
by every process (raylet periodic tick, worker/driver poll loop) through
the chaos-EXEMPT ``chaos_fetch`` RPC, so *healing* a partition propagates
through the partition it heals. Change detection is by raw-spec equality:
an unchanged KV value never re-seeds the deterministic rng mid-run.
"""

from __future__ import annotations

import asyncio
import json
import logging
from typing import Optional

from .._internal.rpc import set_rpc_chaos

logger = logging.getLogger(__name__)

# Raw value of the last spec applied from the KV. None means "never saw a
# cluster spec", which deliberately does NOT clear locally-set chaos (tests
# call set_rpc_chaos directly without the KV); clearing only happens on an
# observed transition from a cluster spec to no/empty spec.
_last_applied: Optional[str] = None


def reset() -> None:
    global _last_applied
    _last_applied = None


def maybe_apply(raw) -> bool:
    """Apply a fetched raw spec if it changed since the last application.
    Returns True when the process-local chaos state was updated."""
    global _last_applied
    if isinstance(raw, (bytes, bytearray, memoryview)):
        raw = bytes(raw).decode("utf-8", "replace")
    if raw == _last_applied:
        return False
    if not raw:
        _last_applied = raw
        set_rpc_chaos({})
        return True
    try:
        spec = json.loads(raw)
    except (ValueError, TypeError):
        logger.warning("ignoring malformed chaos-net spec %r", raw[:200])
        return False
    _last_applied = raw
    set_rpc_chaos(spec)
    return True


async def poll_once(client) -> bool:
    """One best-effort fetch-and-apply against a GCS client. Unreachable
    GCS (e.g. under the very partition being injected) keeps the current
    local spec."""
    try:
        raw = await client.call("chaos_fetch", timeout=2.0)
    except Exception:
        return False
    return maybe_apply(raw)


async def poll_loop(client, period_s: float = 1.0):
    """Long-lived poller for processes without a periodic runner (workers,
    address-mode drivers). Run as a task on the process's event loop."""
    while True:
        try:
            await poll_once(client)
        except Exception:  # pragma: no cover — the poller must never die
            logger.exception("chaosnet poll failed")
        await asyncio.sleep(period_s)
