"""ray_tpu.util: placement groups, scheduling strategies, TPU slices, helpers."""

from .actor_pool import ActorPool
from .check_serialize import inspect_serializability
from .placement_group import (
    PlacementGroup,
    get_placement_group,
    placement_group,
    placement_group_table,
    remove_placement_group,
)
from .scheduling_strategies import (
    NodeAffinitySchedulingStrategy,
    NodeLabelSchedulingStrategy,
    PlacementGroupSchedulingStrategy,
)

__all__ = [
    "ActorPool",
    "inspect_serializability",
    "PlacementGroup",
    "placement_group",
    "remove_placement_group",
    "get_placement_group",
    "placement_group_table",
    "PlacementGroupSchedulingStrategy",
    "NodeAffinitySchedulingStrategy",
    "NodeLabelSchedulingStrategy",
]
