"""multiprocessing.Pool drop-in backed by actors.

Role-equivalent of the reference's ``ray.util.multiprocessing`` (the Pool
shim in util/multiprocessing/pool.py): a ``Pool`` whose worker processes are
actors, so user code written against the stdlib Pool API fans out over the
cluster unchanged.
"""

from __future__ import annotations

import itertools
import threading
from collections import deque
from typing import Any, Callable, Iterable, List, Optional

from .. import api


class TimeoutError(Exception):  # noqa: A001 - mirrors multiprocessing.TimeoutError
    pass


class _PoolWorker:
    """Actor holding an optional initializer's state; runs submitted calls."""

    def __init__(self, initializer=None, initargs=()):
        if initializer is not None:
            initializer(*initargs)

    def run_batch(self, fn, chunk):
        return [fn(*args, **kwargs) for args, kwargs in chunk]

    def ping(self):
        return True


class AsyncResult:
    """multiprocessing.pool.AsyncResult equivalent over ObjectRefs."""

    def __init__(self, refs: List[Any], unpack_single: bool, callback=None,
                 error_callback=None):
        self._refs = refs
        self._unpack_single = unpack_single
        self._callback = callback
        self._error_callback = error_callback
        self._result = None
        self._error = None
        self._done = threading.Event()
        t = threading.Thread(target=self._collect, daemon=True)
        t.start()

    def _collect(self):
        try:
            chunks = api.get(self._refs)
            flat = [v for chunk in chunks for v in chunk]
            self._result = flat[0] if self._unpack_single else flat
            if self._callback is not None:
                self._callback(self._result)
        except Exception as e:  # surfaced again from get()
            self._error = e
            if self._error_callback is not None:
                self._error_callback(e)
        finally:
            self._done.set()

    def get(self, timeout: Optional[float] = None):
        if not self._done.wait(timeout):
            raise TimeoutError("result not ready")
        if self._error is not None:
            raise self._error
        return self._result

    def wait(self, timeout: Optional[float] = None):
        self._done.wait(timeout)

    def ready(self) -> bool:
        return self._done.is_set()

    def successful(self) -> bool:
        if not self._done.is_set():
            raise ValueError("result not ready")
        return self._error is None


class Pool:
    """Actor-backed process pool (stdlib ``multiprocessing.Pool`` API)."""

    def __init__(
        self,
        processes: Optional[int] = None,
        initializer: Optional[Callable] = None,
        initargs: tuple = (),
        ray_remote_args: Optional[dict] = None,
    ):
        if not api.is_initialized():
            api.init()
        if processes is None:
            processes = max(int(api.cluster_resources().get("CPU", 2)), 1)
        if processes < 1:
            raise ValueError("processes must be >= 1")
        self._processes = processes
        remote_args = dict(ray_remote_args or {})
        remote_args.setdefault("num_cpus", 1)
        worker_cls = api.remote(**remote_args)(_PoolWorker)
        self._actors = [
            worker_cls.remote(initializer, initargs) for _ in range(processes)
        ]
        self._rr = itertools.cycle(range(processes))
        self._closed = False

    # -- lifecycle ----------------------------------------------------------

    def close(self):
        self._closed = True

    def terminate(self):
        self._closed = True
        for a in self._actors:
            api.kill(a)
        self._actors = []

    def join(self):
        if not self._closed:
            raise ValueError("join() before close()")
        # all submissions are synchronous on the actor queue; ping flushes
        if self._actors:
            api.get([a.ping.remote() for a in self._actors])

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.terminate()

    # -- submission ---------------------------------------------------------

    def _check_open(self):
        if self._closed or not self._actors:
            raise ValueError("Pool is closed")

    def _submit_chunks(self, fn, calls, chunksize):
        """calls: list of (args, kwargs); returns refs of list-chunks."""
        self._check_open()
        if chunksize is None:
            chunksize = max(len(calls) // (self._processes * 4), 1)
        refs = []
        for i in range(0, len(calls), chunksize):
            chunk = calls[i : i + chunksize]
            actor = self._actors[next(self._rr)]
            refs.append(actor.run_batch.remote(fn, chunk))
        return refs

    def apply(self, fn: Callable, args: tuple = (), kwds: Optional[dict] = None):
        return self.apply_async(fn, args, kwds).get()

    def apply_async(self, fn, args=(), kwds=None, callback=None,
                    error_callback=None) -> AsyncResult:
        refs = self._submit_chunks(fn, [(tuple(args), kwds or {})], 1)
        return AsyncResult(refs, True, callback, error_callback)

    def map(self, fn: Callable, iterable: Iterable, chunksize=None) -> List[Any]:
        return self.map_async(fn, iterable, chunksize).get()

    def map_async(self, fn, iterable, chunksize=None, callback=None,
                  error_callback=None) -> AsyncResult:
        calls = [((x,), {}) for x in iterable]
        refs = self._submit_chunks(fn, calls, chunksize)
        return AsyncResult(refs, False, callback, error_callback)

    def starmap(self, fn: Callable, iterable: Iterable, chunksize=None):
        return self.starmap_async(fn, iterable, chunksize).get()

    def starmap_async(self, fn, iterable, chunksize=None) -> AsyncResult:
        calls = [(tuple(args), {}) for args in iterable]
        refs = self._submit_chunks(fn, calls, chunksize)
        return AsyncResult(refs, False)

    def _lazy_chunks(self, fn, iterable, chunksize):
        """Submit one chunk at a time from the (possibly infinite) iterable —
        the stdlib imap contract is lazy, bounded-memory submission."""
        self._check_open()
        it = iter(iterable)
        while True:
            chunk = [((x,), {}) for x in itertools.islice(it, chunksize)]
            if not chunk:
                return
            actor = self._actors[next(self._rr)]
            yield actor.run_batch.remote(fn, chunk)

    def imap(self, fn: Callable, iterable: Iterable, chunksize=1):
        """Lazy ordered iterator; keeps ~2x pool-size chunks in flight."""
        window = max(2 * self._processes, 2)
        refs: deque = deque()
        submitter = self._lazy_chunks(fn, iterable, chunksize)
        for ref in itertools.islice(submitter, window):
            refs.append(ref)
        while refs:
            yield from api.get(refs.popleft())
            nxt = next(submitter, None)
            if nxt is not None:
                refs.append(nxt)

    def imap_unordered(self, fn: Callable, iterable: Iterable, chunksize=1):
        window = max(2 * self._processes, 2)
        submitter = self._lazy_chunks(fn, iterable, chunksize)
        pending = list(itertools.islice(submitter, window))
        while pending:
            ready, pending = api.wait(pending, num_returns=1)
            nxt = next(submitter, None)
            if nxt is not None:
                pending.append(nxt)
            yield from api.get(ready[0])
