"""Placement groups: public API.

Role-equivalent of the reference's ray.util.placement_group
(python/ray/util/placement_group.py:146): reserve a gang of resource bundles
across the cluster with PACK/SPREAD/STRICT_PACK/STRICT_SPREAD strategies and
schedule tasks/actors into them. On TPU, bundles with slice label selectors
are the mechanism for reserving ICI-connected hosts (see ray_tpu.util.tpu).
"""

from __future__ import annotations

from typing import Dict, List, Optional

from .. import _worker_api
from .._internal.ids import PlacementGroupID
from .._internal.protocol import (
    Bundle,
    PlacementGroupInfo,
    PlacementGroupState,
    PlacementStrategy,
)


class PlacementGroup:
    def __init__(self, pg_id: PlacementGroupID, bundles: List[Dict[str, float]]):
        self.id = pg_id
        self.bundle_specs = bundles

    def ready(self, timeout: Optional[float] = None) -> bool:
        """Block until all bundles are committed (reference:
        PlacementGroup.wait :93)."""
        worker = _worker_api.get_core_worker()
        return _worker_api.run_on_worker_loop(
            worker.client_pool.get(*worker.gcs_address).call(
                "pg_wait_ready", self.id, timeout
            ),
            timeout=None,
        )

    wait = ready

    @property
    def bundle_count(self) -> int:
        return len(self.bundle_specs)

    def info(self) -> PlacementGroupInfo:
        worker = _worker_api.get_core_worker()
        return _worker_api.run_on_worker_loop(
            worker.client_pool.get(*worker.gcs_address).call(
                "get_placement_group", self.id
            )
        )

    def bundle_node_ids(self) -> List[Optional[str]]:
        info = self.info()
        return [
            b.node_id.hex() if b.node_id is not None else None for b in info.bundles
        ]

    def __reduce__(self):
        return (PlacementGroup, (self.id, self.bundle_specs))


def placement_group(
    bundles: List[Dict[str, float]],
    strategy: str = "PACK",
    name: str = "",
    bundle_label_selector: Optional[List[Dict[str, str]]] = None,
    lifetime: Optional[str] = None,
) -> PlacementGroup:
    if not bundles:
        raise ValueError("placement group needs at least one bundle")
    worker = _worker_api.get_core_worker()
    pg_id = PlacementGroupID.from_random()
    selectors = bundle_label_selector or [{} for _ in bundles]
    if len(selectors) != len(bundles):
        raise ValueError("bundle_label_selector length must match bundles")
    info = PlacementGroupInfo(
        placement_group_id=pg_id,
        name=name,
        strategy=PlacementStrategy[strategy],
        bundles=[
            Bundle(bundle_index=i, resources=dict(b), label_selector=dict(sel))
            for i, (b, sel) in enumerate(zip(bundles, selectors))
        ],
        creator_job_id=worker.job_id,
    )
    _worker_api.run_on_worker_loop(
        worker.client_pool.get(*worker.gcs_address).call(
            "create_placement_group", info
        )
    )
    return PlacementGroup(pg_id, [dict(b) for b in bundles])


def remove_placement_group(pg: PlacementGroup):
    worker = _worker_api.get_core_worker()
    _worker_api.run_on_worker_loop(
        worker.client_pool.get(*worker.gcs_address).call(
            "remove_placement_group", pg.id
        )
    )


def get_placement_group(name: str) -> Optional[PlacementGroup]:
    worker = _worker_api.get_core_worker()
    info = _worker_api.run_on_worker_loop(
        worker.client_pool.get(*worker.gcs_address).call(
            "get_placement_group_by_name", name
        )
    )
    if info is None or info.state == PlacementGroupState.REMOVED:
        return None
    return PlacementGroup(
        info.placement_group_id, [dict(b.resources) for b in info.bundles]
    )


def placement_group_table() -> List[dict]:
    worker = _worker_api.get_core_worker()
    infos = _worker_api.run_on_worker_loop(
        worker.client_pool.get(*worker.gcs_address).call("list_placement_groups")
    )
    return [
        {
            "placement_group_id": i.placement_group_id.hex(),
            "name": i.name,
            "strategy": i.strategy.name,
            "state": i.state.name,
            "bundles": [dict(b.resources) for b in i.bundles],
            "nodes": [b.node_id.hex() if b.node_id else None for b in i.bundles],
        }
        for i in infos
    ]
