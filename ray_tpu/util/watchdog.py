"""Hang watchdog: detect in-flight work stuck past a deadline multiple.

A per-process monitor. Request paths (serve replica requests, collective
epochs) register a watch when work starts and drop it when work ends;
a scan thread wakes about once a second and, for any watch whose elapsed
time exceeds ``multiple x timeout``, captures every thread's Python stack
(``sys._current_frames`` — the importable twin of ``faulthandler``'s
output) into the flight recorder and raises the ``stuck_requests`` gauge.
A watch that later completes emits a recovery event and lowers the gauge,
so transient stalls are distinguishable from true hangs post-mortem.

Tunables (env):
- ``RAY_TPU_WATCHDOG_TIMEOUT_S``  default base timeout when the request
  carries none (default 30).
- ``RAY_TPU_WATCHDOG_MULTIPLE``   stuck threshold as a multiple of the
  base timeout (default 3.0 — a request is "stuck", not merely slow,
  only well past the point its caller gave up).
- ``RAY_TPU_WATCHDOG_INTERVAL_S`` scan period (default 1.0).
"""

from __future__ import annotations

import os
import sys
import threading
import time
import traceback
from typing import Dict, Optional

from . import events

_DEFAULT_TIMEOUT_S = float(os.environ.get("RAY_TPU_WATCHDOG_TIMEOUT_S", "30"))
_DEFAULT_MULTIPLE = float(os.environ.get("RAY_TPU_WATCHDOG_MULTIPLE", "3.0"))
_SCAN_INTERVAL_S = float(os.environ.get("RAY_TPU_WATCHDOG_INTERVAL_S", "1.0"))

_lock = threading.Lock()
_watches: Dict[int, dict] = {}
_next_token = 0
_scanner_started = False


def watch(name: str, timeout_s: Optional[float] = None,
          multiple: Optional[float] = None, **meta) -> int:
    """Register in-flight work; returns a token for :func:`unwatch`.
    ``timeout_s`` is the work's own deadline budget (request timeout,
    collective timeout); the watch fires at ``multiple x timeout_s``."""
    global _next_token
    base = _DEFAULT_TIMEOUT_S if timeout_s is None else float(timeout_s)
    mult = _DEFAULT_MULTIPLE if multiple is None else float(multiple)
    entry = {
        "name": name,
        "start": time.monotonic(),
        "deadline_s": max(base, 0.001) * max(mult, 1.0),
        "meta": meta,
        "stuck": False,
    }
    with _lock:
        _next_token += 1
        token = _next_token
        _watches[token] = entry
    _ensure_scanner()
    return token


def unwatch(token: int) -> None:
    """Drop a watch (work finished — however it finished). Emits a
    recovery event if the watch had already been reported stuck."""
    with _lock:
        entry = _watches.pop(token, None)
    if entry is None:
        return
    if entry["stuck"]:
        events.record_event(
            events.WATCHDOG_RECOVERED,
            watch=entry["name"],
            elapsed_s=round(time.monotonic() - entry["start"], 3),
            **entry["meta"],
        )
        _set_gauge()


def stuck_count() -> int:
    with _lock:
        return sum(1 for e in _watches.values() if e["stuck"])


def capture_stacks() -> str:
    """Every thread's current Python stack as one formatted blob (what
    faulthandler.dump_traceback prints, but capturable as a string)."""
    names = {t.ident: t.name for t in threading.enumerate()}
    chunks = []
    for tid, frame in sys._current_frames().items():
        header = f"Thread {names.get(tid, '?')} ({tid}):"
        chunks.append(
            header + "\n" + "".join(traceback.format_stack(frame))
        )
    return "\n".join(chunks)


def _scan_once() -> None:
    now = time.monotonic()
    newly_stuck = []
    with _lock:
        for entry in _watches.values():
            if not entry["stuck"] and now - entry["start"] > entry["deadline_s"]:
                entry["stuck"] = True
                newly_stuck.append(entry)
    if not newly_stuck:
        return
    # one stack capture per scan, shared by every watch that tripped this
    # tick — capturing is the expensive part, and the stacks are identical
    stacks = capture_stacks()
    for entry in newly_stuck:
        events.record_event(
            events.WATCHDOG_STUCK,
            watch=entry["name"],
            elapsed_s=round(now - entry["start"], 3),
            deadline_s=round(entry["deadline_s"], 3),
            stacks=stacks,
            **entry["meta"],
        )
    _set_gauge()


def _set_gauge() -> None:
    try:
        from .metrics import set_stuck_requests

        set_stuck_requests(stuck_count())
    except Exception:
        pass


def _ensure_scanner() -> None:
    global _scanner_started
    with _lock:
        if _scanner_started:
            return
        _scanner_started = True

    def _loop():
        while True:
            time.sleep(_SCAN_INTERVAL_S)
            try:
                _scan_once()
            except Exception:
                pass  # the watchdog must never be the thing that hangs

    threading.Thread(target=_loop, daemon=True, name="hang-watchdog").start()
