"""Alerting engine + cross-worker straggler detector.

Both run *cluster-side*, driven by the GCS timeseries store on its
evaluation tick (runtime/gcs/timeseries_store.py): the store hands them
its series entries, they hand back verdicts and emit flight-recorder
events through the store's synthetic-event callback — so alerts work
even when the offending worker is too wedged to push anything but its
(old) series history.

AlertEngine: declarative rules (threshold, rate-of-change, burn-rate)
with a firing/resolved lifecycle per (rule, series). A firing alert
carries the trace_id of the most recent exemplar-bearing point in its
window — the timeseries→trace link that turns "TTFT is bad" into "look
at THIS request".

StragglerDetector: median-absolute-deviation comparison of per-worker
step-time medians inside a training group. MAD (not stddev) because the
signal it hunts is exactly the heavy tail that wrecks a stddev; the
``rel_floor`` term keeps a tight group (MAD ~ 0) from flagging noise.
"""

import statistics
import time
from typing import Callable, Dict, List, Optional

from . import events as _events

# point layout inside store entries: [ts, value, exemplar]
_TS, _VALUE, _EXEMPLAR = 0, 1, 2

EmitFn = Callable[..., None]  # emit(event_name, **fields)

_RULE_KINDS = ("threshold", "rate_of_change", "burn_rate")
_CMPS = ("gt", "lt")


class AlertRule:
    """One declarative rule. JSON-round-trippable (rules persist in the
    GCS storage backend next to the series they watch)."""

    def __init__(self, name: str, series: str, kind: str = "threshold",
                 threshold: float = 0.0, cmp: str = "gt",
                 window_s: float = 60.0, for_s: float = 0.0,
                 burn_fraction: float = 0.5,
                 labels: Optional[dict] = None):
        if kind not in _RULE_KINDS:
            raise ValueError(f"unknown rule kind {kind!r}; one of {_RULE_KINDS}")
        if cmp not in _CMPS:
            raise ValueError(f"unknown cmp {cmp!r}; one of {_CMPS}")
        self.name = str(name)
        self.series = str(series)
        self.kind = kind
        self.threshold = float(threshold)
        self.cmp = cmp
        self.window_s = float(window_s)
        self.for_s = float(for_s)
        self.burn_fraction = float(burn_fraction)
        self.labels = {str(k): str(v) for k, v in (labels or {}).items()}

    def to_dict(self) -> dict:
        return {
            "name": self.name, "series": self.series, "kind": self.kind,
            "threshold": self.threshold, "cmp": self.cmp,
            "window_s": self.window_s, "for_s": self.for_s,
            "burn_fraction": self.burn_fraction, "labels": self.labels,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "AlertRule":
        return cls(**{k: d[k] for k in (
            "name", "series", "kind", "threshold", "cmp", "window_s",
            "for_s", "burn_fraction", "labels") if k in d})

    def matches(self, entry: dict) -> bool:
        if entry.get("name") != self.series:
            return False
        labels = entry.get("labels") or {}
        return all(labels.get(k) == v for k, v in self.labels.items())

    def signal(self, window: List[list]) -> Optional[float]:
        """Collapse the in-window points to the value the rule compares."""
        if not window:
            return None
        if self.kind == "threshold":
            return window[-1][_VALUE]
        if self.kind == "rate_of_change":
            span = window[-1][_TS] - window[0][_TS]
            if span <= 0 or len(window) < 2:
                return None
            return (window[-1][_VALUE] - window[0][_VALUE]) / span
        # burn_rate: fraction of the window violating the threshold —
        # error-budget burn, fires on sustained violation, not one spike
        bad = sum(1 for p in window if self._violates(p[_VALUE]))
        return bad / len(window)

    def _violates(self, value: float) -> bool:
        return value > self.threshold if self.cmp == "gt" \
            else value < self.threshold

    def breached(self, signal: float) -> bool:
        if self.kind == "burn_rate":
            return signal >= self.burn_fraction
        return self._violates(signal)


def _window_exemplar(window: List[list]) -> Optional[str]:
    for p in reversed(window):
        if len(p) > _EXEMPLAR and p[_EXEMPLAR]:
            return p[_EXEMPLAR]
    return None


class AlertEngine:
    """Rule registry + firing/resolved lifecycle.

    State machine per (rule, series): ok -> pending (breached, waiting
    out ``for_s``) -> firing -> ok.  Transitions into/out of firing emit
    ALERT_FIRING / ALERT_RESOLVED events and append to a bounded
    transition log (the dashboard's and CLI's history surface).
    """

    LOG_CAP = 512

    def __init__(self):
        self._rules: Dict[str, AlertRule] = {}
        # (rule_name, series_id) -> {"state", "since", "value", ...}
        self._states: Dict[tuple, dict] = {}
        self.log: List[dict] = []

    # -- rule registry -------------------------------------------------------

    def set_rule(self, rule: AlertRule) -> None:
        self._rules[rule.name] = rule

    def delete_rule(self, name: str) -> bool:
        self._states = {
            k: v for k, v in self._states.items() if k[0] != name
        }
        return self._rules.pop(name, None) is not None

    def rules(self) -> List[dict]:
        return [r.to_dict() for r in self._rules.values()]

    def get_rule(self, name: str) -> Optional[AlertRule]:
        return self._rules.get(name)

    # -- evaluation ----------------------------------------------------------

    def evaluate(self, entries: List[dict], now: Optional[float] = None,
                 emit: Optional[EmitFn] = None) -> None:
        if now is None:
            now = time.time()
        seen = set()
        for rule in list(self._rules.values()):
            for entry in entries:
                if not rule.matches(entry):
                    continue
                key = (rule.name, entry["id"])
                seen.add(key)
                window = [
                    p for p in entry.get("points", ())
                    if p[_TS] >= now - rule.window_s
                ]
                signal = rule.signal(window)
                self._step(rule, entry, key, signal, window, now, emit)
        # series that vanished (retention reaped them) resolve their alerts
        for key in [k for k in self._states if k not in seen]:
            st = self._states.pop(key)
            if st["state"] == "firing":
                self._transition(key, st, "resolved", now, emit,
                                 reason="series_gone")

    def _step(self, rule: AlertRule, entry: dict, key: tuple,
              signal: Optional[float], window: List[list], now: float,
              emit: Optional[EmitFn]) -> None:
        st = self._states.setdefault(key, {
            "state": "ok", "since": now, "rule": rule.name,
            "series_id": entry["id"], "series": entry.get("name"),
            "labels": entry.get("labels") or {},
            "worker_id": entry.get("worker_id", ""),
            "node_id": entry.get("node_id", ""),
        })
        breached = signal is not None and rule.breached(signal)
        st["value"] = signal
        st["threshold"] = rule.threshold
        st["exemplar"] = _window_exemplar(window) or st.get("exemplar")
        if breached:
            if st["state"] == "ok":
                st["state"], st["since"] = "pending", now
            if st["state"] == "pending" and now - st["since"] >= rule.for_s:
                self._transition(key, st, "firing", now, emit)
        else:
            if st["state"] == "firing":
                self._transition(key, st, "resolved", now, emit)
            st["state"], st["since"] = "ok", now

    def _transition(self, key: tuple, st: dict, to: str, now: float,
                    emit: Optional[EmitFn], **extra) -> None:
        st["state"] = "firing" if to == "firing" else "ok"
        st["since"] = now
        row = {
            "ts": now, "transition": to, "rule": st["rule"],
            "series_id": st["series_id"], "series": st.get("series"),
            "labels": st.get("labels"), "worker_id": st.get("worker_id"),
            "node_id": st.get("node_id"), "value": st.get("value"),
            "threshold": st.get("threshold"),
            "exemplar": st.get("exemplar"),
        }
        row.update(extra)
        self.log.append(row)
        del self.log[:-self.LOG_CAP]
        if emit is not None:
            name = (_events.ALERT_FIRING if to == "firing"
                    else _events.ALERT_RESOLVED)
            emit(name, **{k: v for k, v in row.items() if k != "ts"})

    # -- read surface --------------------------------------------------------

    def active(self) -> List[dict]:
        return [
            {
                "rule": st["rule"], "series_id": st["series_id"],
                "series": st.get("series"), "labels": st.get("labels"),
                "worker_id": st.get("worker_id"),
                "node_id": st.get("node_id"), "state": st["state"],
                "since": st["since"], "value": st.get("value"),
                "threshold": st.get("threshold"),
                "exemplar": st.get("exemplar"),
            }
            for st in self._states.values() if st["state"] == "firing"
        ]


class StragglerDetector:
    """MAD outlier detection of per-worker step time inside a group.

    For each training group (series labelled with ``group``), take each
    worker's median step time over the trailing window, then flag any
    worker whose median exceeds
    ``group_median + max(k * 1.4826 * MAD, rel_floor * group_median)``.
    1.4826 scales MAD to a stddev-consistent estimator; ``rel_floor``
    (default 25% over median) stops a perfectly uniform group — MAD
    zero — from alerting on scheduler jitter.  Needs >= 3 workers so a
    median and deviation are meaningful.
    """

    def __init__(self, k: float = 3.0, rel_floor: float = 0.25,
                 window_s: float = 120.0, min_points: int = 2,
                 min_workers: int = 3):
        self.k = k
        self.rel_floor = rel_floor
        self.window_s = window_s
        self.min_points = min_points
        self.min_workers = min_workers
        # (group, series_id) -> {"firing": bool, "since": ts}
        self._states: Dict[tuple, dict] = {}
        self._verdicts: List[dict] = []

    def evaluate(self, entries: List[dict], now: Optional[float] = None,
                 emit: Optional[EmitFn] = None) -> List[dict]:
        if now is None:
            now = time.time()
        groups: Dict[str, List[dict]] = {}
        for entry in entries:
            if entry.get("name") != "step_time_s":
                continue
            group = (entry.get("labels") or {}).get("group") or \
                (entry.get("labels") or {}).get("run") or "?"
            groups.setdefault(group, []).append(entry)

        verdicts: List[dict] = []
        for group, members in groups.items():
            rows = []
            for entry in members:
                window = [
                    p for p in entry.get("points", ())
                    if p[_TS] >= now - self.window_s
                ]
                if len(window) < self.min_points:
                    continue
                rows.append((entry, window,
                             statistics.median(p[_VALUE] for p in window)))
            if len(rows) < self.min_workers:
                continue
            medians = [m for _, _, m in rows]
            group_median = statistics.median(medians)
            mad = statistics.median(abs(m - group_median) for m in medians)
            cutoff = group_median + max(
                self.k * 1.4826 * mad, self.rel_floor * group_median
            )
            for entry, window, worker_median in rows:
                key = (group, entry["id"])
                st = self._states.setdefault(
                    key, {"firing": False, "since": now})
                firing = worker_median > cutoff
                labels = entry.get("labels") or {}
                verdict = {
                    "group": group,
                    "series_id": entry["id"],
                    "worker_id": entry.get("worker_id", ""),
                    "node_id": entry.get("node_id", ""),
                    "rank": labels.get("rank"),
                    "run": labels.get("run"),
                    "median_s": worker_median,
                    "group_median_s": group_median,
                    "mad_s": mad,
                    "cutoff_s": cutoff,
                    "deviation": (worker_median - group_median)
                    / group_median if group_median else 0.0,
                    "straggler": firing,
                    "since": st["since"] if firing == st["firing"] else now,
                }
                if firing and not st["firing"]:
                    st.update(firing=True, since=now)
                    if emit is not None:
                        emit(
                            _events.STRAGGLER_DETECTED,
                            group=group,
                            worker_id=verdict["worker_id"],
                            node_id=verdict["node_id"],
                            rank=verdict["rank"],
                            median_s=worker_median,
                            group_median_s=group_median,
                            cutoff_s=cutoff,
                            exemplar=_window_exemplar(window),
                            # the offending series tail travels with the
                            # event so the post-mortem needs no extra query
                            series_tail=[
                                [p[_TS], p[_VALUE]] for p in window[-16:]
                            ],
                        )
                elif st["firing"] and not firing:
                    st.update(firing=False, since=now)
                    if emit is not None:
                        emit(
                            _events.STRAGGLER_RESOLVED,
                            group=group,
                            worker_id=verdict["worker_id"],
                            node_id=verdict["node_id"],
                            rank=verdict["rank"],
                            median_s=worker_median,
                            group_median_s=group_median,
                        )
                verdicts.append(verdict)
        verdicts.sort(key=lambda v: v["deviation"], reverse=True)
        self._verdicts = verdicts
        return verdicts

    def verdicts(self) -> List[dict]:
        """Latest per-worker rows, sorted by step-time deviation (what
        ``ray_tpu top`` renders)."""
        return list(self._verdicts)
