"""Per-process telemetry time-series plane.

Everything else in the observability stack is either a point-in-time
snapshot (util/metrics.py counters and gauges, overwritten on every
push) or a post-mortem ring (util/events.py, util/tracing.py).  This
module keeps *history*: a :class:`TelemetryStream` samples registered
series (step time, exposed-collective fraction, KV-pool occupancy,
transfer bytes, RPC latency, ...) into fixed-size downsampling ring
buffers and pushes raw deltas to the GCS-backed store
(`runtime/gcs/timeseries_store.py`, ``ts:`` keys) where the straggler
detector and alert engine evaluate them cluster-side.

Series names form a closed registry, exactly like event names
(util/events.py) and metric declarations (util/metrics.py): every
series recorded anywhere in the tree must be a :class:`SeriesName`
constant declared in THIS file, and label sets must be statically
bounded — both enforced by lint rule RT012
(analysis/checkers/rt012_series_registry.py).

Hot-path budget: ``Series.record`` is one lock plus two list appends
(bench: ``ray_tpu perf`` asserts <1% step-time overhead with sampling
enabled).  Heavier signals (RPC latency, transfer bytes) are pulled by
*samplers* on the push cadence instead of being recorded inline.
"""

import json
import os
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

# -- series-name registry (lint rule RT012 enforces closure) -----------------

_registry: Dict[str, str] = {}
_registry_lock = threading.Lock()


class SeriesName(str):
    """A declared time-series name. Instantiating registers the name;
    duplicates raise so the registry in this file stays the single
    source of truth (mirrors util/events.py EventName)."""

    def __new__(cls, name: str, doc: str = ""):
        with _registry_lock:
            if name in _registry:
                raise ValueError(f"duplicate series name: {name!r}")
            _registry[name] = doc
        return super().__new__(cls, name)


def registered_series_names() -> Dict[str, str]:
    with _registry_lock:
        return dict(_registry)


# -- the series taxonomy -----------------------------------------------------

STEP_TIME_S = SeriesName(
    "step_time_s",
    "Per-worker wall-clock seconds between training step reports; the "
    "straggler detector's input signal.",
)
EXPOSED_COLLECTIVE_FRACTION = SeriesName(
    "exposed_collective_fraction",
    "Fraction of a gradient collective NOT hidden under backward "
    "compute, tagged with the collective group and epoch.",
)
KV_POOL_OCCUPANCY = SeriesName(
    "kv_pool_occupancy",
    "KV block pool occupancy fraction (blocks in use / capacity).",
)
TRANSFER_BYTES = SeriesName(
    "transfer_bytes",
    "Bytes moved by the transfer planes (collective wire + weight wire "
    "+ kvtier wire) per sample interval; sampler-driven delta.",
)
RPC_LATENCY_MS = SeriesName(
    "rpc_latency_ms",
    "Mean client RPC round-trip latency over the sample interval (ms); "
    "sampler-driven delta over the rpc_client_latency_ms histogram.",
)
INPUT_WAIT_S = SeriesName(
    "input_wait_s",
    "Per-step seconds the trainer blocked waiting on input. Declared "
    "ahead of the streaming data plane (ROADMAP item 4); no producer "
    "records it yet.",
)
SERVE_TTFT_S = SeriesName(
    "serve_ttft_s",
    "Per-replica time-to-first-token seconds; points carry the request "
    "trace_id as an exemplar so alerts link to a representative trace.",
)
SERVE_QUEUE_DEPTH = SeriesName(
    "serve_queue_depth",
    "Per-replica queued request count, sampled on the push cadence.",
)


# -- downsampling ring -------------------------------------------------------

# point layout (lists, not dicts: they travel through JSON a lot)
TS_FIRST, TS_LAST, SUM, MIN, MAX, COUNT, EXEMPLAR = range(7)


def merge_points(a: list, b: list) -> list:
    """Merge two adjacent aggregate points (b follows a in time)."""
    return [
        a[TS_FIRST],
        b[TS_LAST],
        a[SUM] + b[SUM],
        min(a[MIN], b[MIN]),
        max(a[MAX], b[MAX]),
        a[COUNT] + b[COUNT],
        b[EXEMPLAR] or a[EXEMPLAR],
    ]


def point_dict(p: list) -> dict:
    """Render an aggregate point for API surfaces."""
    return {
        "ts": p[TS_LAST],
        "ts_first": p[TS_FIRST],
        "value": p[SUM] / p[COUNT] if p[COUNT] else 0.0,
        "min": p[MIN],
        "max": p[MAX],
        "count": p[COUNT],
        "exemplar": p[EXEMPLAR],
    }


class DownsamplingRing:
    """Fixed-capacity time series that degrades resolution, not span.

    Raw samples accumulate into the newest point until that point holds
    ``stride`` of them; when the buffer would exceed ``capacity`` whole
    points, adjacent pairs merge and the stride doubles.  Invariants
    (pinned by tests/test_timeseries.py): total sample count and sum are
    preserved exactly, min/max never tighten, and the buffer never
    exceeds ``capacity`` points — so a long-running series keeps its
    full history at geometrically coarser resolution instead of
    silently forgetting the oldest half.
    """

    def __init__(self, capacity: int = 256):
        if capacity < 2:
            raise ValueError("capacity must be >= 2")
        self._capacity = capacity
        self._stride = 1
        self._points: List[list] = []
        self._lock = threading.Lock()

    def append(self, ts: float, value: float, exemplar=None) -> None:
        with self._lock:
            pts = self._points
            if pts and pts[-1][COUNT] < self._stride:
                p = pts[-1]
                p[TS_LAST] = ts
                p[SUM] += value
                if value < p[MIN]:
                    p[MIN] = value
                if value > p[MAX]:
                    p[MAX] = value
                p[COUNT] += 1
                if exemplar is not None:
                    p[EXEMPLAR] = exemplar
                return
            pts.append([ts, ts, value, value, value, 1, exemplar])
            if len(pts) > self._capacity:
                merged = [
                    merge_points(pts[i], pts[i + 1])
                    for i in range(0, len(pts) - 1, 2)
                ]
                if len(pts) % 2:
                    merged.append(pts[-1])
                self._points = merged
                self._stride *= 2

    @property
    def stride(self) -> int:
        return self._stride

    def __len__(self) -> int:
        with self._lock:
            return len(self._points)

    def total_count(self) -> int:
        with self._lock:
            return sum(p[COUNT] for p in self._points)

    def points(self) -> List[dict]:
        with self._lock:
            return [point_dict(p) for p in self._points]

    def last(self) -> Optional[dict]:
        with self._lock:
            return point_dict(self._points[-1]) if self._points else None


# -- series + stream ---------------------------------------------------------

_PENDING_CAP = 4096


def labels_key(labels: Optional[dict]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((str(k), str(v)) for k, v in (labels or {}).items()))


class Series:
    """One (name, labels) stream: a local downsampling ring for in-process
    reads plus a raw pending buffer drained by the GCS pusher."""

    def __init__(self, name: str, labels: Optional[dict] = None, *,
                 capacity: int = 256,
                 sampler: Optional[Callable[[], Optional[float]]] = None):
        self.name = str(name)
        self.labels = {str(k): str(v) for k, v in (labels or {}).items()}
        self.sampler = sampler
        self.ring = DownsamplingRing(capacity)
        self._pending: List[list] = []
        self._pending_dropped = 0
        self._lock = threading.Lock()

    def record(self, value: float, ts: Optional[float] = None,
               exemplar: Optional[str] = None) -> None:
        """Hot path: one lock, two appends. Never raises."""
        if not _enabled:
            return
        if ts is None:
            ts = time.time()
        value = float(value)
        self.ring.append(ts, value, exemplar)
        with self._lock:
            self._pending.append([ts, value, exemplar])
            if len(self._pending) > _PENDING_CAP:
                drop = len(self._pending) - _PENDING_CAP
                del self._pending[:drop]
                self._pending_dropped += drop

    def drain(self) -> List[list]:
        with self._lock:
            out, self._pending = self._pending, []
        return out

    def requeue(self, points: List[list]) -> None:
        """Put an unsent batch back at the front (push failed)."""
        with self._lock:
            self._pending[:0] = points
            if len(self._pending) > _PENDING_CAP:
                drop = len(self._pending) - _PENDING_CAP
                del self._pending[:drop]
                self._pending_dropped += drop


class TelemetryStream:
    """Process-wide registry of :class:`Series` plus the push loop.

    ``register`` is idempotent per (name, labels) and is the RT012
    chokepoint: names must be SeriesName constants from this module.
    Sampler-backed series are polled once per push tick so their cost
    never lands on a request or step hot path.
    """

    def __init__(self, push_period_s: Optional[float] = None):
        self.push_period_s = push_period_s if push_period_s is not None else \
            float(os.environ.get("RAY_TPU_TS_PUSH_PERIOD_S", "2.0"))
        self._series: Dict[Tuple[str, tuple], Series] = {}
        self._lock = threading.Lock()
        self._pusher_started = False

    def register(self, name: str, labels: Optional[dict] = None, *,
                 sampler: Optional[Callable[[], Optional[float]]] = None,
                 capacity: int = 256) -> Series:
        key = (str(name), labels_key(labels))
        with self._lock:
            s = self._series.get(key)
            if s is None:
                s = Series(name, labels, capacity=capacity, sampler=sampler)
                self._series[key] = s
            elif sampler is not None and s.sampler is None:
                s.sampler = sampler
        self._ensure_pusher()
        return s

    def get(self, name: str, labels: Optional[dict] = None) -> Optional[Series]:
        with self._lock:
            return self._series.get((str(name), labels_key(labels)))

    def series(self) -> List[Series]:
        with self._lock:
            return list(self._series.values())

    def sample_once(self, now: Optional[float] = None) -> None:
        """Poll every sampler-backed series once. Called on the push
        cadence (and directly by tests / flush)."""
        if now is None:
            now = time.time()
        for s in self.series():
            if s.sampler is None:
                continue
            try:
                v = s.sampler()
            except Exception:
                continue
            if v is not None:
                s.record(float(v), ts=now)

    # -- push plane ----------------------------------------------------------

    def build_payload(self) -> Optional[dict]:
        """Drain pending points into one identity-tagged delta payload
        (None when there is nothing to send). Callers that fail to
        deliver it should ``requeue_payload`` so points survive a
        transient GCS outage."""
        from .. import _worker_api
        from . import metrics as _metrics

        series_out = []
        for s in self.series():
            batch = s.drain()
            if batch:
                series_out.append({
                    "name": s.name,
                    "labels": s.labels,
                    "points": batch,
                })
        if not series_out:
            return None
        worker = _worker_api.maybe_get_core_worker()
        return {
            "worker_id": worker.worker_id.hex() if worker else "",
            "node_id": _metrics._node_hex(),
            "pid": os.getpid(),
            "ts": time.time(),
            "series": series_out,
        }

    def requeue_payload(self, payload: dict) -> None:
        for row in payload.get("series", ()):
            s = self.register(row["name"], row["labels"])
            s.requeue(row["points"])

    def flush(self) -> bool:
        """Sample, then push pending deltas to the GCS store. Returns
        True when a payload was delivered. Safe (no-op) with no cluster."""
        from .. import _worker_api

        self.sample_once()
        payload = self.build_payload()
        if payload is None:
            return False
        worker = _worker_api.maybe_get_core_worker()
        if worker is None:
            self.requeue_payload(payload)
            return False
        try:
            _worker_api.run_on_worker_loop(
                worker.client_pool.get(*worker.gcs_address).call(
                    "ts_push", payload
                ),
                timeout=5,
            )
            return True
        except Exception:
            self.requeue_payload(payload)
            return False

    def _ensure_pusher(self) -> None:
        with self._lock:
            if self._pusher_started:
                return
            self._pusher_started = True

        def _loop():
            while True:
                time.sleep(self.push_period_s)
                try:
                    self.flush()
                except Exception:
                    pass  # telemetry is best-effort; never take down the host

        threading.Thread(
            target=_loop, daemon=True, name="telemetry-push"
        ).start()


# -- module-level singleton + convenience ------------------------------------

_stream: Optional[TelemetryStream] = None
_stream_lock = threading.Lock()
_enabled = os.environ.get("RAY_TPU_TELEMETRY", "1") != "0"


def set_enabled(flag: bool) -> bool:
    """Toggle the record() hot path (the perf bench's on/off switch).
    Returns the previous value."""
    global _enabled
    prev, _enabled = _enabled, bool(flag)
    return prev


def telemetry_enabled() -> bool:
    return _enabled


def get_stream() -> TelemetryStream:
    global _stream
    if _stream is None:
        with _stream_lock:
            if _stream is None:
                stream = TelemetryStream()
                _install_default_samplers(stream)
                # assigned last: its non-None-ness gates the fast path, so
                # the default samplers must already exist when readers see it
                _stream = stream
    return _stream


def register_series(name: str, labels: Optional[dict] = None, *,
                    sampler: Optional[Callable[[], Optional[float]]] = None,
                    capacity: int = 256) -> Series:
    """The canonical emitter entry point (what RT012 audits): ``name``
    must be a SeriesName constant declared in this module and ``labels``
    a statically bounded dict literal."""
    return get_stream().register(
        name, labels, sampler=sampler, capacity=capacity
    )


def flush_stream() -> bool:
    """Synchronous flush for tests and the graceful-shutdown path."""
    if _stream is None:
        return False
    return _stream.flush()


def _reset_for_tests() -> None:
    global _stream
    with _stream_lock:
        _stream = None


def _install_default_samplers(stream: TelemetryStream) -> None:
    """Sampler-backed cluster-health series every process exports: delta
    mean RPC latency and delta transfer-plane bytes per push interval.
    Samplers read process-local metric state (no RPCs) and return None
    when nothing changed, so idle processes stay silent."""
    from . import metrics as _metrics

    state = {"rpc_sum": 0.0, "rpc_count": 0, "xfer": 0.0}

    def _rpc_latency_delta() -> Optional[float]:
        latency, _, _ = _metrics._ensure_rpc_metrics()
        with latency._lock:
            total_sum = sum(latency._sums.values())
            total_count = sum(
                sum(counts) for counts in latency._counts.values()
            )
        d_sum = total_sum - state["rpc_sum"]
        d_count = total_count - state["rpc_count"]
        state["rpc_sum"], state["rpc_count"] = total_sum, total_count
        return d_sum / d_count if d_count > 0 else None

    def _counter_total(name: str) -> float:
        with _metrics._registry_lock:
            m = _metrics._registry.get(name)
        if m is None:
            return 0.0
        with m._lock:
            return sum(m._values.values())

    def _transfer_bytes_delta() -> Optional[float]:
        total = (
            _counter_total("collective_wire_bytes_total")
            + _counter_total("weights_wire_bytes_total")
            + _counter_total("kvtier_transfer_bytes_total")
        )
        delta, state["xfer"] = total - state["xfer"], total
        return delta if delta > 0 else None

    stream.register(RPC_LATENCY_MS, sampler=_rpc_latency_delta)
    stream.register(TRANSFER_BYTES, sampler=_transfer_bytes_delta)


def series_table() -> List[dict]:
    """In-process view of every registered series (the clusterless
    debugging surface; the dashboard reads the GCS store instead)."""
    if _stream is None:
        return []
    out = []
    for s in _stream.series():
        last = s.ring.last()
        out.append({
            "name": s.name,
            "labels": s.labels,
            "points": s.ring.total_count(),
            "stride": s.ring.stride,
            "last": last,
        })
    return out


def series_id(name: str, labels: Optional[dict], worker_id: str = "") -> str:
    """Stable id for one (name, labels, worker) stream — the tail of its
    ``ts:`` GCS key. Deterministic so re-pushes append, not fork."""
    lk = labels_key(labels)
    blob = json.dumps(lk, separators=(",", ":"))
    import hashlib

    digest = hashlib.sha1(
        (worker_id + "|" + blob).encode()
    ).hexdigest()[:10]
    return f"{name}:{digest}"
