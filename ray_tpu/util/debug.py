"""Remote debugger for tasks and actors.

Role-equivalent of the reference's distributed debugger (ray.util.rpdb /
util/debugpy.py + the `ray debug` CLI): ``set_trace()`` inside remote code
opens a TCP pdb server on the worker's node, advertises the session in the
GCS KV under the ``debug:`` prefix, and blocks until a client attaches;
``ray_tpu debug`` lists advertised sessions and bridges the local terminal
to one. Post-mortem entry on task failure is gated by the
``RAY_TPU_POSTMORTEM=1`` env var (reference: RAY_DEBUG_POST_MORTEM).
"""

from __future__ import annotations

import json
import os
import pdb
import socket
import sys
import time
import uuid
from typing import Optional

from .. import _worker_api
from ..runtime.gcs import keys as gcs_keys

def _accept_timeout_s() -> float:
    return float(os.environ.get("RAY_TPU_DEBUGGER_TIMEOUT_S", "600"))


class _SocketIO:
    """File-like adapter pdb can use for stdin/stdout over a TCP socket."""

    def __init__(self, conn: socket.socket):
        self._conn = conn
        self._rfile = conn.makefile("r", encoding="utf-8", errors="replace")

    def readline(self):
        return self._rfile.readline()

    def write(self, data: str):
        try:
            self._conn.sendall(data.encode("utf-8", errors="replace"))
        except OSError:
            pass
        return len(data)

    def flush(self):
        pass

    def close(self):
        try:
            self._rfile.close()
        except Exception:
            pass
        try:
            self._conn.close()
        except Exception:
            pass


class _RemotePdb(pdb.Pdb):
    """pdb over a socket. With ``close_on_detach`` the socket is torn down
    when the session ends (continue/quit) — needed for breakpoint sessions,
    where the interaction happens after set_trace() has already returned
    into user code and no enclosing scope can close the socket."""

    def __init__(self, io: _SocketIO, close_on_detach: bool = False):
        super().__init__(stdin=io, stdout=io, nosigint=True)
        self.prompt = "(ray_tpu-pdb) "
        self._io = io
        self._close_on_detach = close_on_detach

    def set_continue(self):
        super().set_continue()
        if self._close_on_detach:
            self._io.close()

    def set_quit(self):
        super().set_quit()
        if self._close_on_detach:
            self._io.close()


def _kv_call(method: str, *args) -> Optional[object]:
    """Best-effort GCS KV access from wherever we are (driver, task thread).
    Returns None when the loop is unreachable (e.g. called on the worker's
    own event loop from an async actor) — the session still works, it is
    just not discoverable through `ray_tpu debug`."""
    try:
        worker = _worker_api.get_core_worker()
        gcs = worker.client_pool.get(*worker.gcs_address)
        return _worker_api.run_on_worker_loop(gcs.call(method, *args), timeout=10)
    except Exception:
        return None


def _session_context() -> dict:
    ctx = {"pid": os.getpid(), "ts": time.time()}
    try:
        from ..runtime_context import get_runtime_context

        ctx.update(get_runtime_context().get())
    except Exception:
        pass
    return ctx


def _auth_token() -> str:
    cfg = _worker_api.get_config()
    return getattr(cfg, "cluster_auth_token", "") or "" if cfg else ""


def _bind_host() -> str:
    """Bind where the cluster control plane is reachable — never wider.
    Same rule as the native transfer plane (store.cc rt_transfer_serve):
    a debugger socket is arbitrary code execution, so it must not listen
    on interfaces the RPC plane doesn't."""
    try:
        worker = _worker_api.get_core_worker()
        host = worker.gcs_address[0]
        if host not in ("127.0.0.1", "localhost", ""):
            # cluster spans hosts: listen on the interface that routes there
            probe = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            try:
                probe.connect((host, 1))
                return probe.getsockname()[0]
            finally:
                probe.close()
    except Exception:
        pass
    return "127.0.0.1"


def _serve_session(reason: str, run):
    """Open the TCP server, advertise, accept one client, and hand its
    socket IO to ``run(io)``. When the cluster has an auth token, the
    client must send it as the first line before getting a prompt."""
    server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    host = _bind_host()
    server.bind((host, 0))
    server.listen(1)
    port = server.getsockname()[1]
    session_id = uuid.uuid4().hex[:12]
    info = {**_session_context(), "host": host, "port": port, "reason": reason}
    key = gcs_keys.DEBUG_SESSION.key(session_id)
    _kv_call("kv_put", key, json.dumps(info).encode(), True)
    print(
        f"RAY_TPU DEBUGGER: {reason} — waiting for a client at "
        f"{host}:{port} (session {session_id}); attach with: "
        f"ray_tpu debug --address <head> {session_id}",
        flush=True,
    )
    timeout_s = _accept_timeout_s()
    token = _auth_token()
    deadline = time.time() + timeout_s
    io = None
    try:
        # accept until an AUTHENTICATED client arrives or the deadline
        # passes: a port scanner or wrong-token client must not consume the
        # one-shot session and silently skip the developer's breakpoint
        while time.time() < deadline:
            server.settimeout(max(deadline - time.time(), 0.1))
            try:
                conn, _addr = server.accept()
            except socket.timeout:
                break
            candidate = _SocketIO(conn)
            if token:
                conn.settimeout(30)
                try:
                    presented = candidate.readline().rstrip("\n")
                except OSError:  # includes socket.timeout
                    presented = None
                conn.settimeout(None)
                if presented != token:
                    candidate.write("authentication failed\n")
                    candidate.close()
                    print(
                        "RAY_TPU DEBUGGER: rejected unauthenticated client; "
                        "still waiting",
                        flush=True,
                    )
                    continue
            io = candidate
            break
    finally:
        _kv_call("kv_del", key)
        server.close()
    if io is None:
        print(
            f"RAY_TPU DEBUGGER: no client within {timeout_s:.0f}s; continuing",
            flush=True,
        )
        return
    # run() owns the io lifetime: post-mortem closes it on return; a
    # breakpoint session hands it to the debugger, which closes it when the
    # user continues/quits (the interaction outlives this call).
    run(io)


def set_trace(frame=None):
    """Breakpoint. In a driver on a TTY this is plain pdb; in remote code it
    opens a remote-attach session (reference: ray.util.rpdb.set_trace)."""
    frame = frame or sys._getframe().f_back
    worker = _worker_api.maybe_get_core_worker()
    is_driver = worker is not None and getattr(worker, "mode", None) is not None \
        and getattr(worker.mode, "name", "") == "DRIVER"
    if (worker is None or is_driver) and sys.stdin is not None and sys.stdin.isatty():
        debugger = pdb.Pdb(nosigint=True)
        debugger.set_trace(frame)
        return

    def run(io: _SocketIO):
        debugger = _RemotePdb(io, close_on_detach=True)
        # Bdb.set_trace()-equivalent, except the stop target is pinned to the
        # USER frame: plain set_step() would halt at the very next 'call'
        # event, which is this module's own socket/cleanup machinery.
        debugger.reset()
        f = frame
        while f:
            f.f_trace = debugger.trace_dispatch
            debugger.botframe = f
            f = f.f_back
        try:
            debugger._set_stopinfo(frame, None)
        except TypeError:  # future signature drift: degrade to plain stepping
            debugger.set_step()
        sys.settrace(debugger.trace_dispatch)

    _serve_session("breakpoint", run)


def post_mortem(traceback=None):
    """Debug an exception's traceback remotely (reference: post-mortem mode
    of the distributed debugger)."""
    if traceback is None:
        traceback = sys.exc_info()[2]
    if traceback is None:
        raise ValueError("no traceback to debug")

    def run(io: _SocketIO):
        try:
            debugger = _RemotePdb(io)
            debugger.reset()
            debugger.interaction(None, traceback)
        finally:
            io.close()

    _serve_session("post-mortem", run)


def post_mortem_enabled() -> bool:
    return os.environ.get("RAY_TPU_POSTMORTEM") == "1"


def list_sessions() -> dict:
    """Advertised debug sessions: session id -> info dict."""
    keys = _kv_call("kv_keys", gcs_keys.DEBUG_SESSION.scan) or []
    out = {}
    for key in keys:
        raw = _kv_call("kv_get", key)
        if raw:
            try:
                out[key.split(":", 1)[1]] = json.loads(bytes(raw).decode())
            except (ValueError, TypeError):
                pass
    return out


def attach(session_id: str, stdin=None, stdout=None) -> bool:
    """Bridge the local terminal to a remote pdb session. Returns False if
    the session is unknown."""
    import threading

    stdin = stdin if stdin is not None else sys.stdin
    stdout = stdout if stdout is not None else sys.stdout
    sessions = list_sessions()
    matches = [sid for sid in sessions if sid.startswith(session_id)]
    if not matches:
        return False
    info = sessions[matches[0]]
    conn = socket.create_connection((info["host"], info["port"]), timeout=10)
    token = _auth_token()
    if token:
        conn.sendall(f"{token}\n".encode())

    # stdin pumps in a daemon thread; the MAIN thread drains the remote so
    # attach() returns the moment the debuggee continues/quits — a blocking
    # stdin.readline() in the main thread would otherwise hold the CLI
    # hostage until one extra Enter after the session already ended.
    def pump_local_to_remote():
        try:
            while True:
                line = stdin.readline()
                if not line:
                    break
                conn.sendall(line.encode("utf-8"))
        except OSError:
            pass

    thread = threading.Thread(target=pump_local_to_remote, daemon=True)
    thread.start()
    try:
        while True:
            data = conn.recv(4096)
            if not data:
                break
            stdout.write(data.decode("utf-8", errors="replace"))
            stdout.flush()
    except OSError:
        pass
    finally:
        try:
            conn.close()
        except OSError:
            pass
    return True
