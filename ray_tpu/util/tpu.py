"""TPU slice reservation.

Role-equivalent of the reference's ray.util.tpu + reserve_tpu_slice
(_private/accelerators/tpu.py:213, util/tpu.py:52,227): reserve every host of
an ICI-connected TPU slice through one placement group so gang workloads land
on one ICI domain, and reserve several slices for multislice (DCN) jobs.

Mechanism (mirrors the reference):
1. place a 1-bundle PG on the slice's head resource ``TPU-<pod_type>-head``
   — only worker 0 of a slice advertises it, so winning that bundle claims
   the slice;
2. read the winning node's ``ray.io/tpu-slice-name`` label;
3. build the worker gang as per-host bundles with a
   ``bundle_label_selector={ray.io/tpu-slice-name: <name>}`` so all ranked
   workers pin to that slice's hosts.
"""

from __future__ import annotations

import logging
from typing import Dict, List, Optional

from .. import _worker_api
from .._internal.accelerators import (
    TPU_SLICE_NAME_LABEL,
    chips_per_host,
    pod_type_num_hosts,
    tpu_head_resource,
)
from .placement_group import PlacementGroup, placement_group, remove_placement_group

logger = logging.getLogger(__name__)


class SliceReservation:
    """One reserved slice: the head PG plus the worker-gang PG."""

    def __init__(
        self,
        pod_type: str,
        slice_name: str,
        head_pg: PlacementGroup,
        workers_pg: PlacementGroup,
    ):
        self.pod_type = pod_type
        self.slice_name = slice_name
        self.head_pg = head_pg
        self.workers_pg = workers_pg

    @property
    def num_hosts(self) -> int:
        return pod_type_num_hosts(self.pod_type)

    @property
    def chips_per_host(self) -> int:
        return chips_per_host(self.pod_type)

    @property
    def placement_group(self) -> PlacementGroup:
        return self.workers_pg

    def bundle_label_selector(self) -> Dict[str, str]:
        return {TPU_SLICE_NAME_LABEL: self.slice_name}

    def release(self):
        remove_placement_group(self.workers_pg)
        remove_placement_group(self.head_pg)


def reserve_tpu_slice(
    pod_type: str,
    *,
    extra_worker_resources: Optional[Dict[str, float]] = None,
    timeout: Optional[float] = 60.0,
) -> SliceReservation:
    """Reserve one whole slice of ``pod_type`` (e.g. "v5e-16").

    Reference flow: reserve_tpu_slice (_private/accelerators/tpu.py:213) —
    head-resource PG, slice-name lookup, label-selector gang.
    """
    head_pg = placement_group(
        [{tpu_head_resource(pod_type): 1.0}], strategy="STRICT_PACK",
    )
    if not head_pg.ready(timeout=timeout):
        remove_placement_group(head_pg)
        raise TimeoutError(f"no free {pod_type} slice available")
    info = head_pg.info()
    head_node = info.bundles[0].node_id
    slice_name = _node_label(head_node, TPU_SLICE_NAME_LABEL)
    if slice_name is None:
        remove_placement_group(head_pg)
        raise RuntimeError(
            f"slice head node {head_node} lacks {TPU_SLICE_NAME_LABEL} label"
        )
    num_hosts = pod_type_num_hosts(pod_type)
    per_host = {"TPU": float(chips_per_host(pod_type))}
    per_host.update(extra_worker_resources or {})
    workers_pg = placement_group(
        [dict(per_host) for _ in range(num_hosts)],
        strategy="STRICT_SPREAD" if num_hosts > 1 else "STRICT_PACK",
        bundle_label_selector=[
            {TPU_SLICE_NAME_LABEL: slice_name} for _ in range(num_hosts)
        ],
    )
    if not workers_pg.ready(timeout=timeout):
        remove_placement_group(workers_pg)
        remove_placement_group(head_pg)
        raise TimeoutError(f"could not reserve all {num_hosts} hosts of {slice_name}")
    logger.info("reserved TPU slice %s (%s, %d hosts)", slice_name, pod_type, num_hosts)
    return SliceReservation(pod_type, slice_name, head_pg, workers_pg)


class SlicePlacementGroup:
    """Multislice reservation: N whole slices for a DCN-spanning job
    (reference: ray.util.tpu.SlicePlacementGroup util/tpu.py:52)."""

    def __init__(
        self,
        num_slices: int,
        pod_type: str,
        *,
        timeout: Optional[float] = 120.0,
    ):
        self.num_slices = num_slices
        self.pod_type = pod_type
        self._reservations: List[SliceReservation] = []
        try:
            for _ in range(num_slices):
                self._reservations.append(
                    reserve_tpu_slice(pod_type, timeout=timeout)
                )
        except Exception:
            self.release()
            raise

    @property
    def reservations(self) -> List[SliceReservation]:
        return list(self._reservations)

    @property
    def slice_names(self) -> List[str]:
        return [r.slice_name for r in self._reservations]

    @property
    def num_hosts_per_slice(self) -> int:
        return pod_type_num_hosts(self.pod_type)

    def release(self):
        for r in self._reservations:
            try:
                r.release()
            except Exception:
                pass
        self._reservations.clear()


def slice_placement_group(num_slices: int, pod_type: str, **kwargs) -> SlicePlacementGroup:
    return SlicePlacementGroup(num_slices, pod_type, **kwargs)


def _node_label(node_id, key: str) -> Optional[str]:
    worker = _worker_api.get_core_worker()
    nodes = _worker_api.run_on_worker_loop(
        worker.client_pool.get(*worker.gcs_address).call("get_all_nodes")
    )
    for n in nodes:
        if n.node_id == node_id:
            return n.labels.get(key)
    return None
