"""Distributed FIFO queue backed by an actor.

Role-equivalent of the reference's ray.util.queue.Queue (util/queue.py):
a bounded multi-producer/multi-consumer queue usable from any task or actor.
"""

from __future__ import annotations

import asyncio
from typing import Any, List, Optional

from .. import api


class Empty(Exception):
    pass


class Full(Exception):
    pass


class _QueueActor:
    def __init__(self, maxsize: int):
        self._queue = asyncio.Queue(maxsize)

    async def put(self, item, timeout=None):
        try:
            if timeout is None:
                await self._queue.put(item)
            else:
                await asyncio.wait_for(self._queue.put(item), timeout)
            return True
        except asyncio.TimeoutError:
            return False

    async def get(self, timeout=None):
        try:
            if timeout is None:
                return True, await self._queue.get()
            return True, await asyncio.wait_for(self._queue.get(), timeout)
        except asyncio.TimeoutError:
            return False, None

    async def put_nowait(self, item):
        try:
            self._queue.put_nowait(item)
            return True
        except asyncio.QueueFull:
            return False

    async def get_nowait(self):
        try:
            return True, self._queue.get_nowait()
        except asyncio.QueueEmpty:
            return False, None

    async def qsize(self):
        return self._queue.qsize()

    async def empty(self):
        return self._queue.empty()

    async def full(self):
        return self._queue.full()


class Queue:
    def __init__(self, maxsize: int = 0, actor_options: Optional[dict] = None):
        options = dict(actor_options or {})
        options.setdefault("num_cpus", 0)
        cls = api.remote(_QueueActor)
        self._actor = cls.options(**options).remote(maxsize)

    def put(self, item: Any, block: bool = True, timeout: Optional[float] = None):
        if not block:
            ok = api.get(self._actor.put_nowait.remote(item))
            if not ok:
                raise Full
            return
        ok = api.get(self._actor.put.remote(item, timeout))
        if not ok:
            raise Full

    def get(self, block: bool = True, timeout: Optional[float] = None) -> Any:
        if not block:
            ok, item = api.get(self._actor.get_nowait.remote())
            if not ok:
                raise Empty
            return item
        ok, item = api.get(self._actor.get.remote(timeout))
        if not ok:
            raise Empty
        return item

    def put_nowait(self, item: Any):
        self.put(item, block=False)

    def get_nowait(self) -> Any:
        return self.get(block=False)

    def qsize(self) -> int:
        return api.get(self._actor.qsize.remote())

    def empty(self) -> bool:
        return api.get(self._actor.empty.remote())

    def full(self) -> bool:
        return api.get(self._actor.full.remote())

    def put_async(self, item):
        return self._actor.put.remote(item, None)

    def get_async(self):
        return self._actor.get.remote(None)

    def shutdown(self):
        api.kill(self._actor)
