"""Flight recorder: always-on per-process ring buffer of structured events.

Role-equivalent of Ray's export-event / state-transition logs, rebuilt for
post-mortem forensics: every process keeps a bounded ring of cheap
structured events (replica state transitions, autoscale decisions,
collective epochs, admission blocks, drain rejections, watchdog stack
captures) and a background thread streams the suffix to the GCS event
store about once a second. Because the push is continuous, the GCS copy
survives a SIGKILL of the recording process — post-mortem queries
(``ray_tpu events`` / ``/api/events``) read the cluster store, not the
dead process. ``dump_events()`` forces a synchronous flush for the
graceful-crash path.

Recording is unconditional (unlike spans, which are trace-gated): one
dict append under a lock per event, a few events per state transition —
cheap enough to never turn off.

Event names are the taxonomy. Every name is an :class:`EventName`
constant declared in THIS module, exactly once, in snake_case — enforced
by the RT007 analysis rule (the flight-recorder twin of RT004's metrics
registry), so ``ray_tpu events --name X`` and the docs' event table can't
drift from the code.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional

_lock = threading.Lock()
_events: List[dict] = []
_events_cap = int(os.environ.get("RAY_TPU_EVENTS_CAP", "4096"))
_flush_cursor = 0
_flush_lock = threading.Lock()  # serializes read-push-trim in flush_events
_pusher_started = False

# -- event-name registry (RT007 home) ----------------------------------------

_registry: Dict[str, "EventName"] = {}
_registry_lock = threading.Lock()


class EventName(str):
    """A registered flight-recorder event name. Constructing one registers
    it process-wide (keyed by name, like the metrics registry), and RT007
    requires every construction to be a literal snake_case string in
    util/events.py — the single place the event taxonomy lives."""

    def __new__(cls, name: str) -> "EventName":
        obj = super().__new__(cls, name)
        with _registry_lock:
            _registry[name] = obj
        return obj


def registered_event_names() -> List[str]:
    """Sorted taxonomy, for the docs table and the registry tests."""
    with _registry_lock:
        return sorted(_registry)


# The taxonomy. Emitters import these constants; a bare-string
# record_event("typo_name", ...) still records (forensics must never
# throw) but the name won't pass RT007 review at the emit site's import.
REPLICA_STATE = EventName("replica_state")
REPLICA_START = EventName("replica_start")
REPLICA_STOP = EventName("replica_stop")
AUTOSCALE_DECISION = EventName("autoscale_decision")
COLLECTIVE_EPOCH = EventName("collective_epoch")
ADMISSION_BLOCKED = EventName("admission_blocked")
DRAIN_REJECTED = EventName("drain_rejected")
REQUEST_RETRY = EventName("request_retry")
REQUEST_SHED = EventName("request_shed")
ENGINE_ADMISSION_BLOCKED = EventName("engine_admission_blocked")
WORKER_DEATH = EventName("worker_death")
WATCHDOG_STUCK = EventName("watchdog_stuck")
WATCHDOG_RECOVERED = EventName("watchdog_recovered")
NODE_SUSPECT = EventName("node_suspect")
NODE_FENCED = EventName("node_fenced")
NODE_UNFENCED = EventName("node_unfenced")
CIRCUIT_OPEN = EventName("circuit_open")
CIRCUIT_CLOSE = EventName("circuit_close")
PROXY_START = EventName("proxy_start")
PROXY_STOP = EventName("proxy_stop")
PROXY_DRAIN = EventName("proxy_drain")
KV_SHIPPED = EventName("kv_shipped")
KVTIER_EVICT = EventName("kvtier_evict")
ADAPTER_COLD_ATTACH = EventName("adapter_cold_attach")
ADAPTER_EVICT = EventName("adapter_evict")
STRAGGLER_DETECTED = EventName("straggler_detected")
STRAGGLER_RESOLVED = EventName("straggler_resolved")
ALERT_FIRING = EventName("alert_firing")
ALERT_RESOLVED = EventName("alert_resolved")


# -- recording ----------------------------------------------------------------


def record_event(name: str, **fields) -> None:
    """Append one structured event to the ring. Always on; one locked
    append per call. ``fields`` must be JSON-serializable (they travel
    through the GCS RPC envelope)."""
    ev = {"ts": time.time(), "pid": os.getpid(), "name": str(name)}
    ev.update(fields)
    global _flush_cursor
    dropped = 0
    with _lock:
        _events.append(ev)
        if len(_events) > _events_cap:
            # ring semantics: drop the oldest, keep the flush cursor
            # aligned with the surviving suffix
            drop = len(_events) - _events_cap
            del _events[:drop]
            _flush_cursor = max(0, _flush_cursor - drop)
            dropped = drop
    if dropped:
        try:
            from . import metrics as _metrics

            _metrics.record_events_dropped(dropped)
        except Exception:
            pass  # forensics are best-effort; never take down the caller
    _ensure_event_pusher()


def get_events(name: Optional[str] = None) -> List[dict]:
    with _lock:
        out = list(_events)
    if name is not None:
        out = [e for e in out if e.get("name") == name]
    return out


def clear_events() -> None:
    global _flush_cursor
    with _lock:
        _events.clear()
        _flush_cursor = 0


# -- streaming to the GCS event store ----------------------------------------


def flush_events() -> None:
    """Push events recorded since the last flush to the GCS event store.
    Unlike tracing.flush_spans this does NOT trim flushed events — the
    local ring stays intact (bounded by the cap) so in-process dumps and
    the watchdog's recent-history checks keep working; the cursor just
    advances past the pushed suffix. Mirrors flush_spans otherwise."""
    global _flush_cursor
    from .. import _worker_api

    worker = _worker_api.maybe_get_core_worker()
    if worker is None:
        return
    with _flush_lock:
        with _lock:
            batch = _events[_flush_cursor:]
            cursor = len(_events)
        if not batch:
            return
        try:
            _worker_api.run_on_worker_loop(
                worker.client_pool.get(*worker.gcs_address).call(
                    "report_events", batch
                ),
                timeout=5,
            )
            with _lock:
                _flush_cursor = max(_flush_cursor, min(cursor, len(_events)))
        except Exception:
            pass  # forensics are best-effort; never take down the caller


def dump_events(reason: str = "") -> None:
    """Synchronous flush for the graceful-crash path (actor death
    handlers, atexit): record a marker, then push everything now rather
    than waiting for the 1s pusher tick."""
    if reason:
        record_event(WORKER_DEATH, reason=reason, synthetic=False)
    flush_events()


def _ensure_event_pusher() -> None:
    global _pusher_started
    with _lock:
        if _pusher_started:
            return
        _pusher_started = True

    def _loop():
        while True:
            time.sleep(1.0)
            flush_events()

    threading.Thread(target=_loop, daemon=True, name="event-push").start()
