"""joblib backend running joblib tasks as remote tasks.

Role-equivalent of the reference's ``ray.util.joblib`` (register_ray in
util/joblib/__init__.py + the backend in ray_backend.py): after
``register_ray()``, ``joblib.parallel_backend("ray")`` runs scikit-learn
style joblib workloads on the cluster.
"""

from __future__ import annotations

from .. import api


def register_ray() -> None:
    from joblib.parallel import register_parallel_backend

    register_parallel_backend("ray", RayBackend)


class _AsyncRef:
    """Future-like over one ObjectRef; callback fires from a waiter thread so
    joblib's dispatch loop keeps feeding batches while earlier ones run."""

    def __init__(self, ref, callback=None):
        import threading

        self._ref = ref
        self._value = None
        self._error = None
        self._done = threading.Event()

        def _wait():
            try:
                self._value = api.get(ref)
            except Exception as e:
                self._error = e
            finally:
                self._done.set()
                if callback is not None:
                    callback(self)

        threading.Thread(target=_wait, daemon=True).start()

    def get(self, timeout=None):
        if not self._done.wait(timeout):
            raise TimeoutError("joblib task not ready")
        if self._error is not None:
            raise self._error
        return self._value


def _run_batch(batch):
    return batch()


from joblib._parallel_backends import ParallelBackendBase


class RayBackend(ParallelBackendBase):
    """joblib backend: each joblib batch (a ``BatchedCalls`` callable)
    becomes one remote task. Inherits the rest of the joblib protocol
    (retrieval_context, nesting bookkeeping) from ParallelBackendBase."""

    supports_timeout = True
    uses_threads = False
    supports_sharedmem = False

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self.parallel = None
        self._n_jobs = 1
        self._remote = None

    # -- ParallelBackendBase protocol ---------------------------------------

    def configure(self, n_jobs=1, parallel=None, prefer=None, require=None,
                  **kwargs):
        if not api.is_initialized():
            api.init()
        self.parallel = parallel
        self._n_jobs = self.effective_n_jobs(n_jobs)
        self._remote = api.remote(num_cpus=1)(_run_batch)
        return self._n_jobs

    def effective_n_jobs(self, n_jobs):
        if n_jobs == 0:
            raise ValueError("n_jobs == 0 has no meaning")
        total = max(int(api.cluster_resources().get("CPU", 1)), 1)
        if n_jobs is None or n_jobs < 0:
            return total
        return n_jobs

    def apply_async(self, func, callback=None):
        return _AsyncRef(self._remote.remote(func), callback)

    def get_nested_backend(self):
        from joblib._parallel_backends import SequentialBackend

        return SequentialBackend(nesting_level=self.nesting_level + 1), None

    def abort_everything(self, ensure_ready=True):
        if ensure_ready:
            self.configure(n_jobs=self._n_jobs, parallel=self.parallel)

    def terminate(self):
        pass
