"""Process-local node fence flag.

When a raylet loses GCS contact past its liveness window it self-fences
(split-brain prevention: the GCS may already be restarting this node's
actors/replicas elsewhere) and fans the flag out to its resident workers
via a ``set_fenced`` one-way RPC. In-process consumers — serve replica
admission, collective abort checks — read :func:`is_fenced` instead of
asking the (unreachable) GCS. The flag clears on the raylet's first
successful report after the partition heals.

Deliberately dependency-free module globals: the readers sit on hot
admission paths and inside collective poll ticks.
"""

from __future__ import annotations

import threading
from typing import Tuple

_lock = threading.Lock()
_fenced = False
_node_id = ""
_reason = ""


def set_fenced(fenced: bool, node_id: str = "", reason: str = "") -> None:
    global _fenced, _node_id, _reason
    with _lock:
        _fenced = bool(fenced)
        _node_id = node_id
        _reason = reason if fenced else ""


def is_fenced() -> bool:
    return _fenced


def fence_info() -> Tuple[bool, str, str]:
    """(fenced, node_id_hex, reason) — for error messages and tests."""
    with _lock:
        return _fenced, _node_id, _reason
