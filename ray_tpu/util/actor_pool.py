"""ActorPool: round-robin work distribution over a fixed set of actors.

Role-equivalent of the reference's ray.util.ActorPool (util/actor_pool.py):
submit/map over idle actors, results retrievable in completion or submission
order.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, List

from .. import api


class ActorPool:
    def __init__(self, actors: List[Any]):
        self._idle = list(actors)
        self._future_to_actor = {}
        self._index_to_future = {}
        self._next_task_index = 0
        self._next_return_index = 0
        self._pending_submits: List[tuple] = []

    def submit(self, fn: Callable, value: Any):
        """fn(actor, value) -> ObjectRef; queued if all actors are busy."""
        if self._idle:
            actor = self._idle.pop()
            ref = fn(actor, value)
            self._future_to_actor[ref] = (self._next_task_index, actor)
            self._index_to_future[self._next_task_index] = ref
            self._next_task_index += 1
        else:
            self._pending_submits.append((fn, value))

    def has_next(self) -> bool:
        return bool(self._index_to_future) or bool(self._pending_submits)

    def get_next(self, timeout=None) -> Any:
        """Next result in submission order."""
        if self._next_return_index not in self._index_to_future:
            raise StopIteration("no pending results")
        ref = self._index_to_future.pop(self._next_return_index)
        self._next_return_index += 1
        value = api.get(ref, timeout=timeout)
        self._return_actor(ref)
        return value

    def get_next_unordered(self, timeout=None) -> Any:
        """Next result in completion order."""
        if not self._future_to_actor:
            raise StopIteration("no pending results")
        ready, _ = api.wait(
            list(self._future_to_actor), num_returns=1, timeout=timeout
        )
        if not ready:
            raise TimeoutError("get_next_unordered timed out")
        ref = ready[0]
        index, _ = self._future_to_actor[ref]
        self._index_to_future.pop(index, None)
        value = api.get(ref)
        self._return_actor(ref)
        return value

    def _return_actor(self, ref):
        _, actor = self._future_to_actor.pop(ref)
        self._idle.append(actor)
        if self._pending_submits:
            fn, value = self._pending_submits.pop(0)
            self.submit(fn, value)

    def map(self, fn: Callable, values: Iterable[Any]):
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next()

    def map_unordered(self, fn: Callable, values: Iterable[Any]):
        for v in values:
            self.submit(fn, v)
        while self._future_to_actor or self._pending_submits:
            yield self.get_next_unordered()

    def has_free(self) -> bool:
        return bool(self._idle)

    def pop_idle(self):
        return self._idle.pop() if self._idle else None

    def push(self, actor):
        self._idle.append(actor)
