"""Dask-on-ray_tpu: execute dask task graphs as ray_tpu tasks.

Role-equivalent of the reference's ``ray.util.dask`` (the ``ray_dask_get``
scheduler): a dask *scheduler function* receives a plain graph dict
(`key -> literal | (callable, arg...) | alias-key | [nested...]`) and the
requested output keys, and must return results in the same nested shape.
Each graph task becomes one ray_tpu task whose dependencies are passed as
ObjectRefs, so independent subgraphs run in parallel across the cluster and
intermediate results live in the object store.

The core scheduler deliberately avoids importing dask — the graph protocol
is plain data — so it is usable (and testable) without dask installed.
``enable_dask_on_ray`` registers it as dask's default get when dask IS
available.
"""

from __future__ import annotations

from typing import Any, Dict, Hashable, List

from .. import api as _api
from ..api import remote as _remote


def _istask(x) -> bool:
    return isinstance(x, tuple) and len(x) > 0 and callable(x[0])


def _find_deps(expr, dsk, out: set):
    """Collect graph keys referenced by ``expr`` (dask semantics: any
    hashable leaf that is a key of the graph is a dependency)."""
    if _istask(expr):
        for arg in expr[1:]:
            _find_deps(arg, dsk, out)
    elif isinstance(expr, list):
        for item in expr:
            _find_deps(item, dsk, out)
    else:
        try:
            if expr in dsk:
                out.add(expr)
        except TypeError:
            pass  # unhashable literal
    return out


def _rebuild(expr, lookup: Dict[Hashable, Any]):
    """Evaluate a dask expression with dependency keys already materialized."""
    if _istask(expr):
        func = expr[0]
        args = [_rebuild(a, lookup) for a in expr[1:]]
        return func(*args)
    if isinstance(expr, list):
        return [_rebuild(item, lookup) for item in expr]
    try:
        if expr in lookup:
            return lookup[expr]
    except TypeError:
        pass
    return expr


@_remote
def _exec_dask_task(expr, dep_keys: List[Hashable], *dep_values):
    return _rebuild(expr, dict(zip(dep_keys, dep_values)))


def _toposort(dsk) -> List[Hashable]:
    """Iterative DFS (deep linear chains are routine in dask graphs; a
    recursive visit would hit the interpreter recursion limit ~1000)."""
    order: List[Hashable] = []
    state: Dict[Hashable, int] = {}  # 1 = visiting, 2 = done

    for root in dsk:
        if state.get(root) == 2:
            continue
        stack: List[tuple] = [(root, False)]
        while stack:
            key, children_done = stack.pop()
            if children_done:
                state[key] = 2
                order.append(key)
                continue
            if state.get(key) == 2:
                continue
            if state.get(key) == 1:
                raise ValueError(f"cycle in dask graph at {key!r}")
            state[key] = 1
            stack.append((key, True))
            for dep in sorted(_find_deps(dsk[key], dsk, set()), key=repr):
                if dep == key:
                    continue
                if state.get(dep) == 1:
                    raise ValueError(f"cycle in dask graph at {dep!r}")
                if state.get(dep) != 2:
                    stack.append((dep, False))
        # stack unwound: everything reachable from root is done
    return order


def ray_dask_get(dsk: Dict, keys, ray_remote_args: Dict | None = None, **_kw):
    """Dask scheduler: one ray_tpu task per graph entry, dependencies as
    ObjectRefs (reference: ray.util.dask.ray_dask_get). ``keys`` may be a
    single key or arbitrarily nested lists of keys; the return value has
    the same shape."""
    refs: Dict[Hashable, Any] = {}
    literals: Dict[Hashable, Any] = {}
    submit = (
        _exec_dask_task.options(**ray_remote_args)
        if ray_remote_args
        else _exec_dask_task
    )
    for key in _toposort(dsk):
        expr = dsk[key]
        deps = sorted(
            (d for d in _find_deps(expr, dsk, set()) if d != key), key=repr
        )
        if not _istask(expr) and not isinstance(expr, list):
            if deps:  # alias: reuse the target's ref/literal directly
                target = deps[0]
                if target in refs:
                    refs[key] = refs[target]
                else:
                    literals[key] = literals[target]
            else:  # plain literal: no scheduler round-trip for a no-op
                literals[key] = expr
            continue
        args = [
            refs[d] if d in refs else literals[d] for d in deps
        ]
        refs[key] = submit.remote(expr, deps, *args)

    def materialize(k):
        if isinstance(k, list):
            return [materialize(i) for i in k]
        if k in literals:
            return literals[k]
        return _api.get(refs[k])

    return materialize(keys)


def enable_dask_on_ray():
    """Set ray_dask_get as dask's default scheduler (requires dask)."""
    try:
        import dask
    except ImportError as e:
        raise ImportError(
            "dask is not installed; ray_dask_get still works directly on "
            "plain graph dicts: ray_dask_get(dsk, keys)"
        ) from e
    return dask.config.set(scheduler=ray_dask_get)
