"""User-facing metrics: Counter / Gauge / Histogram.

Role-equivalent of the reference's ray.util.metrics (python/ray/util/
metrics.py backed by the per-node metrics agent + Prometheus export,
_private/metrics_agent.py). Metrics record locally and are pushed to the
GCS KV under ``metrics:<worker>`` every few seconds; ``prometheus_text()``
aggregates every worker's push into Prometheus exposition format.
"""

from __future__ import annotations

import bisect
import json
import os
import threading
import time
from contextlib import contextmanager
from typing import Dict, List, Optional, Tuple

from ..runtime.gcs import keys as gcs_keys

_registry_lock = threading.Lock()
_registry: Dict[str, "Metric"] = {}
_pusher_started = False


class Metric:
    def __init__(
        self,
        name: str,
        description: str = "",
        tag_keys: Tuple[str, ...] = (),
    ):
        self._name = name
        self._description = description
        self._tag_keys = tuple(tag_keys)
        self._default_tags: Dict[str, str] = {}
        self._values: Dict[Tuple[str, ...], float] = {}
        self._lock = threading.Lock()
        with _registry_lock:
            _registry[name] = self
        _ensure_pusher()

    def set_default_tags(self, tags: Dict[str, str]):
        self._default_tags = dict(tags)
        return self

    def _tag_tuple(self, tags: Optional[Dict[str, str]]) -> Tuple[str, ...]:
        merged = {**self._default_tags, **(tags or {})}
        return tuple(merged.get(k, "") for k in self._tag_keys)

    def _snapshot(self) -> dict:
        with self._lock:
            return {
                "name": self._name,
                "type": type(self).__name__.lower(),
                "description": self._description,
                "tag_keys": self._tag_keys,
                "values": {json.dumps(k): v for k, v in self._values.items()},
            }


class _BoundCounter:
    """Counter pre-bound to one tag combination: the tag dict merge and
    tuple build happen ONCE at bind time, so the per-request hot path
    (e.g. the ingress proxy) is a lock + dict-slot add with zero
    allocation. Obtain via ``Counter.bind(**tags)``."""

    __slots__ = ("_metric", "_key")

    def __init__(self, metric: "Counter", key: Tuple[str, ...]):
        self._metric = metric
        self._key = key

    def inc(self, value: float = 1.0):
        m = self._metric
        with m._lock:
            m._values[self._key] = m._values.get(self._key, 0.0) + value


class _BoundGauge:
    """See _BoundCounter; obtain via ``Gauge.bind(**tags)``."""

    __slots__ = ("_metric", "_key")

    def __init__(self, metric: "Gauge", key: Tuple[str, ...]):
        self._metric = metric
        self._key = key

    def set(self, value: float):
        m = self._metric
        with m._lock:
            m._values[self._key] = float(value)


class _BoundHistogram:
    """See _BoundCounter; obtain via ``Histogram.bind(**tags)``. No
    exemplar support — exemplars belong to traced paths, and bound handles
    exist for the untraced fast path."""

    __slots__ = ("_metric", "_key")

    def __init__(self, metric: "Histogram", key: Tuple[str, ...]):
        self._metric = metric
        self._key = key

    def observe(self, value: float):
        m = self._metric
        with m._lock:
            counts = m._counts.get(self._key)
            if counts is None:
                counts = m._counts[self._key] = \
                    [0] * (len(m._boundaries) + 1)
            counts[bisect.bisect_left(m._boundaries, value)] += 1
            total = m._sums.get(self._key, 0.0) + value
            m._sums[self._key] = total
            m._values[self._key] = total


class Counter(Metric):
    def inc(self, value: float = 1.0, tags: Optional[Dict[str, str]] = None):
        key = self._tag_tuple(tags)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + value

    def bind(self, **tags: str) -> _BoundCounter:
        return _BoundCounter(self, self._tag_tuple(tags))


class Gauge(Metric):
    def set(self, value: float, tags: Optional[Dict[str, str]] = None):
        with self._lock:
            self._values[self._tag_tuple(tags)] = float(value)

    def bind(self, **tags: str) -> _BoundGauge:
        return _BoundGauge(self, self._tag_tuple(tags))


class Histogram(Metric):
    def __init__(
        self,
        name: str,
        description: str = "",
        boundaries: Optional[List[float]] = None,
        tag_keys: Tuple[str, ...] = (),
    ):
        super().__init__(name, description, tag_keys)
        self._boundaries = sorted(boundaries or [0.1, 1, 10, 100, 1000])
        self._counts: Dict[Tuple[str, ...], List[int]] = {}
        self._sums: Dict[Tuple[str, ...], float] = {}
        # per-bucket exemplars (OpenMetrics-style): the last trace_id (and
        # its value) observed in each bucket, so a bad p99 bucket links to
        # a concrete trace in the span store instead of just a count
        self._exemplars: Dict[Tuple[str, ...], Dict[int, dict]] = {}

    def observe(self, value: float, tags: Optional[Dict[str, str]] = None,
                exemplar: Optional[str] = None):
        key = self._tag_tuple(tags)
        with self._lock:
            counts = self._counts.setdefault(
                key, [0] * (len(self._boundaries) + 1)
            )
            bucket = bisect.bisect_left(self._boundaries, value)
            counts[bucket] += 1
            self._sums[key] = self._sums.get(key, 0.0) + value
            self._values[key] = self._sums[key]
            if exemplar:
                self._exemplars.setdefault(key, {})[bucket] = {
                    "trace_id": exemplar, "value": value, "ts": time.time(),
                }

    def bind(self, **tags: str) -> _BoundHistogram:
        return _BoundHistogram(self, self._tag_tuple(tags))

    def _snapshot(self) -> dict:
        snap = super()._snapshot()
        with self._lock:
            snap["boundaries"] = self._boundaries
            snap["counts"] = {
                json.dumps(k): v for k, v in self._counts.items()
            }
            if self._exemplars:
                snap["exemplars"] = {
                    json.dumps(k): dict(v)
                    for k, v in self._exemplars.items()
                }
        return snap


# ---------------------------------------------------------------------------
# Control-plane RPC metrics (the lease-reuse / v2-framing proof layer):
# per-method client-call latency histograms plus an RPCs-per-task counter
# pair, recorded from _internal/rpc.py on every client call and surfaced by
# the microbenchmark CLI and the lease-reuse regression tests.
# ---------------------------------------------------------------------------

_RPC_LATENCY_BOUNDARIES_MS = [
    0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10, 25, 50, 100, 250, 1000,
]

_rpc_latency: Optional["Histogram"] = None
_rpc_calls: Optional["Counter"] = None
_tasks_submitted: Optional["Counter"] = None
_rpc_init_lock = threading.Lock()


def _ensure_rpc_metrics():
    global _rpc_latency, _rpc_calls, _tasks_submitted
    if _rpc_latency is None:
        with _rpc_init_lock:
            if _rpc_latency is None:
                _rpc_calls = Counter(
                    "rpc_client_calls_total",
                    "Client RPCs issued by this process, by method",
                    tag_keys=("method",),
                )
                _tasks_submitted = Counter(
                    "tasks_submitted_total",
                    "Normal tasks submitted by this process",
                )
                # assigned last: its non-None-ness gates the fast path, so
                # the other two must already exist when readers see it
                _rpc_latency = Histogram(
                    "rpc_client_latency_ms",
                    "Client RPC round-trip latency by method (ms)",
                    boundaries=_RPC_LATENCY_BOUNDARIES_MS,
                    tag_keys=("method",),
                )
    return _rpc_latency, _rpc_calls, _tasks_submitted


def record_rpc(method: str, latency_s: float):
    """Called from RpcClient.call / call_oneway (hot path — keep cheap)."""
    latency, calls, _ = _ensure_rpc_metrics()
    tags = {"method": method}
    latency.observe(latency_s * 1000.0, tags)
    calls.inc(1.0, tags)


def note_task_submitted(n: float = 1.0):
    """Called from CoreWorker._launch_task; pairs with rpc_call counts to
    derive RPCs-per-task."""
    _, _, tasks = _ensure_rpc_metrics()
    tasks.inc(n)


def rpc_calls_by_method() -> Dict[str, float]:
    """Process-local snapshot: method -> client calls issued."""
    _, calls, _ = _ensure_rpc_metrics()
    with calls._lock:
        return {k[0]: v for k, v in calls._values.items()}


def tasks_submitted_total() -> float:
    _, _, tasks = _ensure_rpc_metrics()
    with tasks._lock:
        return sum(tasks._values.values())


def rpc_latency_summary() -> Dict[str, dict]:
    """Process-local per-method latency summary: count, mean ms, and the
    cumulative histogram buckets ({le: count}) — the machine-readable shape
    the microbenchmark CLI emits for BENCH_LOG.md."""
    latency, _, _ = _ensure_rpc_metrics()
    out: Dict[str, dict] = {}
    with latency._lock:
        for key, counts in latency._counts.items():
            method = key[0]
            total = sum(counts)
            if not total:
                continue
            cum = 0
            buckets = {}
            for bound, c in zip(latency._boundaries, counts):
                cum += c
                buckets[str(bound)] = cum
            buckets["+Inf"] = total
            out[method] = {
                "count": total,
                "mean_ms": latency._sums.get(key, 0.0) / total,
                "buckets": buckets,
            }
    return out


# -- flight-recorder health ---------------------------------------------------

_events_dropped: Optional["Counter"] = None
_events_dropped_lock = threading.Lock()


def _ensure_events_dropped():
    global _events_dropped
    if _events_dropped is None:
        with _events_dropped_lock:
            if _events_dropped is None:
                _events_dropped = Counter(
                    "events_dropped_total",
                    "Flight-recorder ring overflows: oldest events dropped "
                    "when the cap was hit, truncating the post-mortem window",
                )
    return _events_dropped


def record_events_dropped(n: float = 1.0):
    """Called from util/events.py when the ring drops its oldest events."""
    _ensure_events_dropped().inc(float(n))


def events_dropped_total() -> float:
    """Process-local readback."""
    c = _ensure_events_dropped()
    with c._lock:
        return sum(c._values.values())


def events_dropped_from_payloads(payloads) -> float:
    """Cluster rollup over pushed metric payloads: total events every
    process's ring has dropped (the /api/events truncation banner)."""
    total = 0.0
    for payload in payloads:
        for snap in payload.get("metrics", ()):
            if snap.get("name") == "events_dropped_total":
                total += sum(snap.get("values", {}).values())
    return total


# ---------------------------------------------------------------------------
# Object-serialization accounting: how many times (and how many bytes) this
# process serialized values into the object plane, by context — "put"
# (api.put / CoreWorker.put) vs "task_arg" (inline task-argument packing).
# The rllib put-once regression guard asserts train() serializes the params
# pytree at most once per iteration instead of once per env-runner.
# ---------------------------------------------------------------------------

_ser_count: Optional["Counter"] = None
_ser_bytes: Optional["Counter"] = None
_ser_init_lock = threading.Lock()


def _ensure_serialization_metrics():
    global _ser_count, _ser_bytes
    if _ser_bytes is None:
        with _ser_init_lock:
            if _ser_bytes is None:
                _ser_count = Counter(
                    "object_serializations_total",
                    "Object-plane serializations by context (put | task_arg)",
                    tag_keys=("context",),
                )
                # assigned last: gates the fast path (see _ensure_rpc_metrics)
                _ser_bytes = Counter(
                    "object_serialization_bytes_total",
                    "Bytes serialized into the object plane by context",
                    tag_keys=("context",),
                )
    return _ser_count, _ser_bytes


def record_object_serialization(context: str, nbytes: int):
    """Called from CoreWorker.put and prepare_args (hot path — keep cheap)."""
    count, total = _ensure_serialization_metrics()
    tags = {"context": context}
    count.inc(1.0, tags)
    total.inc(float(nbytes), tags)


def object_serializations() -> Dict[str, Dict[str, float]]:
    """Process-local snapshot: context -> {count, bytes}."""
    count, total = _ensure_serialization_metrics()
    out: Dict[str, Dict[str, float]] = {}
    with count._lock:
        for key, v in count._values.items():
            out.setdefault(key[0], {"count": 0.0, "bytes": 0.0})["count"] = v
    with total._lock:
        for key, v in total._values.items():
            out.setdefault(key[0], {"count": 0.0, "bytes": 0.0})["bytes"] = v
    return out


# ---------------------------------------------------------------------------
# Weight-plane metrics (ray_tpu.weights): publish latency, broadcast volume,
# tree depth, and subscriber staleness, tagged by model name. Surfaced via
# the GCS pusher / prometheus_text like every other metric, and snapshotted
# process-locally by the weights microbenchmark + tests.
# ---------------------------------------------------------------------------

_WEIGHTS_LATENCY_BOUNDARIES_MS = [
    1, 5, 10, 25, 50, 100, 250, 500, 1000, 2500, 5000, 10000,
]

_weights_metrics: Optional[dict] = None
_weights_init_lock = threading.Lock()


def _ensure_weights_metrics() -> dict:
    global _weights_metrics
    if _weights_metrics is None:
        with _weights_init_lock:
            if _weights_metrics is None:
                _weights_metrics = {
                    "publish_latency": Histogram(
                        "weights_publish_latency_ms",
                        "WeightPublisher.publish wall time by model (ms)",
                        boundaries=_WEIGHTS_LATENCY_BOUNDARIES_MS,
                        tag_keys=("model",),
                    ),
                    "fetch_latency": Histogram(
                        "weights_fetch_latency_ms",
                        "WeightSubscriber full-version fetch wall time (ms)",
                        boundaries=_WEIGHTS_LATENCY_BOUNDARIES_MS,
                        tag_keys=("model",),
                    ),
                    "broadcast_bytes": Counter(
                        "weights_broadcast_bytes_total",
                        "Logical weight bytes moved by direction "
                        "(publish | fetch) — raw leaf bytes, pre-codec",
                        tag_keys=("model", "direction"),
                    ),
                    # wire vs logical split: with the int8 chunk codec the
                    # store/broadcast bytes are ~2-4x smaller than the leaf
                    # bytes; conflating them would silently hide (or
                    # double-count) the compression win
                    "wire_bytes": Counter(
                        "weights_wire_bytes_total",
                        "Encoded on-the-wire weight bytes by direction "
                        "(publish | fetch)",
                        tag_keys=("model", "direction"),
                    ),
                    "codec_publishes": Counter(
                        "weights_codec_publish_total",
                        "Published versions by chunk codec (raw | int8)",
                        tag_keys=("model", "codec"),
                    ),
                    "tree_depth": Gauge(
                        "weights_broadcast_tree_depth",
                        "Depth of the binomial broadcast tree by model",
                        tag_keys=("model",),
                    ),
                    "staleness": Gauge(
                        "weights_staleness_versions",
                        "Versions behind head for this subscriber, by model",
                        tag_keys=("model",),
                    ),
                }
    return _weights_metrics


def record_weights_publish(
    model: str, latency_s: float, nbytes: int,
    wire_nbytes: Optional[int] = None, codec: str = "raw",
):
    m = _ensure_weights_metrics()
    tags = {"model": model, "direction": "publish"}
    m["publish_latency"].observe(latency_s * 1000.0, {"model": model})
    m["broadcast_bytes"].inc(float(nbytes), tags)
    m["wire_bytes"].inc(
        float(wire_nbytes if wire_nbytes is not None else nbytes), tags
    )
    m["codec_publishes"].inc(1.0, {"model": model, "codec": codec})


def record_weights_fetch(
    model: str, latency_s: float, nbytes: int,
    wire_nbytes: Optional[int] = None,
):
    m = _ensure_weights_metrics()
    tags = {"model": model, "direction": "fetch"}
    m["fetch_latency"].observe(latency_s * 1000.0, {"model": model})
    m["broadcast_bytes"].inc(float(nbytes), tags)
    m["wire_bytes"].inc(
        float(wire_nbytes if wire_nbytes is not None else nbytes), tags
    )


def set_weights_tree_depth(model: str, depth: int):
    _ensure_weights_metrics()["tree_depth"].set(float(depth), {"model": model})


def set_weights_staleness(model: str, versions_behind: int):
    _ensure_weights_metrics()["staleness"].set(
        float(versions_behind), {"model": model}
    )


def weights_staleness(model: str) -> Optional[float]:
    """Process-local staleness gauge readback (tests + state CLI)."""
    gauge = _ensure_weights_metrics()["staleness"]
    with gauge._lock:
        return gauge._values.get(gauge._tag_tuple({"model": model}))


# ---------------------------------------------------------------------------
# Collective / ICI instrumentation (the scaling-efficiency proof layer):
# every out-of-graph collective op (collective/xla_group.py, cpu_group.py)
# records bytes moved and wall latency; the achieved-bandwidth gauge is the
# last op's bytes/latency. Per-step compute/collective/idle breakdowns come
# from train/rllib learner steps and roll up into a scaling-efficiency
# gauge (achieved useful-compute fraction vs. the linear-scaling ideal of
# 1.0 — the step-time decomposition Podracer/MLPerf-TPU attribute scaling
# wins to).
# ---------------------------------------------------------------------------

_COLLECTIVE_LATENCY_BOUNDARIES_MS = [
    0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10, 25, 50, 100, 250, 1000, 5000,
]

_collective_metrics: Optional[dict] = None
_collective_init_lock = threading.Lock()


def _ensure_collective_metrics() -> dict:
    global _collective_metrics
    if _collective_metrics is None:
        with _collective_init_lock:
            if _collective_metrics is None:
                _collective_metrics = {
                    "latency": Histogram(
                        "collective_op_latency_ms",
                        "Out-of-graph collective op wall time (ms)",
                        boundaries=_COLLECTIVE_LATENCY_BOUNDARIES_MS,
                        tag_keys=("op", "backend", "group"),
                    ),
                    "bytes": Counter(
                        "collective_bytes_total",
                        "Logical bytes moved through collective ops "
                        "(operand bytes, pre-codec)",
                        tag_keys=("op", "backend", "group"),
                    ),
                    "wire_bytes": Counter(
                        "collective_wire_bytes_total",
                        "Encoded on-the-wire bytes of collective ops "
                        "(== logical when transport is full-width)",
                        tag_keys=("op", "backend", "group"),
                    ),
                    "bandwidth": Gauge(
                        "collective_bandwidth_gb_s",
                        "Achieved wire bandwidth of the last collective "
                        "op (GB/s, encoded bytes / wall time)",
                        tag_keys=("op", "backend", "group"),
                    ),
                }
    return _collective_metrics


def record_collective(
    op: str, backend: str, group: str, nbytes: int, latency_s: float,
    wire_nbytes: Optional[int] = None,
):
    """Called from every collective backend op (hot path — keep cheap).
    ``nbytes`` is the logical operand size; ``wire_nbytes`` the encoded
    size when the transport compresses (None: wire == logical). The
    bandwidth gauge is wire-basis — it reports what the link carried."""
    m = _ensure_collective_metrics()
    tags = {"op": op, "backend": backend, "group": group}
    wire = wire_nbytes if wire_nbytes is not None else nbytes
    m["latency"].observe(latency_s * 1000.0, tags)
    m["bytes"].inc(float(nbytes), tags)
    m["wire_bytes"].inc(float(wire), tags)
    if latency_s > 0:
        m["bandwidth"].set(wire / latency_s / 1e9, tags)


def collective_seconds_total() -> float:
    """Process-local cumulative wall time spent in collective ops; step
    breakdowns diff this across a step to split compute from collective."""
    m = _ensure_collective_metrics()
    hist = m["latency"]
    with hist._lock:
        return sum(hist._sums.values()) / 1000.0


def collective_summary() -> Dict[str, Dict[str, float]]:
    """Process-local snapshot: op -> {count, bytes, mean_ms} (tests + CLI)."""
    m = _ensure_collective_metrics()
    out: Dict[str, Dict[str, float]] = {}
    hist = m["latency"]
    with hist._lock:
        for key, counts in hist._counts.items():
            total = sum(counts)
            if total:
                out[key[0]] = {
                    "count": float(total),
                    "mean_ms": hist._sums.get(key, 0.0) / total,
                }
    with m["bytes"]._lock:
        for key, v in m["bytes"]._values.items():
            out.setdefault(key[0], {})["bytes"] = v
    with m["wire_bytes"]._lock:
        for key, v in m["wire_bytes"]._values.items():
            out.setdefault(key[0], {})["wire_bytes"] = v
    return out


# -- overlap split: the overlapped-reduction scheduler
# (collective/scheduler.py) attributes every async op's latency to either
# "exposed" (caller blocked in wait) or "overlapped" (ran under compute).
# collective_seconds_total above keeps recording FULL op latencies — under
# overlap that clock overstates critical-path cost, and this split is the
# number that actually proves the win.

_overlap_metrics: Optional[dict] = None
_overlap_init_lock = threading.Lock()


def _ensure_overlap_metrics() -> dict:
    global _overlap_metrics
    if _overlap_metrics is None:
        with _overlap_init_lock:
            if _overlap_metrics is None:
                _overlap_metrics = {
                    "exposed": Counter(
                        "collective_exposed_seconds_total",
                        "Async collective time the caller actually "
                        "blocked on (critical-path cost)",
                        tag_keys=("group",),
                    ),
                    "overlapped": Counter(
                        "collective_overlapped_seconds_total",
                        "Async collective time hidden under the "
                        "caller's compute",
                        tag_keys=("group",),
                    ),
                    "fraction": Gauge(
                        "collective_overlap_fraction",
                        "Hidden fraction of the last gradient "
                        "reduction's collective time (1.0 = fully "
                        "overlapped, 0.0 = fully exposed)",
                        tag_keys=("group",),
                    ),
                }
    return _overlap_metrics


def record_collective_overlap(group: str, exposed_s: float,
                              overlapped_s: float):
    """One gradient reduction's exposure split, summed over its buckets
    (called from PendingReduce.wait on every path, including sync mode
    where overlapped_s is 0 — the A/B baseline shows fraction 0.0)."""
    m = _ensure_overlap_metrics()
    tags = {"group": group}
    exposed_s = max(exposed_s, 0.0)
    overlapped_s = max(overlapped_s, 0.0)
    m["exposed"].inc(exposed_s, tags)
    m["overlapped"].inc(overlapped_s, tags)
    total = exposed_s + overlapped_s
    if total > 0:
        m["fraction"].set(overlapped_s / total, tags)


def collective_exposed_seconds_total() -> float:
    metric = _ensure_overlap_metrics()["exposed"]
    with metric._lock:
        return float(sum(metric._values.values()))


def collective_overlapped_seconds_total() -> float:
    metric = _ensure_overlap_metrics()["overlapped"]
    with metric._lock:
        return float(sum(metric._values.values()))


def collective_overlap_summary() -> Dict[str, Dict[str, float]]:
    """Process-local snapshot: group -> {exposed_s, overlapped_s,
    overlap_fraction} (tests + bench + CLI)."""
    m = _ensure_overlap_metrics()
    out: Dict[str, Dict[str, float]] = {}
    for label, metric in (("exposed_s", m["exposed"]),
                          ("overlapped_s", m["overlapped"])):
        with metric._lock:
            for key, v in metric._values.items():
                out.setdefault(key[0], {})[label] = v
    for group, entry in out.items():
        total = entry.get("exposed_s", 0.0) + entry.get("overlapped_s", 0.0)
        entry["overlap_fraction"] = (
            entry.get("overlapped_s", 0.0) / total if total > 0 else 0.0
        )
    return out


_step_metrics: Optional[dict] = None
_step_init_lock = threading.Lock()


def _ensure_step_metrics() -> dict:
    global _step_metrics
    if _step_metrics is None:
        with _step_init_lock:
            if _step_metrics is None:
                _step_metrics = {
                    "seconds": Gauge(
                        "step_time_seconds",
                        "Last train-step wall time by component "
                        "(compute | collective | idle | total)",
                        tag_keys=("role", "component"),
                    ),
                    "efficiency": Gauge(
                        "scaling_efficiency_ratio",
                        "Useful-compute fraction of the last step "
                        "(1.0 = linear-scaling ideal: zero collective/idle)",
                        tag_keys=("role",),
                    ),
                }
    return _step_metrics


def record_step_breakdown(
    role: str, compute_s: float, collective_s: float, idle_s: float,
    exposed_s: Optional[float] = None, overlapped_s: Optional[float] = None,
):
    """``collective_s`` is the full-latency collective clock delta (the
    pre-overlap decomposition). When the step ran under the overlapped
    scheduler, ``exposed_s``/``overlapped_s`` additionally split that time
    into critical-path vs hidden-under-compute components."""
    m = _ensure_step_metrics()
    compute_s = max(compute_s, 0.0)
    collective_s = max(collective_s, 0.0)
    idle_s = max(idle_s, 0.0)
    total = compute_s + collective_s + idle_s
    components = [
        ("compute", compute_s),
        ("collective", collective_s),
        ("idle", idle_s),
        ("total", total),
    ]
    if exposed_s is not None:
        components.append(("collective_exposed", max(exposed_s, 0.0)))
    if overlapped_s is not None:
        components.append(("collective_overlapped", max(overlapped_s, 0.0)))
    for component, value in components:
        m["seconds"].set(value, {"role": role, "component": component})
    if total > 0:
        m["efficiency"].set(compute_s / total, {"role": role})


def scaling_efficiency(role: str) -> Optional[float]:
    """Process-local efficiency gauge readback (tests + state CLI)."""
    gauge = _ensure_step_metrics()["efficiency"]
    with gauge._lock:
        return gauge._values.get(gauge._tag_tuple({"role": role}))


class StepBreakdown:
    """Per-step compute/collective/idle decomposition for a train loop.

    ``step()`` wraps one learner step: collective time is the delta of the
    process-local collective clock across the block, compute is the rest of
    the block, and idle is the gap since the previous step ended (data
    stall / rollout wait). ``mark()`` is the boundary-only variant for
    loops that can't wrap their step body (ray_tpu.train session.report):
    it treats report-to-report intervals as steps with unknown idle."""

    def __init__(self, role: str):
        self.role = role
        self._last_end: Optional[float] = None
        self._last_coll: Optional[float] = None
        self._last_exposed: Optional[float] = None
        self._last_overlapped: Optional[float] = None

    @contextmanager
    def step(self):
        start = time.perf_counter()
        coll0 = collective_seconds_total()
        exp0 = collective_exposed_seconds_total()
        ovl0 = collective_overlapped_seconds_total()
        try:
            yield
        finally:
            end = time.perf_counter()
            coll = collective_seconds_total() - coll0
            idle = (
                start - self._last_end if self._last_end is not None else 0.0
            )
            self._last_end = end
            # under the overlapped scheduler only the EXPOSED share of the
            # collective clock actually left the critical path's compute —
            # the overlapped share ran under it and stays counted as compute
            exposed = collective_exposed_seconds_total() - exp0
            overlapped = collective_overlapped_seconds_total() - ovl0
            critical_coll = min(coll, exposed) if overlapped > 0 else coll
            record_step_breakdown(
                self.role, (end - start) - critical_coll, critical_coll,
                idle, exposed_s=exposed, overlapped_s=overlapped,
            )

    def mark(self):
        now = time.perf_counter()
        coll_now = collective_seconds_total()
        exp_now = collective_exposed_seconds_total()
        ovl_now = collective_overlapped_seconds_total()
        if self._last_end is not None:
            total = now - self._last_end
            coll = coll_now - (self._last_coll or 0.0)
            exposed = exp_now - (self._last_exposed or 0.0)
            overlapped = ovl_now - (self._last_overlapped or 0.0)
            critical_coll = min(coll, exposed) if overlapped > 0 else coll
            record_step_breakdown(
                self.role, total - critical_coll, critical_coll, 0.0,
                exposed_s=exposed, overlapped_s=overlapped,
            )
        self._last_end = now
        self._last_coll = coll_now
        self._last_exposed = exp_now
        self._last_overlapped = ovl_now


# ---------------------------------------------------------------------------
# Train fault-tolerance telemetry: elastic resizes, gang restarts, collective
# aborts, and kill-to-resumed recovery time. Raw recovery samples are kept
# process-locally alongside the histogram so bench/CLI readers get exact
# p50/p99 (buckets alone can't give those).
# ---------------------------------------------------------------------------

_TRAIN_RECOVERY_BOUNDARIES_S = [
    0.1, 0.25, 0.5, 1, 2.5, 5, 10, 30, 60, 120, 300,
]

_train_ft_metrics: Optional[dict] = None
_train_ft_init_lock = threading.Lock()
_recovery_samples: List[float] = []


def _ensure_train_ft_metrics() -> dict:
    global _train_ft_metrics
    if _train_ft_metrics is None:
        with _train_ft_init_lock:
            if _train_ft_metrics is None:
                _train_ft_metrics = {
                    "resize": Counter(
                        "train_resize_total",
                        "Elastic worker-group resizes (survivors kept, "
                        "group re-formed at a new epoch)",
                        tag_keys=("run",),
                    ),
                    "restart": Counter(
                        "train_restart_total",
                        "Full gang restarts (all workers respawned)",
                        tag_keys=("run",),
                    ),
                    "abort": Counter(
                        "collective_abort_total",
                        "In-flight collective ops aborted by member "
                        "death or explicit abort",
                        tag_keys=("group",),
                    ),
                    "recovery": Histogram(
                        "train_recovery_seconds",
                        "Failure-detected to training-resumed wall time",
                        boundaries=_TRAIN_RECOVERY_BOUNDARIES_S,
                        tag_keys=("run", "kind"),
                    ),
                }
    return _train_ft_metrics


def record_train_resize(run: str):
    _ensure_train_ft_metrics()["resize"].inc(1.0, {"run": run})


def record_train_restart(run: str):
    _ensure_train_ft_metrics()["restart"].inc(1.0, {"run": run})


def record_collective_abort(group: str):
    _ensure_train_ft_metrics()["abort"].inc(1.0, {"group": group})


def record_train_recovery(run: str, seconds: float, kind: str = "resize"):
    _ensure_train_ft_metrics()["recovery"].observe(
        seconds, {"run": run, "kind": kind}
    )
    with _train_ft_init_lock:
        _recovery_samples.append(seconds)
        # bounded: a pathological kill-loop must not grow memory forever
        if len(_recovery_samples) > 10_000:
            del _recovery_samples[:5_000]


def train_recovery_percentiles() -> Dict[str, float]:
    """Process-local exact recovery-time percentiles (bench + CLI)."""
    with _train_ft_init_lock:
        samples = sorted(_recovery_samples)
    if not samples:
        return {}

    def _pct(p: float) -> float:
        return samples[min(len(samples) - 1, int(p * len(samples)))]

    return {
        "count": float(len(samples)),
        "p50_s": _pct(0.50),
        "p99_s": _pct(0.99),
        "max_s": samples[-1],
    }


def train_ft_counters() -> Dict[str, float]:
    """Process-local totals across all tag values (tests + CLI)."""
    m = _ensure_train_ft_metrics()
    out: Dict[str, float] = {}
    for label, metric in (
        ("resizes", m["resize"]),
        ("restarts", m["restart"]),
        ("aborts", m["abort"]),
    ):
        with metric._lock:
            out[label] = float(sum(metric._values.values()))
    return out


def train_ft_summary(
    payloads: List[dict],
    stragglers: Optional[List[dict]] = None,
) -> Dict[str, object]:
    """Cluster rollup of the train fault-tolerance plane from every
    worker's pushed snapshot (state.metrics_summary / dashboard).
    ``stragglers`` joins the timeseries plane's MAD verdicts (GCS
    ``straggler_verdicts`` RPC) into the same rollup, so the dashboard's
    train table answers "is anyone slow" next to "did anyone die"."""
    out = {
        "resizes": 0.0,
        "restarts": 0.0,
        "aborts": 0.0,
        "recoveries": 0.0,
        "recovery_mean_s": 0.0,
        "collective_exposed_s": 0.0,
        "collective_overlapped_s": 0.0,
        "overlap_fraction": 0.0,
    }
    recovery_sum = 0.0
    for payload in payloads:
        for snap in payload.get("metrics", []):
            name = snap.get("name")
            if name == "train_resize_total":
                out["resizes"] += sum(snap["values"].values())
            elif name == "train_restart_total":
                out["restarts"] += sum(snap["values"].values())
            elif name == "collective_abort_total":
                out["aborts"] += sum(snap["values"].values())
            elif name == "train_recovery_seconds":
                for counts in snap.get("counts", {}).values():
                    out["recoveries"] += float(sum(counts))
                recovery_sum += sum(snap.get("values", {}).values())
            elif name == "collective_exposed_seconds_total":
                out["collective_exposed_s"] += sum(snap["values"].values())
            elif name == "collective_overlapped_seconds_total":
                out["collective_overlapped_s"] += sum(
                    snap["values"].values()
                )
    if out["recoveries"]:
        out["recovery_mean_s"] = recovery_sum / out["recoveries"]
    overlap_total = (
        out["collective_exposed_s"] + out["collective_overlapped_s"]
    )
    if overlap_total > 0:
        out["overlap_fraction"] = (
            out["collective_overlapped_s"] / overlap_total
        )
    if stragglers is not None:
        out["stragglers"] = [v for v in stragglers if v.get("straggler")]
        out["straggler_verdicts"] = stragglers
    return out


# ---------------------------------------------------------------------------
# Serve fault-tolerance plane: handle-side failover retries, replica-side
# sheds (admission queue cap) and dead-on-arrival rejections, and graceful
# drain durations. Same shape as the train_ft section above: pushed
# snapshots roll up cluster-wide via serve_ft_summary; process-local
# serve_ft_counters back tests and bench.
# ---------------------------------------------------------------------------

_SERVE_DRAIN_BOUNDARIES_S = [
    0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10, 30, 60,
]

_serve_ft_metrics: Optional[dict] = None
_serve_ft_init_lock = threading.Lock()


def _ensure_serve_ft_metrics() -> dict:
    global _serve_ft_metrics
    if _serve_ft_metrics is None:
        with _serve_ft_init_lock:
            if _serve_ft_metrics is None:
                _serve_ft_metrics = {
                    "retry": Counter(
                        "serve_retry_total",
                        "Handle-side failover resubmissions (replica "
                        "death, drain race, transport failure, or "
                        "retried backpressure)",
                        tag_keys=("deployment", "reason", "replica"),
                    ),
                    "shed": Counter(
                        "serve_shed_total",
                        "Requests shed by replica admission control "
                        "(queue cap reached -> BackPressureError)",
                        tag_keys=("deployment",),
                    ),
                    "doa": Counter(
                        "serve_doa_total",
                        "Dead-on-arrival rejections (request deadline "
                        "already passed at admission)",
                        tag_keys=("deployment",),
                    ),
                    "drain": Histogram(
                        "serve_drain_seconds",
                        "Graceful replica drain duration (stop-routing "
                        "to last in-flight request finished)",
                        boundaries=_SERVE_DRAIN_BOUNDARIES_S,
                        tag_keys=("deployment",),
                    ),
                }
    return _serve_ft_metrics


def record_serve_retry(deployment: str, reason: str, replica: str = ""):
    """``replica`` is the OUTCOME replica the retry was resubmitted to —
    tagging it answers "which replica absorbed the failover" without
    joining against the span store."""
    _ensure_serve_ft_metrics()["retry"].inc(
        1.0, {"deployment": deployment, "reason": reason, "replica": replica}
    )


def record_serve_shed(deployment: str):
    _ensure_serve_ft_metrics()["shed"].inc(1.0, {"deployment": deployment})


def record_serve_doa(deployment: str):
    _ensure_serve_ft_metrics()["doa"].inc(1.0, {"deployment": deployment})


def record_serve_drain(deployment: str, seconds: float):
    _ensure_serve_ft_metrics()["drain"].observe(
        seconds, {"deployment": deployment}
    )


def serve_ft_counters() -> Dict[str, float]:
    """Process-local totals across all tag values (tests + bench). Note:
    retries count in the CALLING process (the handle runs the envelope),
    sheds/DOA/drains count in the replica process."""
    m = _ensure_serve_ft_metrics()
    out: Dict[str, float] = {}
    for label, metric in (
        ("retries", m["retry"]),
        ("sheds", m["shed"]),
        ("doa", m["doa"]),
    ):
        with metric._lock:
            out[label] = float(sum(metric._values.values()))
    drain = m["drain"]
    with drain._lock:
        out["drains"] = float(
            sum(sum(c) for c in drain._counts.values())
        )
    return out


def serve_ft_summary(payloads: List[dict]) -> Dict[str, object]:
    """Cluster rollup of the serve fault-tolerance plane from every
    worker's pushed snapshot (state.metrics_summary / dashboard)."""
    out = {
        "retries": 0.0,
        "sheds": 0.0,
        "doa": 0.0,
        "drains": 0.0,
        "drain_mean_s": 0.0,
        "retry_reasons": {},
    }
    drain_sum = 0.0
    for payload in payloads:
        for snap in payload.get("metrics", []):
            name = snap.get("name")
            if name == "serve_retry_total":
                out["retries"] += sum(snap["values"].values())
                for tag_json, value in snap["values"].items():
                    tags = dict(zip(snap["tag_keys"], json.loads(tag_json)))
                    reason = tags.get("reason", "?")
                    out["retry_reasons"][reason] = (
                        out["retry_reasons"].get(reason, 0.0) + value
                    )
            elif name == "serve_shed_total":
                out["sheds"] += sum(snap["values"].values())
            elif name == "serve_doa_total":
                out["doa"] += sum(snap["values"].values())
            elif name == "serve_drain_seconds":
                for counts in snap.get("counts", {}).values():
                    out["drains"] += float(sum(counts))
                drain_sum += sum(snap.get("values", {}).values())
    if out["drains"]:
        out["drain_mean_s"] = drain_sum / out["drains"]
    return out


# ---------------------------------------------------------------------------
# Partition-tolerance plane: control-plane retry counts (retry_call),
# per-peer circuit-breaker state, and node self-fence transitions. Same
# shape as the serve_ft section above: process-local partition_counters
# back tests and bench, pushed snapshots roll up via partition_summary.
# ---------------------------------------------------------------------------

_partition_metrics: Optional[dict] = None
_partition_init_lock = threading.Lock()


def _ensure_partition_metrics() -> dict:
    global _partition_metrics
    if _partition_metrics is None:
        with _partition_init_lock:
            if _partition_metrics is None:
                _partition_metrics = {
                    "retry": Counter(
                        "rpc_retry_total",
                        "Control-plane RPC retries performed by retry_call "
                        "after a transport-level failure",
                        tag_keys=("method",),
                    ),
                    "circuit": Gauge(
                        "rpc_circuit_state",
                        "Per-peer circuit-breaker state: 0 closed, 1 open "
                        "(failing fast), 2 half-open (probe in flight)",
                        tag_keys=("peer",),
                    ),
                    "fenced": Counter(
                        "node_fenced_total",
                        "Raylet self-fence transitions (GCS unreachable "
                        "past the liveness window)",
                        tag_keys=("node",),
                    ),
                }
    return _partition_metrics


def record_rpc_retry(method: str):
    _ensure_partition_metrics()["retry"].inc(1.0, {"method": method})


def set_rpc_circuit_state(peer: str, state: int):
    _ensure_partition_metrics()["circuit"].set(float(state), {"peer": peer})


def record_node_fenced(node: str):
    _ensure_partition_metrics()["fenced"].inc(1.0, {"node": node})


def partition_counters() -> Dict[str, float]:
    """Process-local totals (tests + bench): retries count in the calling
    process, fence transitions in the raylet's process. circuits_open is
    the number of peers whose breaker is currently not closed."""
    m = _ensure_partition_metrics()
    out: Dict[str, float] = {}
    for label, metric in (("retries", m["retry"]), ("fenced", m["fenced"])):
        with metric._lock:
            out[label] = float(sum(metric._values.values()))
    circuit = m["circuit"]
    with circuit._lock:
        out["circuits_open"] = float(
            sum(1 for v in circuit._values.values() if v)
        )
    return out


def partition_summary(payloads: List[dict]) -> Dict[str, object]:
    """Cluster rollup of the partition-tolerance plane from every worker's
    pushed snapshot (state.metrics_summary / dashboard)."""
    out = {
        "retries": 0.0,
        "fenced": 0.0,
        "circuits_open": 0.0,
        "retry_methods": {},
    }
    for payload in payloads:
        for snap in payload.get("metrics", []):
            name = snap.get("name")
            if name == "rpc_retry_total":
                out["retries"] += sum(snap["values"].values())
                for tag_json, value in snap["values"].items():
                    tags = dict(zip(snap["tag_keys"], json.loads(tag_json)))
                    method = tags.get("method", "?")
                    out["retry_methods"][method] = (
                        out["retry_methods"].get(method, 0.0) + value
                    )
            elif name == "node_fenced_total":
                out["fenced"] += sum(snap["values"].values())
            elif name == "rpc_circuit_state":
                out["circuits_open"] += sum(
                    1 for v in snap["values"].values() if v
                )
    return out


# ---------------------------------------------------------------------------
# Device telemetry: per-device HBM used/limit gauges sampled from
# jax.local_devices() memory stats, tagged by node and device. Sampled by
# the metrics pusher whenever jax is already imported in this process (no
# forced jax import for pure control-plane workers).
# ---------------------------------------------------------------------------

_device_metrics: Optional[dict] = None
_device_init_lock = threading.Lock()


def _ensure_device_metrics() -> dict:
    global _device_metrics
    if _device_metrics is None:
        with _device_init_lock:
            if _device_metrics is None:
                _device_metrics = {
                    "used": Gauge(
                        "tpu_hbm_used_bytes",
                        "Device memory in use (HBM on TPU)",
                        tag_keys=("node", "device", "kind"),
                    ),
                    "limit": Gauge(
                        "tpu_hbm_limit_bytes",
                        "Device memory capacity (HBM on TPU)",
                        tag_keys=("node", "device", "kind"),
                    ),
                }
    return _device_metrics


def sample_device_memory() -> Dict[str, Dict[str, float]]:
    """Set the per-device HBM gauges from jax.local_devices() memory stats
    and return {device: {used, limit}}. Devices without memory stats (CPU
    backend) report zeros so the series exist on every platform."""
    import sys

    if "jax" not in sys.modules:
        return {}
    import jax

    node = _node_hex()
    m = _ensure_device_metrics()
    out: Dict[str, Dict[str, float]] = {}
    try:
        devices = jax.local_devices()
    except Exception:
        return {}
    for d in devices:
        try:
            stats = d.memory_stats() or {}
        except Exception:
            stats = {}
        used = float(stats.get("bytes_in_use", 0) or 0)
        limit = float(stats.get("bytes_limit", 0) or 0)
        dev = f"{getattr(d, 'platform', 'dev')}:{getattr(d, 'id', 0)}"
        kind = str(getattr(d, "device_kind", ""))
        tags = {"node": node, "device": dev, "kind": kind}
        m["used"].set(used, tags)
        m["limit"].set(limit, tags)
        out[dev] = {"used": used, "limit": limit}
    return out


# ---------------------------------------------------------------------------
# KV-cache plane instrumentation (the paged prefix cache's proof layer):
# the engine records per-admission hit/computed token counts and TTFT
# (tagged hit | miss), the KVCacheManager keeps the block-pool gauges and
# eviction/backpressure counters current. kvcache_summary() is the one
# aggregation shared by state.metrics_summary(), the `ray_tpu kvcache`
# CLI, and the dashboard's /api/kvcache.
# ---------------------------------------------------------------------------

_KVCACHE_TTFT_BOUNDARIES_MS = [
    1, 5, 10, 25, 50, 100, 250, 500, 1000, 2500, 5000, 10000,
]

_kvcache_metrics: Optional[dict] = None
_kvcache_init_lock = threading.Lock()


def _ensure_kvcache_metrics() -> dict:
    global _kvcache_metrics
    if _kvcache_metrics is None:
        with _kvcache_init_lock:
            if _kvcache_metrics is None:
                # every kvcache metric carries the replica's mesh shape
                # ("tp=1", "tp=2", ...) so sharded and single-device
                # replicas separate cleanly in one cluster rollup
                _kvcache_metrics = {
                    "hit_tokens": Counter(
                        "kvcache_prefix_hit_tokens_total",
                        "Prompt tokens served from the prefix cache "
                        "instead of prefilled",
                        tag_keys=("mesh",),
                    ),
                    "prefill_tokens": Counter(
                        "kvcache_prefill_tokens_total",
                        "Prompt tokens actually computed at admission",
                        tag_keys=("mesh",),
                    ),
                    "evictions": Counter(
                        "kvcache_evictions_total",
                        "KV blocks LRU-evicted from the prefix index",
                        tag_keys=("mesh",),
                    ),
                    "blocked": Counter(
                        "kvcache_admission_blocked_total",
                        "Admissions deferred: block pool exhausted "
                        "(backpressure, not OOM)",
                        tag_keys=("mesh",),
                    ),
                    "blocks_in_use": Gauge(
                        "kvcache_blocks_in_use",
                        "Allocated KV blocks in this engine's pool",
                        tag_keys=("mesh",),
                    ),
                    "blocks_capacity": Gauge(
                        "kvcache_blocks_capacity",
                        "Total KV blocks in this engine's pool",
                        tag_keys=("mesh",),
                    ),
                    # "tier" separates where the prefix came from:
                    # local (this replica's radix), peer (pulled through
                    # the cluster KV tier), miss (computed from scratch)
                    "ttft": Histogram(
                        "kvcache_ttft_ms",
                        "Time to first token (ms) by prefix-cache outcome",
                        boundaries=_KVCACHE_TTFT_BOUNDARIES_MS,
                        tag_keys=("cache", "mesh", "tier"),
                    ),
                }
    return _kvcache_metrics


def record_kvcache_prefill(
    hit_tokens: int, computed_tokens: int, mesh: str = "tp=1"
):
    m = _ensure_kvcache_metrics()
    m["hit_tokens"].inc(float(hit_tokens), {"mesh": mesh})
    m["prefill_tokens"].inc(float(computed_tokens), {"mesh": mesh})


def record_kvcache_eviction(n: int = 1, mesh: str = "tp=1"):
    _ensure_kvcache_metrics()["evictions"].inc(float(n), {"mesh": mesh})


def record_kvcache_blocked(mesh: str = "tp=1"):
    _ensure_kvcache_metrics()["blocked"].inc(1.0, {"mesh": mesh})


def set_kvcache_blocks(in_use: int, capacity: int, mesh: str = "tp=1"):
    m = _ensure_kvcache_metrics()
    m["blocks_in_use"].set(float(in_use), {"mesh": mesh})
    m["blocks_capacity"].set(float(capacity), {"mesh": mesh})
    if capacity > 0:
        try:
            from . import timeseries as _ts

            _ts.register_series(
                _ts.KV_POOL_OCCUPANCY, labels={"mesh": mesh}
            ).record(float(in_use) / float(capacity))
        except Exception:
            pass  # telemetry is best-effort; the gauges above are canonical


def record_kvcache_ttft(
    seconds: float, hit: bool, mesh: str = "tp=1", tier: str = "local"
):
    _ensure_kvcache_metrics()["ttft"].observe(
        seconds * 1000.0,
        {"cache": "hit" if hit else "miss", "mesh": mesh, "tier": tier},
    )


def kvcache_counters() -> Dict[str, float]:
    """Process-local counter readback (tests + bench; no cluster needed)."""
    m = _ensure_kvcache_metrics()

    def _total(metric) -> float:
        with metric._lock:
            return float(sum(metric._values.values()))

    return {
        "prefix_hit_tokens": _total(m["hit_tokens"]),
        "prefill_tokens_computed": _total(m["prefill_tokens"]),
        "evictions": _total(m["evictions"]),
        "admission_blocked": _total(m["blocked"]),
    }


def kvcache_summary(payloads: List[dict]) -> Dict[str, object]:
    """Cluster-wide KV-cache rollup from pushed payloads: counters and
    block gauges summed across engines (each engine owns its own pool, so
    the cluster total is the sum), TTFT mean by hit/miss tag."""
    out: Dict[str, object] = {
        "prefix_hit_tokens": 0.0,
        "prefill_tokens_computed": 0.0,
        "evictions": 0.0,
        "admission_blocked": 0.0,
        "blocks_in_use": 0.0,
        "blocks_capacity": 0.0,
        "ttft_ms": {},
    }
    simple = {
        "kvcache_prefix_hit_tokens_total": "prefix_hit_tokens",
        "kvcache_prefill_tokens_total": "prefill_tokens_computed",
        "kvcache_evictions_total": "evictions",
        "kvcache_admission_blocked_total": "admission_blocked",
        "kvcache_blocks_in_use": "blocks_in_use",
        "kvcache_blocks_capacity": "blocks_capacity",
    }
    ttft: Dict[str, Dict[str, float]] = out["ttft_ms"]  # type: ignore[assignment]
    ttft_buckets: Dict[str, List[float]] = {}
    ttft_bounds: Dict[str, List[float]] = {}
    for payload in payloads:
        for snap in payload.get("metrics", []):
            name = snap["name"]
            if name in simple:
                out[simple[name]] += float(sum(snap["values"].values()))
            elif name == "kvcache_ttft_ms":
                for tag_json, counts in snap.get("counts", {}).items():
                    tags = dict(zip(snap["tag_keys"], json.loads(tag_json)))
                    cache = tags.get("cache", "?")
                    row = ttft.setdefault(
                        cache, {"count": 0.0, "sum_ms": 0.0}
                    )
                    row["count"] += float(sum(counts))
                    row["sum_ms"] += float(
                        snap["values"].get(tag_json, 0.0)
                    )
                    merged = ttft_buckets.setdefault(cache, [0.0] * len(counts))
                    if len(merged) < len(counts):
                        merged.extend([0.0] * (len(counts) - len(merged)))
                    for i, c in enumerate(counts):
                        merged[i] += c
                    ttft_bounds.setdefault(
                        cache,
                        list(snap.get("boundaries")
                             or _KVCACHE_TTFT_BOUNDARIES_MS),
                    )
    for cache, row in ttft.items():
        if row["count"]:
            row["mean_ms"] = row["sum_ms"] / row["count"]
            counts = ttft_buckets.get(cache)
            if counts:
                bounds = ttft_bounds[cache]
                row["p50_ms"] = quantile_from_buckets(bounds, counts, 0.50)
                row["p99_ms"] = quantile_from_buckets(bounds, counts, 0.99)
    return out


# ---------------------------------------------------------------------------
# Cluster KV-tier instrumentation (kvtier's proof layer): per-request
# resolution outcomes (hit = registry had a deeper prefix, peer_pull =
# the blocks actually arrived and decoded, recompute = tier consulted
# but the prefix was prefilled anyway — miss, lease conflict, dead
# holder), plus the logical/wire byte split so the int8 shipment codec's
# compression is visible instead of silently folded into one number.
# kvtier_summary() is shared by the `ray_tpu kvtier` CLI and the
# dashboard's /api/kvtier; the per-tier TTFT split rides the kvcache
# histogram's "tier" tag rather than a second histogram.
# ---------------------------------------------------------------------------

_kvtier_metrics: Optional[dict] = None
_kvtier_init_lock = threading.Lock()

_KVTIER_OUTCOMES = ("hit", "peer_pull", "recompute")


def _ensure_kvtier_metrics() -> dict:
    global _kvtier_metrics
    if _kvtier_metrics is None:
        with _kvtier_init_lock:
            if _kvtier_metrics is None:
                _kvtier_metrics = {
                    "hit": Counter(
                        "kvtier_hit_total",
                        "Tier resolutions that found a registered prefix "
                        "deeper than the local radix",
                        tag_keys=("model",),
                    ),
                    "peer_pull": Counter(
                        "kvtier_peer_pull_total",
                        "Warm prefixes successfully pulled from a peer "
                        "replica and adopted",
                        tag_keys=("model",),
                    ),
                    "recompute": Counter(
                        "kvtier_recompute_total",
                        "Tier consultations that fell back to prefill "
                        "(miss, lease conflict, or dead holder)",
                        tag_keys=("model",),
                    ),
                    "transfer_bytes": Counter(
                        "kvtier_transfer_bytes_total",
                        "KV bytes moved through the tier by kind "
                        "(logical = raw leaf bytes, wire = encoded)",
                        tag_keys=("model", "kind"),
                    ),
                }
    return _kvtier_metrics


def record_kvtier(outcome: str, model: str = ""):
    """One tier resolution outcome: hit | peer_pull | recompute."""
    if outcome not in _KVTIER_OUTCOMES:
        raise ValueError(
            f"kvtier outcome must be one of {_KVTIER_OUTCOMES}, "
            f"got {outcome!r}"
        )
    _ensure_kvtier_metrics()[outcome].inc(1.0, {"model": model})


def record_kvtier_transfer(
    logical_nbytes: int, wire_nbytes: int, model: str = ""
):
    m = _ensure_kvtier_metrics()
    m["transfer_bytes"].inc(float(logical_nbytes),
                            {"model": model, "kind": "logical"})
    m["transfer_bytes"].inc(float(wire_nbytes),
                            {"model": model, "kind": "wire"})


def kvtier_counters() -> Dict[str, float]:
    """Process-local readback (tests + bench; no cluster needed)."""
    m = _ensure_kvtier_metrics()

    def _total(metric) -> float:
        with metric._lock:
            return float(sum(metric._values.values()))

    def _kind(kind: str) -> float:
        tm = m["transfer_bytes"]
        with tm._lock:
            return float(sum(
                v for k, v in tm._values.items() if kind in k
            ))

    return {
        "hit": _total(m["hit"]),
        "peer_pull": _total(m["peer_pull"]),
        "recompute": _total(m["recompute"]),
        "transfer_logical_bytes": _kind("logical"),
        "transfer_wire_bytes": _kind("wire"),
    }


def kvtier_summary(payloads: List[dict]) -> Dict[str, object]:
    """Cluster-wide KV-tier rollup from pushed payloads: outcome counters
    and byte totals summed across replicas, plus the per-tier TTFT split
    (local | peer | miss) read off the kvcache histogram's tier tag."""
    out: Dict[str, object] = {
        "hit": 0.0,
        "peer_pull": 0.0,
        "recompute": 0.0,
        "transfer_bytes": {"logical": 0.0, "wire": 0.0},
        "ttft_ms_by_tier": {},
    }
    simple = {
        "kvtier_hit_total": "hit",
        "kvtier_peer_pull_total": "peer_pull",
        "kvtier_recompute_total": "recompute",
    }
    ttft: Dict[str, Dict[str, float]] = out["ttft_ms_by_tier"]  # type: ignore[assignment]
    ttft_buckets: Dict[str, List[float]] = {}
    ttft_bounds: Dict[str, List[float]] = {}
    xfer: Dict[str, float] = out["transfer_bytes"]  # type: ignore[assignment]
    for payload in payloads:
        for snap in payload.get("metrics", []):
            name = snap["name"]
            if name in simple:
                out[simple[name]] += float(sum(snap["values"].values()))
            elif name == "kvtier_transfer_bytes_total":
                for tag_json, v in snap["values"].items():
                    tags = dict(zip(snap["tag_keys"], json.loads(tag_json)))
                    kind = tags.get("kind", "?")
                    xfer[kind] = xfer.get(kind, 0.0) + float(v)
            elif name == "kvcache_ttft_ms":
                for tag_json, counts in snap.get("counts", {}).items():
                    tags = dict(zip(snap["tag_keys"], json.loads(tag_json)))
                    tier = tags.get("tier", "local")
                    row = ttft.setdefault(
                        tier, {"count": 0.0, "sum_ms": 0.0}
                    )
                    row["count"] += float(sum(counts))
                    row["sum_ms"] += float(
                        snap["values"].get(tag_json, 0.0)
                    )
                    merged = ttft_buckets.setdefault(
                        tier, [0.0] * len(counts)
                    )
                    if len(merged) < len(counts):
                        merged.extend([0.0] * (len(counts) - len(merged)))
                    for i, c in enumerate(counts):
                        merged[i] += c
                    ttft_bounds.setdefault(
                        tier,
                        list(snap.get("boundaries")
                             or _KVCACHE_TTFT_BOUNDARIES_MS),
                    )
    for tier, row in ttft.items():
        if row["count"]:
            row["mean_ms"] = row["sum_ms"] / row["count"]
            counts = ttft_buckets.get(tier)
            if counts:
                bounds = ttft_bounds[tier]
                row["p50_ms"] = quantile_from_buckets(bounds, counts, 0.50)
                row["p99_ms"] = quantile_from_buckets(bounds, counts, 0.99)
    return out


# ---------------------------------------------------------------------------
# Histogram quantiles from pushed buckets. The push plane ships bucket
# counts, not raw samples, so cluster rollups (state.metrics_summary, the
# autoscale controller, the dashboard) estimate percentiles by linear
# interpolation inside the containing bucket — the same estimator
# Prometheus's histogram_quantile uses. Exact sample percentiles stay
# available only where a process kept raw samples (e.g. train recovery).
# ---------------------------------------------------------------------------


def quantile_from_buckets(
    boundaries: List[float], counts: List[float], q: float
) -> Optional[float]:
    """Estimate the q-quantile from non-cumulative histogram buckets.

    Bucket i spans (boundaries[i-1], boundaries[i]]; the first bucket's
    lower edge is 0 (all recorded values are non-negative) and the overflow
    bucket clamps to the last boundary since it has no upper edge to
    interpolate toward. Returns None for an empty histogram."""
    total = float(sum(counts))
    if total <= 0:
        return None
    rank = min(max(q, 0.0), 1.0) * total
    cum = 0.0
    lo = 0.0
    for i, c in enumerate(counts):
        if c and cum + c >= rank:
            if i >= len(boundaries):
                return float(lo)
            hi = float(boundaries[i])
            return lo + (hi - lo) * ((rank - cum) / c)
        cum += c
        if i < len(boundaries):
            lo = float(boundaries[i])
    return float(lo)


def merged_histogram(
    payloads: List[dict],
    name: str,
    tag_filter: Optional[Dict[str, str]] = None,
) -> Optional[dict]:
    """Merge one histogram's buckets across every pushed payload, keeping
    only series whose tags include ``tag_filter``. Returns {boundaries,
    counts, sum, count} or None if no matching series was pushed."""
    boundaries: Optional[List[float]] = None
    merged: Optional[List[float]] = None
    total_sum = 0.0
    for payload in payloads:
        for snap in payload.get("metrics", []):
            if snap.get("name") != name:
                continue
            for tag_json, counts in snap.get("counts", {}).items():
                if tag_filter:
                    tags = dict(
                        zip(snap.get("tag_keys", ()), json.loads(tag_json))
                    )
                    if any(tags.get(k) != v for k, v in tag_filter.items()):
                        continue
                if merged is None:
                    boundaries = list(snap.get("boundaries") or [])
                    merged = [0.0] * len(counts)
                if len(merged) < len(counts):
                    merged.extend([0.0] * (len(counts) - len(merged)))
                for i, c in enumerate(counts):
                    merged[i] += c
                total_sum += float(snap.get("values", {}).get(tag_json, 0.0))
    if merged is None:
        return None
    return {
        "boundaries": boundaries or [],
        "counts": merged,
        "sum": total_sum,
        "count": float(sum(merged)),
    }


# ---------------------------------------------------------------------------
# Serve latency plane: per-deployment TTFT (admission to first output:
# first stream item, or completion for unary calls) and replica warmup
# (actor start to ready-to-serve, including weight-plane resolution). The
# TTFT p99 here is the SLO signal the autoscale controller evaluates.
# ---------------------------------------------------------------------------

_SERVE_TTFT_BOUNDARIES_S = [
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1, 2.5, 5,
    10, 30,
]

_SERVE_WARMUP_BOUNDARIES_S = [
    0.01, 0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10, 30, 60, 120,
]

_serve_latency_metrics: Optional[dict] = None
_serve_latency_init_lock = threading.Lock()


def _ensure_serve_latency_metrics() -> dict:
    global _serve_latency_metrics
    if _serve_latency_metrics is None:
        with _serve_latency_init_lock:
            if _serve_latency_metrics is None:
                _serve_latency_metrics = {
                    "ttft": Histogram(
                        "serve_ttft_seconds",
                        "Replica-side time to first output: admission "
                        "(queue wait included) to first stream item or "
                        "unary completion",
                        boundaries=_SERVE_TTFT_BOUNDARIES_S,
                        tag_keys=("deployment",),
                    ),
                    "warmup": Histogram(
                        "serve_replica_warmup_seconds",
                        "Replica cold-start: constructor entry to "
                        "ready-to-serve (user init + weight resolution "
                        "+ warmup hook)",
                        boundaries=_SERVE_WARMUP_BOUNDARIES_S,
                        tag_keys=("deployment",),
                    ),
                }
    return _serve_latency_metrics


def record_serve_ttft(deployment: str, seconds: float,
                      trace_id: Optional[str] = None):
    """``trace_id`` (when the request is traced) becomes the bucket's
    exemplar, so a bad p99 bucket links to a concrete trace."""
    _ensure_serve_latency_metrics()["ttft"].observe(
        seconds, {"deployment": deployment}, exemplar=trace_id
    )


def record_serve_replica_warmup(deployment: str, seconds: float):
    _ensure_serve_latency_metrics()["warmup"].observe(
        seconds, {"deployment": deployment}
    )


def serve_latency_summary(payloads: List[dict]) -> Dict[str, object]:
    """Cluster rollup: per-deployment TTFT (ms) and warmup (s) with
    bucket-derived p50/p99 (state.metrics_summary / dashboard / CLI)."""
    out: Dict[str, object] = {"ttft_ms": {}, "warmup_s": {}}
    specs = (
        ("serve_ttft_seconds", "ttft_ms", 1000.0),
        ("serve_replica_warmup_seconds", "warmup_s", 1.0),
    )
    deployments: Dict[str, set] = {key: set() for _, key, _ in specs}
    for payload in payloads:
        for snap in payload.get("metrics", []):
            for name, key, _scale in specs:
                if snap.get("name") != name:
                    continue
                for tag_json in snap.get("counts", {}):
                    tags = dict(
                        zip(snap.get("tag_keys", ()), json.loads(tag_json))
                    )
                    deployments[key].add(tags.get("deployment", "?"))
    for name, key, scale in specs:
        section: Dict[str, dict] = out[key]  # type: ignore[assignment]
        for dep in sorted(deployments[key]):
            m = merged_histogram(payloads, name, {"deployment": dep})
            if not m or not m["count"]:
                continue
            section[dep] = {
                "count": m["count"],
                "mean": m["sum"] / m["count"] * scale,
                "p50": _scaled_quantile(m, 0.50, scale),
                "p99": _scaled_quantile(m, 0.99, scale),
            }
    return out


def _scaled_quantile(m: dict, q: float, scale: float) -> Optional[float]:
    est = quantile_from_buckets(m["boundaries"], m["counts"], q)
    return None if est is None else est * scale


# ---------------------------------------------------------------------------
# LLM decode plane: inter-token latency (the per-token cadence a streaming
# client sees — TTFT's sibling for everything after the first token) and
# the speculative-decoding ledger (proposed vs accepted draft tokens; the
# acceptance rate decides whether speculation is paying for itself on this
# workload). Engines record through ray_tpu.llm.engine's _record_itl /
# _record_spec; llm_summary() is the one rollup shared by
# state.metrics_summary()["llm"] and the dashboard's /api/serve.
# ---------------------------------------------------------------------------

_SERVE_ITL_BOUNDARIES_S = [
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1, 2.5, 5,
]

_llm_metrics: Optional[dict] = None
_llm_init_lock = threading.Lock()


def _ensure_llm_metrics() -> dict:
    global _llm_metrics
    if _llm_metrics is None:
        with _llm_init_lock:
            if _llm_metrics is None:
                _llm_metrics = {
                    "itl": Histogram(
                        "serve_itl_seconds",
                        "Inter-token latency: gap between consecutive "
                        "emitted tokens of one request (a speculative "
                        "step landing n tokens records n observations "
                        "of gap/n)",
                        boundaries=_SERVE_ITL_BOUNDARIES_S,
                        tag_keys=("mesh",),
                    ),
                    "proposed": Counter(
                        "spec_proposed_tokens_total",
                        "Draft tokens proposed to the verify pass",
                        tag_keys=("mesh",),
                    ),
                    "accepted": Counter(
                        "spec_accepted_tokens_total",
                        "Draft tokens the target accepted (excludes the "
                        "per-step bonus/correction token)",
                        tag_keys=("mesh",),
                    ),
                    "acceptance": Gauge(
                        "spec_acceptance_rate",
                        "Lifetime accepted/proposed ratio of this "
                        "process's speculative engines",
                        tag_keys=("mesh",),
                    ),
                }
    return _llm_metrics


def record_serve_itl(seconds: float, mesh: str = "tp=1", n: int = 1):
    m = _ensure_llm_metrics()
    for _ in range(max(int(n), 1)):
        m["itl"].observe(seconds, {"mesh": mesh})


def record_spec_tokens(proposed: int, accepted: int, mesh: str = "tp=1"):
    m = _ensure_llm_metrics()
    m["proposed"].inc(float(proposed), {"mesh": mesh})
    m["accepted"].inc(float(accepted), {"mesh": mesh})
    with m["proposed"]._lock:
        total_p = float(sum(m["proposed"]._values.values()))
    with m["accepted"]._lock:
        total_a = float(sum(m["accepted"]._values.values()))
    if total_p > 0:
        m["acceptance"].set(total_a / total_p, {"mesh": mesh})


def llm_counters() -> Dict[str, float]:
    """Process-local readback (tests + bench; no cluster needed)."""
    m = _ensure_llm_metrics()

    def _total(metric) -> float:
        with metric._lock:
            return float(sum(metric._values.values()))

    def _count(hist) -> float:
        with hist._lock:
            return float(
                sum(sum(c) for c in hist._counts.values())
            )

    return {
        "spec_proposed_tokens": _total(m["proposed"]),
        "spec_accepted_tokens": _total(m["accepted"]),
        "itl_observations": _count(m["itl"]),
    }


def llm_summary(payloads: List[dict]) -> Dict[str, object]:
    """Cluster rollup: speculative acceptance + ITL percentiles (ms)."""
    out: Dict[str, object] = {
        "spec_proposed_tokens": 0.0,
        "spec_accepted_tokens": 0.0,
        "spec_acceptance_rate": None,
        "itl_ms": None,
    }
    simple = {
        "spec_proposed_tokens_total": "spec_proposed_tokens",
        "spec_accepted_tokens_total": "spec_accepted_tokens",
    }
    for payload in payloads:
        for snap in payload.get("metrics", []):
            name = snap.get("name")
            if name in simple:
                out[simple[name]] += float(sum(snap["values"].values()))
    if out["spec_proposed_tokens"]:
        out["spec_acceptance_rate"] = (
            out["spec_accepted_tokens"] / out["spec_proposed_tokens"]
        )
    m = merged_histogram(payloads, "serve_itl_seconds")
    if m and m["count"]:
        out["itl_ms"] = {
            "count": m["count"],
            "mean": m["sum"] / m["count"] * 1000.0,
            "p50": _scaled_quantile(m, 0.50, 1000.0),
            "p99": _scaled_quantile(m, 0.99, 1000.0),
        }
    return out


# ---------------------------------------------------------------------------
# Adapter plane (ray_tpu.lora): per-replica AdapterStore hit/cold-attach/
# evict counters, a live-slots gauge, and the cold-attach latency histogram
# — the number that tells an operator whether max_live is sized right
# (thrashing shows up as evictions + cold-attach p99, a healthy fleet shows
# hits). Stores record through lora/store.py's lazy hooks; adapter_summary()
# is the one rollup shared by state.metrics_summary()["adapters"], the
# `ray_tpu adapters` CLI, and the dashboard's /api/serve.
# ---------------------------------------------------------------------------

_ADAPTER_ATTACH_BOUNDARIES_S = [
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10,
]

_adapter_metrics: Optional[dict] = None
_adapter_init_lock = threading.Lock()


def _ensure_adapter_metrics() -> dict:
    global _adapter_metrics
    if _adapter_metrics is None:
        with _adapter_init_lock:
            if _adapter_metrics is None:
                _adapter_metrics = {
                    "hits": Counter(
                        "adapter_hit_total",
                        "Adapter lease acquisitions served by a resident "
                        "slot (no weight-plane pull)",
                        tag_keys=("mesh",),
                    ),
                    "cold": Counter(
                        "adapter_cold_attach_total",
                        "Adapter lease acquisitions that pulled and wrote "
                        "the adapter into a slot",
                        tag_keys=("mesh",),
                    ),
                    "evict": Counter(
                        "adapter_evict_total",
                        "Idle adapters evicted from their slot (LRU) to "
                        "make room for a cold attach",
                        tag_keys=("mesh",),
                    ),
                    "live": Gauge(
                        "adapter_slots_live",
                        "Adapters currently resident in this process's "
                        "slot banks (pinned + idle)",
                        tag_keys=("mesh",),
                    ),
                    "attach": Histogram(
                        "adapter_cold_attach_seconds",
                        "Cold-attach latency: source fetch + normalize + "
                        "slot write, the TTFT tax of an adapter's first "
                        "request on a replica",
                        boundaries=_ADAPTER_ATTACH_BOUNDARIES_S,
                        tag_keys=("mesh",),
                    ),
                }
    return _adapter_metrics


def record_adapter_hit(mesh: str = "tp=1"):
    _ensure_adapter_metrics()["hits"].inc(1.0, {"mesh": mesh})


def record_adapter_cold_attach(seconds: float, mesh: str = "tp=1"):
    m = _ensure_adapter_metrics()
    m["cold"].inc(1.0, {"mesh": mesh})
    m["attach"].observe(seconds, {"mesh": mesh})


def record_adapter_evict(mesh: str = "tp=1"):
    _ensure_adapter_metrics()["evict"].inc(1.0, {"mesh": mesh})


def set_adapter_slots_live(n: int, mesh: str = "tp=1"):
    _ensure_adapter_metrics()["live"].set(float(n), {"mesh": mesh})


def adapter_counters() -> Dict[str, float]:
    """Process-local readback (tests + bench; no cluster needed)."""
    m = _ensure_adapter_metrics()

    def _total(metric) -> float:
        with metric._lock:
            return float(sum(metric._values.values()))

    return {
        "adapter_hits": _total(m["hits"]),
        "adapter_cold_attaches": _total(m["cold"]),
        "adapter_evictions": _total(m["evict"]),
    }


def adapter_summary(payloads: List[dict]) -> Dict[str, object]:
    """Cluster rollup: hit rate + cold-attach latency percentiles (ms)."""
    out: Dict[str, object] = {
        "hits": 0.0,
        "cold_attaches": 0.0,
        "evictions": 0.0,
        "slots_live": 0.0,
        "hit_rate": None,
        "cold_attach_ms": None,
    }
    simple = {
        "adapter_hit_total": "hits",
        "adapter_cold_attach_total": "cold_attaches",
        "adapter_evict_total": "evictions",
        "adapter_slots_live": "slots_live",
    }
    for payload in payloads:
        for snap in payload.get("metrics", []):
            name = snap.get("name")
            if name in simple:
                out[simple[name]] += float(sum(snap["values"].values()))
    acquired = out["hits"] + out["cold_attaches"]
    if acquired:
        out["hit_rate"] = out["hits"] / acquired
    m = merged_histogram(payloads, "adapter_cold_attach_seconds")
    if m and m["count"]:
        out["cold_attach_ms"] = {
            "count": m["count"],
            "mean": m["sum"] / m["count"] * 1000.0,
            "p50": _scaled_quantile(m, 0.50, 1000.0),
            "p99": _scaled_quantile(m, 0.99, 1000.0),
        }
    return out


# ---------------------------------------------------------------------------
# Ingress plane: per-proxy request counters / inflight gauge / end-to-end
# proxy latency, tagged proxy_id so the multi-proxy data plane shows per-
# listener load spread. The proxies record through pre-bound handles
# (ingress_handles) — at saturation the data plane runs thousands of
# requests a second per proxy, and the per-call tag-dict merge is real
# overhead there.
# ---------------------------------------------------------------------------

_INGRESS_LATENCY_BOUNDARIES_MS = [
    0.5, 1, 2.5, 5, 10, 25, 50, 100, 250, 500, 1000, 2500, 5000,
]

_ingress_metrics: Optional[dict] = None
_ingress_init_lock = threading.Lock()


def _ensure_ingress_metrics() -> dict:
    global _ingress_metrics
    if _ingress_metrics is None:
        with _ingress_init_lock:
            if _ingress_metrics is None:
                _ingress_metrics = {
                    "requests": Counter(
                        "proxy_requests_total",
                        "Requests completed by an ingress proxy, by "
                        "outcome (ok/error/shed/timeout/drain)",
                        tag_keys=("proxy_id", "outcome"),
                    ),
                    "inflight": Gauge(
                        "proxy_inflight",
                        "Requests currently being served by this proxy",
                        tag_keys=("proxy_id",),
                    ),
                    "latency": Histogram(
                        "proxy_request_latency_ms",
                        "End-to-end proxy latency: request read to "
                        "response write",
                        boundaries=_INGRESS_LATENCY_BOUNDARIES_MS,
                        tag_keys=("proxy_id",),
                    ),
                }
    return _ingress_metrics


def ingress_handles(proxy_id: str) -> dict:
    """Pre-bound per-proxy metric handles for the proxy request loop:
    {ok, error, shed, timeout, drain} counters plus {inflight, latency}.
    Bind once at proxy start; each record is then a lock + slot update."""
    m = _ensure_ingress_metrics()
    req = m["requests"]
    return {
        "ok": req.bind(proxy_id=proxy_id, outcome="ok"),
        "error": req.bind(proxy_id=proxy_id, outcome="error"),
        "shed": req.bind(proxy_id=proxy_id, outcome="shed"),
        "timeout": req.bind(proxy_id=proxy_id, outcome="timeout"),
        "drain": req.bind(proxy_id=proxy_id, outcome="drain"),
        "inflight": m["inflight"].bind(proxy_id=proxy_id),
        "latency": m["latency"].bind(proxy_id=proxy_id),
    }


def ingress_summary(payloads: List[dict]) -> Dict[str, object]:
    """Cluster rollup for state.metrics_summary()["ingress"]: per-proxy
    request counts by outcome, current inflight, and latency p50/p99
    (ms), plus fleet totals."""
    proxies: Dict[str, dict] = {}

    def row(proxy_id: str) -> dict:
        return proxies.setdefault(
            proxy_id, {"requests": {}, "inflight": 0.0}
        )

    for payload in payloads:
        for snap in payload.get("metrics", []):
            name = snap.get("name")
            tag_keys = snap.get("tag_keys", ())
            if name == "proxy_requests_total":
                for tag_json, value in snap.get("values", {}).items():
                    tags = dict(zip(tag_keys, json.loads(tag_json)))
                    outcomes = row(tags.get("proxy_id", "?"))["requests"]
                    outcome = tags.get("outcome", "?")
                    outcomes[outcome] = outcomes.get(outcome, 0.0) + value
            elif name == "proxy_inflight":
                for tag_json, value in snap.get("values", {}).items():
                    tags = dict(zip(tag_keys, json.loads(tag_json)))
                    row(tags.get("proxy_id", "?"))["inflight"] = value
    total_requests = 0.0
    for proxy_id, entry in proxies.items():
        entry["total"] = sum(entry["requests"].values())
        total_requests += entry["total"]
        m = merged_histogram(
            payloads, "proxy_request_latency_ms", {"proxy_id": proxy_id}
        )
        if m and m["count"]:
            entry["latency_ms"] = {
                "count": m["count"],
                "mean": m["sum"] / m["count"],
                "p50": _scaled_quantile(m, 0.50, 1.0),
                "p99": _scaled_quantile(m, 0.99, 1.0),
            }
    return {
        "proxies": {k: proxies[k] for k in sorted(proxies)},
        "num_proxies": len(proxies),
        "requests_total": total_requests,
    }


# ---------------------------------------------------------------------------
# Hang-watchdog plane (util/watchdog.py): how many watched units of work
# (replica requests, collective epochs) are currently past their stuck
# threshold in this process. A nonzero value is the "look at the flight
# recorder's watchdog_stuck stack captures" signal.
# ---------------------------------------------------------------------------

_watchdog_metrics: Optional[dict] = None
_watchdog_init_lock = threading.Lock()


def _ensure_watchdog_metrics() -> dict:
    global _watchdog_metrics
    if _watchdog_metrics is None:
        with _watchdog_init_lock:
            if _watchdog_metrics is None:
                _watchdog_metrics = {
                    "stuck": Gauge(
                        "stuck_requests",
                        "Watched in-flight work currently past its hang "
                        "threshold (deadline x watchdog multiple)",
                    ),
                }
    return _watchdog_metrics


def set_stuck_requests(count: int):
    _ensure_watchdog_metrics()["stuck"].set(float(count))


# ---------------------------------------------------------------------------
# Autoscale decision telemetry: scale-up/down counters per deployment and
# the breach-to-decision latency histogram (how long pressure persisted
# before the controller acted — the "reacting in seconds, not minutes"
# proof). Recorded in the serve controller process; events themselves live
# in the controller's event log (GCS key serve:autoscale_log).
# ---------------------------------------------------------------------------

_AUTOSCALE_DECISION_BOUNDARIES_S = [
    0.1, 0.25, 0.5, 1, 2.5, 5, 10, 30, 60, 120, 300,
]

_autoscale_metrics: Optional[dict] = None
_autoscale_init_lock = threading.Lock()


def _ensure_autoscale_metrics() -> dict:
    global _autoscale_metrics
    if _autoscale_metrics is None:
        with _autoscale_init_lock:
            if _autoscale_metrics is None:
                _autoscale_metrics = {
                    "up": Counter(
                        "autoscale_scale_up_total",
                        "SLO-autoscaler scale-up decisions applied",
                        tag_keys=("deployment",),
                    ),
                    "down": Counter(
                        "autoscale_scale_down_total",
                        "SLO-autoscaler scale-down decisions applied",
                        tag_keys=("deployment",),
                    ),
                    "decision": Histogram(
                        "autoscale_decision_seconds",
                        "Pressure-onset (or idle-onset) to applied "
                        "decision wall time",
                        boundaries=_AUTOSCALE_DECISION_BOUNDARIES_S,
                        tag_keys=("deployment", "direction"),
                    ),
                }
    return _autoscale_metrics


def record_autoscale_decision(
    deployment: str, direction: str, breach_age_s: float
):
    m = _ensure_autoscale_metrics()
    m["up" if direction == "up" else "down"].inc(
        1.0, {"deployment": deployment}
    )
    m["decision"].observe(
        max(breach_age_s, 0.0),
        {"deployment": deployment, "direction": direction},
    )


def autoscale_counters() -> Dict[str, float]:
    """Process-local totals across deployments (tests + bench)."""
    m = _ensure_autoscale_metrics()
    out: Dict[str, float] = {}
    for label, metric in (("scale_ups", m["up"]), ("scale_downs", m["down"])):
        with metric._lock:
            out[label] = float(sum(metric._values.values()))
    return out


def autoscale_summary(payloads: List[dict]) -> Dict[str, object]:
    """Cluster rollup of autoscaler activity from pushed snapshots
    (state.metrics_summary / dashboard /api/autoscale / CLI)."""
    out: Dict[str, object] = {
        "scale_ups": 0.0,
        "scale_downs": 0.0,
        "by_deployment": {},
        "decision_p50_s": None,
        "decision_p99_s": None,
    }
    by_dep: Dict[str, dict] = out["by_deployment"]  # type: ignore[assignment]
    for payload in payloads:
        for snap in payload.get("metrics", []):
            field = {
                "autoscale_scale_up_total": "scale_ups",
                "autoscale_scale_down_total": "scale_downs",
            }.get(snap.get("name", ""))
            if field is None:
                continue
            for tag_json, value in snap["values"].items():
                out[field] += value
                tags = dict(
                    zip(snap.get("tag_keys", ()), json.loads(tag_json))
                )
                row = by_dep.setdefault(
                    tags.get("deployment", "?"),
                    {"scale_ups": 0.0, "scale_downs": 0.0},
                )
                row[field] += value
    m = merged_histogram(payloads, "autoscale_decision_seconds")
    if m and m["count"]:
        out["decision_p50_s"] = quantile_from_buckets(
            m["boundaries"], m["counts"], 0.50
        )
        out["decision_p99_s"] = quantile_from_buckets(
            m["boundaries"], m["counts"], 0.99
        )
    return out


def weights_summary(payloads: List[dict]) -> Dict[str, object]:
    """Cluster rollup of weight-plane traffic with the logical/wire byte
    split (state.metrics_summary()["weights"]): per direction
    (publish | fetch) the raw leaf bytes, the encoded bytes that actually
    crossed the store/broadcast tree, and their ratio — the compression
    win the int8 chunk codec is buying — plus publish counts by codec
    and a per-model breakdown."""
    out: Dict[str, object] = {
        "publish": {"logical_bytes": 0.0, "wire_bytes": 0.0},
        "fetch": {"logical_bytes": 0.0, "wire_bytes": 0.0},
        "publishes_by_codec": {},
        "by_model": {},
    }
    by_codec: Dict[str, float] = out["publishes_by_codec"]  # type: ignore[assignment]
    by_model: Dict[str, dict] = out["by_model"]  # type: ignore[assignment]
    for payload in payloads:
        for snap in payload.get("metrics", []):
            name = snap.get("name", "")
            field = {
                "weights_broadcast_bytes_total": "logical_bytes",
                "weights_wire_bytes_total": "wire_bytes",
            }.get(name)
            if field is not None:
                for tag_json, value in snap["values"].items():
                    tags = dict(
                        zip(snap.get("tag_keys", ()), json.loads(tag_json))
                    )
                    direction = tags.get("direction", "?")
                    if direction in ("publish", "fetch"):
                        out[direction][field] += value  # type: ignore[index]
                    row = by_model.setdefault(
                        tags.get("model", "?"),
                        {"logical_bytes": 0.0, "wire_bytes": 0.0},
                    )
                    row[field] += value
            elif name == "weights_codec_publish_total":
                for tag_json, value in snap["values"].items():
                    tags = dict(
                        zip(snap.get("tag_keys", ()), json.loads(tag_json))
                    )
                    codec = tags.get("codec", "?")
                    by_codec[codec] = by_codec.get(codec, 0.0) + value
    for direction in ("publish", "fetch"):
        row = out[direction]  # type: ignore[index]
        row["compression_ratio"] = (
            row["logical_bytes"] / row["wire_bytes"]
            if row["wire_bytes"] else None
        )
    return out


def _node_hex() -> str:
    from .. import _worker_api

    worker = _worker_api.maybe_get_core_worker()
    node_id = getattr(worker, "node_id", None) if worker else None
    return node_id.hex() if node_id is not None else ""


def _ensure_pusher():
    """Background thread pushing this process's metrics to the GCS KV."""
    global _pusher_started
    if _pusher_started:
        return
    _pusher_started = True

    def _push_loop():
        from .. import _worker_api

        while True:
            time.sleep(3.0)
            worker = _worker_api.maybe_get_core_worker()
            if worker is None:
                continue
            try:
                # piggyback device telemetry on the push cadence; only when
                # this process already uses jax (no forced import)
                sample_device_memory()
            except Exception:
                pass
            with _registry_lock:
                snaps = [m._snapshot() for m in _registry.values()]
            if not snaps:
                continue
            # identity-tagged payload: prometheus_text renders gauges as
            # per-worker series, and the GCS reaps this key when it observes
            # this worker's (or node's) death
            payload = {
                "worker_id": worker.worker_id.hex(),
                "node_id": _node_hex(),
                "pid": os.getpid(),
                "ts": time.time(),
                "metrics": snaps,
            }
            try:
                _worker_api.run_on_worker_loop(
                    worker.client_pool.get(*worker.gcs_address).call(
                        "kv_put",
                        gcs_keys.METRICS.key(worker.worker_id.hex()),
                        json.dumps(payload).encode(),
                        True,
                    ),
                    timeout=5,
                )
            except Exception:
                pass

    threading.Thread(target=_push_loop, daemon=True, name="metrics-push").start()


def fetch_metric_payloads(gcs_call) -> List[dict]:
    """Fetch every worker's pushed snapshot through ``gcs_call(method,
    *args)`` and normalize to identity-tagged payload dicts. Shared by
    prometheus_text (driver side) and the dashboard (GCS-client side)."""
    payloads: List[dict] = []
    for key in gcs_call("kv_keys", gcs_keys.METRICS.scan):
        raw = gcs_call("kv_get", key)
        if raw is None:
            continue
        doc = json.loads(raw)
        if isinstance(doc, list):  # legacy untagged push
            doc = {"worker_id": key.split(":", 1)[-1], "node_id": "",
                   "metrics": doc}
        payloads.append(doc)
    return payloads


def render_prometheus(payloads: List[dict]) -> str:
    """Aggregate pushed snapshots into Prometheus exposition format
    (reference: metrics agent -> /metrics endpoint). Counters and
    histograms with the same (name, labels) across workers are summed into
    ONE series; GAUGES are per-worker facts (summing ``weights_staleness``
    over N workers is meaningless), so each worker's gauge renders as its
    own series distinguished by a ``worker`` label. Histograms render
    cumulative ``_bucket``/``_sum``/``_count`` series as the format
    requires."""
    # merged[name] = {"snap": first snapshot, "values": {label_tuple: sum},
    #                 "counts": {label_tuple: [bucket sums]},
    #                 "series": {(worker, tag_json): value}}  (gauges only)
    merged: Dict[str, dict] = {}
    for payload in payloads:
        worker_tag = str(payload.get("worker_id", ""))[:12]
        for snap in payload.get("metrics", []):
            name = snap["name"]
            m = merged.setdefault(
                name, {"snap": snap, "values": {}, "counts": {},
                       "series": {}}
            )
            if snap["type"] == "gauge":
                for tag_json, value in snap["values"].items():
                    m["series"][(worker_tag, tag_json)] = value
                continue
            for tag_json, value in snap["values"].items():
                m["values"][tag_json] = m["values"].get(tag_json, 0.0) + value
            for tag_json, counts in snap.get("counts", {}).items():
                cur = m["counts"].get(tag_json)
                if cur is None:
                    m["counts"][tag_json] = list(counts)
                else:
                    m["counts"][tag_json] = [
                        a + b for a, b in zip(cur, counts)
                    ]
    lines: List[str] = []
    for name, m in merged.items():
        snap = m["snap"]
        kind = {"counter": "counter", "gauge": "gauge"}.get(
            snap["type"], "histogram"
        )
        lines.append(f"# HELP {name} {snap['description']}")
        lines.append(f"# TYPE {name} {kind}")
        if kind == "gauge":
            for (worker_tag, tag_json), value in m["series"].items():
                label_pairs = [
                    (k, v)
                    for k, v in zip(snap["tag_keys"], json.loads(tag_json))
                    if v
                ]
                if worker_tag:
                    label_pairs.append(("worker", worker_tag))
                lines.append(_sample(name, label_pairs, value))
            continue
        for tag_json in m["values"]:
            label_pairs = [
                (k, v)
                for k, v in zip(snap["tag_keys"], json.loads(tag_json))
                if v
            ]
            if kind == "histogram":
                counts = m["counts"].get(tag_json, [])
                bounds = snap.get("boundaries", [])
                cum = 0
                for bound, c in zip(bounds, counts):
                    cum += c
                    lines.append(
                        _sample(
                            f"{name}_bucket",
                            label_pairs + [("le", str(bound))],
                            cum,
                        )
                    )
                cum += counts[len(bounds)] if len(counts) > len(bounds) else 0
                lines.append(
                    _sample(
                        f"{name}_bucket", label_pairs + [("le", "+Inf")], cum
                    )
                )
                lines.append(_sample(f"{name}_count", label_pairs, cum))
                lines.append(
                    _sample(f"{name}_sum", label_pairs, m["values"][tag_json])
                )
            else:
                lines.append(
                    _sample(name, label_pairs, m["values"][tag_json])
                )
    return "\n".join(lines) + "\n"


def prometheus_text() -> str:
    """Cluster-wide /metrics payload, aggregated from every worker's GCS
    push (see render_prometheus for the aggregation semantics)."""
    from .. import _worker_api

    worker = _worker_api.get_core_worker()

    def _call(method, *args):
        return _worker_api.run_on_worker_loop(
            worker.client_pool.get(*worker.gcs_address).call(method, *args)
        )

    return render_prometheus(fetch_metric_payloads(_call))


def device_rows(payloads: List[dict]) -> List[dict]:
    """Per-device HBM rows aggregated from pushed snapshots (dashboard
    /api/devices): one row per (node, device) with used/limit bytes."""
    rows: Dict[tuple, dict] = {}
    for payload in payloads:
        for snap in payload.get("metrics", []):
            field = {
                "tpu_hbm_used_bytes": "used",
                "tpu_hbm_limit_bytes": "limit",
            }.get(snap["name"])
            if field is None:
                continue
            for tag_json, value in snap["values"].items():
                tags = dict(zip(snap["tag_keys"], json.loads(tag_json)))
                key = (tags.get("node", ""), tags.get("device", ""))
                row = rows.setdefault(
                    key,
                    {
                        "node": key[0],
                        "device": key[1],
                        "kind": tags.get("kind", ""),
                        "used": 0.0,
                        "limit": 0.0,
                    },
                )
                row[field] = value
    return [rows[k] for k in sorted(rows)]


def _escape_label_value(value) -> str:
    """Prometheus exposition escaping for label values: backslash, double
    quote, and newline (a model name with a quote must not corrupt the
    scrape)."""
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _sample(name: str, label_pairs, value) -> str:
    labels = ",".join(
        f'{k}="{_escape_label_value(v)}"' for k, v in label_pairs
    )
    label_str = f"{{{labels}}}" if labels else ""
    return f"{name}{label_str} {value}"
