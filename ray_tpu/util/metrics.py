"""User-facing metrics: Counter / Gauge / Histogram.

Role-equivalent of the reference's ray.util.metrics (python/ray/util/
metrics.py backed by the per-node metrics agent + Prometheus export,
_private/metrics_agent.py). Metrics record locally and are pushed to the
GCS KV under ``metrics:<worker>`` every few seconds; ``prometheus_text()``
aggregates every worker's push into Prometheus exposition format.
"""

from __future__ import annotations

import bisect
import json
import threading
import time
from typing import Dict, List, Optional, Tuple

_registry_lock = threading.Lock()
_registry: Dict[str, "Metric"] = {}
_pusher_started = False


class Metric:
    def __init__(
        self,
        name: str,
        description: str = "",
        tag_keys: Tuple[str, ...] = (),
    ):
        self._name = name
        self._description = description
        self._tag_keys = tuple(tag_keys)
        self._default_tags: Dict[str, str] = {}
        self._values: Dict[Tuple[str, ...], float] = {}
        self._lock = threading.Lock()
        with _registry_lock:
            _registry[name] = self
        _ensure_pusher()

    def set_default_tags(self, tags: Dict[str, str]):
        self._default_tags = dict(tags)
        return self

    def _tag_tuple(self, tags: Optional[Dict[str, str]]) -> Tuple[str, ...]:
        merged = {**self._default_tags, **(tags or {})}
        return tuple(merged.get(k, "") for k in self._tag_keys)

    def _snapshot(self) -> dict:
        with self._lock:
            return {
                "name": self._name,
                "type": type(self).__name__.lower(),
                "description": self._description,
                "tag_keys": self._tag_keys,
                "values": {json.dumps(k): v for k, v in self._values.items()},
            }


class Counter(Metric):
    def inc(self, value: float = 1.0, tags: Optional[Dict[str, str]] = None):
        key = self._tag_tuple(tags)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + value


class Gauge(Metric):
    def set(self, value: float, tags: Optional[Dict[str, str]] = None):
        with self._lock:
            self._values[self._tag_tuple(tags)] = float(value)


class Histogram(Metric):
    def __init__(
        self,
        name: str,
        description: str = "",
        boundaries: Optional[List[float]] = None,
        tag_keys: Tuple[str, ...] = (),
    ):
        super().__init__(name, description, tag_keys)
        self._boundaries = sorted(boundaries or [0.1, 1, 10, 100, 1000])
        self._counts: Dict[Tuple[str, ...], List[int]] = {}
        self._sums: Dict[Tuple[str, ...], float] = {}

    def observe(self, value: float, tags: Optional[Dict[str, str]] = None):
        key = self._tag_tuple(tags)
        with self._lock:
            counts = self._counts.setdefault(
                key, [0] * (len(self._boundaries) + 1)
            )
            counts[bisect.bisect_left(self._boundaries, value)] += 1
            self._sums[key] = self._sums.get(key, 0.0) + value
            self._values[key] = self._sums[key]

    def _snapshot(self) -> dict:
        snap = super()._snapshot()
        with self._lock:
            snap["boundaries"] = self._boundaries
            snap["counts"] = {
                json.dumps(k): v for k, v in self._counts.items()
            }
        return snap


# ---------------------------------------------------------------------------
# Control-plane RPC metrics (the lease-reuse / v2-framing proof layer):
# per-method client-call latency histograms plus an RPCs-per-task counter
# pair, recorded from _internal/rpc.py on every client call and surfaced by
# the microbenchmark CLI and the lease-reuse regression tests.
# ---------------------------------------------------------------------------

_RPC_LATENCY_BOUNDARIES_MS = [
    0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10, 25, 50, 100, 250, 1000,
]

_rpc_latency: Optional["Histogram"] = None
_rpc_calls: Optional["Counter"] = None
_tasks_submitted: Optional["Counter"] = None
_rpc_init_lock = threading.Lock()


def _ensure_rpc_metrics():
    global _rpc_latency, _rpc_calls, _tasks_submitted
    if _rpc_latency is None:
        with _rpc_init_lock:
            if _rpc_latency is None:
                _rpc_calls = Counter(
                    "rpc_client_calls_total",
                    "Client RPCs issued by this process, by method",
                    tag_keys=("method",),
                )
                _tasks_submitted = Counter(
                    "tasks_submitted_total",
                    "Normal tasks submitted by this process",
                )
                # assigned last: its non-None-ness gates the fast path, so
                # the other two must already exist when readers see it
                _rpc_latency = Histogram(
                    "rpc_client_latency_ms",
                    "Client RPC round-trip latency by method (ms)",
                    boundaries=_RPC_LATENCY_BOUNDARIES_MS,
                    tag_keys=("method",),
                )
    return _rpc_latency, _rpc_calls, _tasks_submitted


def record_rpc(method: str, latency_s: float):
    """Called from RpcClient.call / call_oneway (hot path — keep cheap)."""
    latency, calls, _ = _ensure_rpc_metrics()
    tags = {"method": method}
    latency.observe(latency_s * 1000.0, tags)
    calls.inc(1.0, tags)


def note_task_submitted(n: float = 1.0):
    """Called from CoreWorker._launch_task; pairs with rpc_call counts to
    derive RPCs-per-task."""
    _, _, tasks = _ensure_rpc_metrics()
    tasks.inc(n)


def rpc_calls_by_method() -> Dict[str, float]:
    """Process-local snapshot: method -> client calls issued."""
    _, calls, _ = _ensure_rpc_metrics()
    with calls._lock:
        return {k[0]: v for k, v in calls._values.items()}


def tasks_submitted_total() -> float:
    _, _, tasks = _ensure_rpc_metrics()
    with tasks._lock:
        return sum(tasks._values.values())


def rpc_latency_summary() -> Dict[str, dict]:
    """Process-local per-method latency summary: count, mean ms, and the
    cumulative histogram buckets ({le: count}) — the machine-readable shape
    the microbenchmark CLI emits for BENCH_LOG.md."""
    latency, _, _ = _ensure_rpc_metrics()
    out: Dict[str, dict] = {}
    with latency._lock:
        for key, counts in latency._counts.items():
            method = key[0]
            total = sum(counts)
            if not total:
                continue
            cum = 0
            buckets = {}
            for bound, c in zip(latency._boundaries, counts):
                cum += c
                buckets[str(bound)] = cum
            buckets["+Inf"] = total
            out[method] = {
                "count": total,
                "mean_ms": latency._sums.get(key, 0.0) / total,
                "buckets": buckets,
            }
    return out


# ---------------------------------------------------------------------------
# Object-serialization accounting: how many times (and how many bytes) this
# process serialized values into the object plane, by context — "put"
# (api.put / CoreWorker.put) vs "task_arg" (inline task-argument packing).
# The rllib put-once regression guard asserts train() serializes the params
# pytree at most once per iteration instead of once per env-runner.
# ---------------------------------------------------------------------------

_ser_count: Optional["Counter"] = None
_ser_bytes: Optional["Counter"] = None
_ser_init_lock = threading.Lock()


def _ensure_serialization_metrics():
    global _ser_count, _ser_bytes
    if _ser_bytes is None:
        with _ser_init_lock:
            if _ser_bytes is None:
                _ser_count = Counter(
                    "object_serializations_total",
                    "Object-plane serializations by context (put | task_arg)",
                    tag_keys=("context",),
                )
                # assigned last: gates the fast path (see _ensure_rpc_metrics)
                _ser_bytes = Counter(
                    "object_serialization_bytes_total",
                    "Bytes serialized into the object plane by context",
                    tag_keys=("context",),
                )
    return _ser_count, _ser_bytes


def record_object_serialization(context: str, nbytes: int):
    """Called from CoreWorker.put and prepare_args (hot path — keep cheap)."""
    count, total = _ensure_serialization_metrics()
    tags = {"context": context}
    count.inc(1.0, tags)
    total.inc(float(nbytes), tags)


def object_serializations() -> Dict[str, Dict[str, float]]:
    """Process-local snapshot: context -> {count, bytes}."""
    count, total = _ensure_serialization_metrics()
    out: Dict[str, Dict[str, float]] = {}
    with count._lock:
        for key, v in count._values.items():
            out.setdefault(key[0], {"count": 0.0, "bytes": 0.0})["count"] = v
    with total._lock:
        for key, v in total._values.items():
            out.setdefault(key[0], {"count": 0.0, "bytes": 0.0})["bytes"] = v
    return out


# ---------------------------------------------------------------------------
# Weight-plane metrics (ray_tpu.weights): publish latency, broadcast volume,
# tree depth, and subscriber staleness, tagged by model name. Surfaced via
# the GCS pusher / prometheus_text like every other metric, and snapshotted
# process-locally by the weights microbenchmark + tests.
# ---------------------------------------------------------------------------

_WEIGHTS_LATENCY_BOUNDARIES_MS = [
    1, 5, 10, 25, 50, 100, 250, 500, 1000, 2500, 5000, 10000,
]

_weights_metrics: Optional[dict] = None
_weights_init_lock = threading.Lock()


def _ensure_weights_metrics() -> dict:
    global _weights_metrics
    if _weights_metrics is None:
        with _weights_init_lock:
            if _weights_metrics is None:
                _weights_metrics = {
                    "publish_latency": Histogram(
                        "weights_publish_latency_ms",
                        "WeightPublisher.publish wall time by model (ms)",
                        boundaries=_WEIGHTS_LATENCY_BOUNDARIES_MS,
                        tag_keys=("model",),
                    ),
                    "fetch_latency": Histogram(
                        "weights_fetch_latency_ms",
                        "WeightSubscriber full-version fetch wall time (ms)",
                        boundaries=_WEIGHTS_LATENCY_BOUNDARIES_MS,
                        tag_keys=("model",),
                    ),
                    "broadcast_bytes": Counter(
                        "weights_broadcast_bytes_total",
                        "Weight bytes moved by direction (publish | fetch)",
                        tag_keys=("model", "direction"),
                    ),
                    "tree_depth": Gauge(
                        "weights_broadcast_tree_depth",
                        "Depth of the binomial broadcast tree by model",
                        tag_keys=("model",),
                    ),
                    "staleness": Gauge(
                        "weights_staleness_versions",
                        "Versions behind head for this subscriber, by model",
                        tag_keys=("model",),
                    ),
                }
    return _weights_metrics


def record_weights_publish(model: str, latency_s: float, nbytes: int):
    m = _ensure_weights_metrics()
    m["publish_latency"].observe(latency_s * 1000.0, {"model": model})
    m["broadcast_bytes"].inc(
        float(nbytes), {"model": model, "direction": "publish"}
    )


def record_weights_fetch(model: str, latency_s: float, nbytes: int):
    m = _ensure_weights_metrics()
    m["fetch_latency"].observe(latency_s * 1000.0, {"model": model})
    m["broadcast_bytes"].inc(
        float(nbytes), {"model": model, "direction": "fetch"}
    )


def set_weights_tree_depth(model: str, depth: int):
    _ensure_weights_metrics()["tree_depth"].set(float(depth), {"model": model})


def set_weights_staleness(model: str, versions_behind: int):
    _ensure_weights_metrics()["staleness"].set(
        float(versions_behind), {"model": model}
    )


def weights_staleness(model: str) -> Optional[float]:
    """Process-local staleness gauge readback (tests + state CLI)."""
    gauge = _ensure_weights_metrics()["staleness"]
    with gauge._lock:
        return gauge._values.get(gauge._tag_tuple({"model": model}))


def _ensure_pusher():
    """Background thread pushing this process's metrics to the GCS KV."""
    global _pusher_started
    if _pusher_started:
        return
    _pusher_started = True

    def _push_loop():
        from .. import _worker_api

        while True:
            time.sleep(3.0)
            worker = _worker_api.maybe_get_core_worker()
            if worker is None:
                continue
            with _registry_lock:
                snaps = [m._snapshot() for m in _registry.values()]
            if not snaps:
                continue
            try:
                _worker_api.run_on_worker_loop(
                    worker.client_pool.get(*worker.gcs_address).call(
                        "kv_put",
                        f"metrics:{worker.worker_id.hex()}",
                        json.dumps(snaps).encode(),
                        True,
                    ),
                    timeout=5,
                )
            except Exception:
                pass

    threading.Thread(target=_push_loop, daemon=True, name="metrics-push").start()


def prometheus_text() -> str:
    """Aggregate all workers' pushed metrics into Prometheus exposition
    format (reference: metrics agent -> /metrics endpoint). Samples with the
    same (name, labels) across workers are summed into ONE series —
    duplicate series make a scrape invalid; histograms render cumulative
    ``_bucket``/``_sum``/``_count`` series as the format requires."""
    from .. import _worker_api

    worker = _worker_api.get_core_worker()
    keys = _worker_api.run_on_worker_loop(
        worker.client_pool.get(*worker.gcs_address).call("kv_keys", "metrics:")
    )
    # merged[name] = {"snap": first snapshot, "values": {label_tuple: sum},
    #                 "counts": {label_tuple: [bucket sums]}, "sums": {...}}
    merged: Dict[str, dict] = {}
    for key in keys:
        raw = _worker_api.run_on_worker_loop(
            worker.client_pool.get(*worker.gcs_address).call("kv_get", key)
        )
        if raw is None:
            continue
        for snap in json.loads(raw):
            name = snap["name"]
            m = merged.setdefault(
                name, {"snap": snap, "values": {}, "counts": {}}
            )
            for tag_json, value in snap["values"].items():
                m["values"][tag_json] = m["values"].get(tag_json, 0.0) + value
            for tag_json, counts in snap.get("counts", {}).items():
                cur = m["counts"].get(tag_json)
                if cur is None:
                    m["counts"][tag_json] = list(counts)
                else:
                    m["counts"][tag_json] = [
                        a + b for a, b in zip(cur, counts)
                    ]
    lines: List[str] = []
    for name, m in merged.items():
        snap = m["snap"]
        kind = {"counter": "counter", "gauge": "gauge"}.get(
            snap["type"], "histogram"
        )
        lines.append(f"# HELP {name} {snap['description']}")
        lines.append(f"# TYPE {name} {kind}")
        for tag_json in m["values"]:
            label_pairs = [
                (k, v)
                for k, v in zip(snap["tag_keys"], json.loads(tag_json))
                if v
            ]
            if kind == "histogram":
                counts = m["counts"].get(tag_json, [])
                bounds = snap.get("boundaries", [])
                cum = 0
                for bound, c in zip(bounds, counts):
                    cum += c
                    lines.append(
                        _sample(
                            f"{name}_bucket",
                            label_pairs + [("le", str(bound))],
                            cum,
                        )
                    )
                cum += counts[len(bounds)] if len(counts) > len(bounds) else 0
                lines.append(
                    _sample(
                        f"{name}_bucket", label_pairs + [("le", "+Inf")], cum
                    )
                )
                lines.append(_sample(f"{name}_count", label_pairs, cum))
                lines.append(
                    _sample(f"{name}_sum", label_pairs, m["values"][tag_json])
                )
            else:
                lines.append(
                    _sample(name, label_pairs, m["values"][tag_json])
                )
    return "\n".join(lines) + "\n"


def _sample(name: str, label_pairs, value) -> str:
    labels = ",".join(f'{k}="{v}"' for k, v in label_pairs)
    label_str = f"{{{labels}}}" if labels else ""
    return f"{name}{label_str} {value}"
