"""Tracing: spans around task submission/execution + timeline export.

Role-equivalent of the reference's tracing helper
(python/ray/util/tracing/tracing_helper.py:165-221 — OpenTelemetry spans
patched around ``.remote()`` and task execution) and of ``ray timeline``
(chrome-trace export of per-task profile events). Spans here are recorded
by a dependency-free in-process recorder; the cluster-wide timeline is
reconstructed from the GCS task-event store (per-state timestamps), and
device-side profiling delegates to ``jax.profiler`` (the TPU-native
equivalent of NVTX ranges).
"""

from __future__ import annotations

import json
import os
import threading
import time
from contextlib import contextmanager
from typing import Any, Dict, List, Optional

_lock = threading.Lock()
_spans: List[dict] = []
_enabled = os.environ.get("RAY_TPU_TRACE", "") not in ("", "0")


def enable_tracing():
    """Turn on span recording in this process (reference:
    ray.init(_tracing_startup_hook=...))."""
    global _enabled
    _enabled = True


def is_tracing_enabled() -> bool:
    return _enabled


@contextmanager
def trace_span(name: str, category: str = "app", **attrs):
    """Record one span (reference: tracing_helper span context managers)."""
    if not _enabled:
        yield
        return
    start = time.perf_counter()
    wall = time.time()
    try:
        yield
    finally:
        dur = time.perf_counter() - start
        with _lock:
            _spans.append(
                {
                    "name": name,
                    "cat": category,
                    "ph": "X",
                    "ts": wall * 1e6,
                    "dur": dur * 1e6,
                    "pid": os.getpid(),
                    "tid": threading.get_ident() % 100000,
                    "args": attrs,
                }
            )


def get_spans() -> List[dict]:
    with _lock:
        return list(_spans)


def clear_spans():
    with _lock:
        _spans.clear()


def export_spans(filename: str):
    """Write this process's spans as a chrome trace."""
    with open(filename, "w") as f:
        json.dump({"traceEvents": get_spans()}, f)


def build_chrome_trace(events: List[dict]) -> List[dict]:
    """GCS task-event records -> chrome-trace complete ("X") events.
    Shared by ``timeline()`` and the dashboard's /api/timeline."""
    trace: List[dict] = []
    for ev in events:
        start = ev.get("ts_running")
        if start is None:
            continue
        end = ev.get("ts_finished") or ev.get("ts_failed") or time.time()
        trace.append(
            {
                "name": ev.get("name", ev.get("task_id", "?")),
                "cat": ev.get("type", "TASK"),
                "ph": "X",
                "ts": start * 1e6,
                "dur": max(end - start, 0.0) * 1e6,
                "pid": ev.get("node_id", "node"),
                "tid": ev.get("worker_pid", 0),
                "args": {
                    "task_id": ev.get("task_id"),
                    "state": ev.get("state"),
                    "attempt": ev.get("attempt", 0),
                },
            }
        )
    return trace


def timeline(filename: Optional[str] = None) -> List[dict]:
    """Cluster-wide task timeline as chrome-trace events, reconstructed
    from the GCS task-event store (reference: `ray timeline` building a
    chrome trace from profile events). Returns the events; also writes
    ``filename`` if given."""
    from .. import _worker_api

    worker = _worker_api.get_core_worker()
    events = _worker_api.run_on_worker_loop(
        worker.client_pool.get(*worker.gcs_address).call(
            "list_task_events", None, 100000
        )
    )
    trace = build_chrome_trace(events)
    # driver-side spans join the same trace
    trace.extend(get_spans())
    if filename:
        with open(filename, "w") as f:
            json.dump({"traceEvents": trace}, f)
    return trace


# -- device profiling (TPU): jax.profiler passthrough -----------------------


def start_device_trace(log_dir: str = "/tmp/ray_tpu_trace"):
    """Start a jax.profiler trace capturing XLA/TPU activity (the
    TPU-native role of the reference's NVTX/torch profiler flags)."""
    import jax

    jax.profiler.start_trace(log_dir)
    return log_dir


def stop_device_trace():
    import jax

    jax.profiler.stop_trace()


@contextmanager
def device_trace(log_dir: str = "/tmp/ray_tpu_trace"):
    start_device_trace(log_dir)
    try:
        yield log_dir
    finally:
        stop_device_trace()


@contextmanager
def device_profile(logdir: str, *, host_tracer_level: int = 2):
    """Capture a device (TPU/XLA) profile around a block of jax work
    (SURVEY §5: 'jax.profiler traces + XPlane export' as the TPU analogue of
    the reference's NVTX/torch profiling flags). Writes an XPlane trace a
    TensorBoard profiler plugin can open:

        with ray_tpu.util.tracing.device_profile("/tmp/prof"):
            train_step(...)
    """
    import jax

    jax.profiler.start_trace(
        logdir, create_perfetto_link=False, create_perfetto_trace=False
    )
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def annotate_device_trace(name: str):
    """Named region inside a device profile (jax.profiler.TraceAnnotation):
    shows up in the XPlane timeline around the annotated host-side dispatch."""
    import jax

    return jax.profiler.TraceAnnotation(name)
