"""Tracing: spans around task submission/execution + timeline export.

Role-equivalent of the reference's tracing helper
(python/ray/util/tracing/tracing_helper.py:165-221 — OpenTelemetry spans
patched around ``.remote()`` and task execution) and of ``ray timeline``
(chrome-trace export of per-task profile events). Spans here are recorded
by a dependency-free in-process recorder; the cluster-wide timeline is
reconstructed from the GCS task-event store (per-state timestamps), and
device-side profiling delegates to ``jax.profiler`` (the TPU-native
equivalent of NVTX ranges).
"""

from __future__ import annotations

import contextvars
import json
import os
import threading
import time
import uuid
from contextlib import contextmanager
from typing import Any, Dict, List, Optional

_lock = threading.Lock()
_spans: List[dict] = []
_spans_cap = 50000  # local backstop mirroring the GCS store's cap
_enabled = os.environ.get("RAY_TPU_TRACE", "") not in ("", "0")

# -- distributed trace context ----------------------------------------------
# Every span carries (trace_id, span_id, parent_id). The ACTIVE context is a
# per-thread stack of open spans; when a thread has no open span the task
# context (restored from TaskSpec.trace_context around task execution) is the
# parent. The task context is a ContextVar, NOT a module global: the worker
# RPC server dispatches each push_task/actor_task via asyncio.ensure_future,
# so many task-execution coroutines interleave on one event loop — a
# ContextVar is coroutine-local under asyncio, so concurrent tasks can't
# clobber each other's context and exits can't restore a stale one. User code
# running in executor threads inherits it via contextvars.copy_context()
# handoff at the run_in_executor call sites (core_worker._run_traced).
_tls = threading.local()
_task_context: contextvars.ContextVar[Optional[Dict[str, str]]] = (
    contextvars.ContextVar("ray_tpu_task_context", default=None)
)
# one trace per process for submissions with no enclosing span, so all
# root-level tasks of one driver loop correlate in the timeline
_root_trace_id: Optional[str] = None

# spans not yet streamed to the GCS span store
_flush_cursor = 0
_flush_lock = threading.Lock()  # serializes read-push-advance in flush_spans
_span_pusher_started = False


def _new_id() -> str:
    return uuid.uuid4().hex[:16]


def enable_tracing():
    """Turn on span recording in this process (reference:
    ray.init(_tracing_startup_hook=...))."""
    global _enabled
    _enabled = True


def is_tracing_enabled() -> bool:
    """True when this process records spans — either statically (the
    RAY_TPU_TRACE env / enable_tracing()) or dynamically because it is
    executing a task whose submitter propagated a trace context (workers
    need no env of their own: the trace follows the task)."""
    return _enabled or _task_context.get() is not None


def current_context() -> Optional[Dict[str, str]]:
    """The active span context: innermost open span of this thread, else
    the restored task context."""
    stack = getattr(_tls, "stack", None)
    if stack:
        return stack[-1]
    return _task_context.get()


def inject_context() -> Optional[Dict[str, str]]:
    """Context to stamp into a TaskSpec at .remote() time; None when
    tracing is off (zero per-task cost on the untraced hot path)."""
    if not is_tracing_enabled():
        return None
    ctx = current_context()
    if ctx is None:
        # root of the process-wide trace: submissions with no enclosing span
        # still correlate (every task of one driver loop shares a trace)
        return {"trace_id": _root_trace(), "span_id": ""}
    return dict(ctx)


def _root_trace() -> str:
    """The per-process trace_id for spans/submissions with no enclosing
    context, created once so all root-level work of one driver correlates."""
    global _root_trace_id
    if _root_trace_id is None:
        with _lock:
            if _root_trace_id is None:
                _root_trace_id = _new_id()
    return _root_trace_id


@contextmanager
def trace_span(name: str, category: str = "app", **attrs):
    """Record one span (reference: tracing_helper span context managers),
    linked to the enclosing span/task context."""
    if not is_tracing_enabled():
        yield
        return
    parent = current_context()
    ctx = {
        "trace_id": parent["trace_id"] if parent else _root_trace(),
        "span_id": _new_id(),
    }
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    stack.append(ctx)
    start = time.perf_counter()
    wall = time.time()
    try:
        yield
    finally:
        dur = time.perf_counter() - start
        stack.pop()
        _record_span(
            name, category, wall, dur,
            ctx["trace_id"], ctx["span_id"],
            (parent or {}).get("span_id", ""), attrs,
        )


@contextmanager
def task_execution_span(name: str, ctx: Optional[Dict[str, str]], **attrs):
    """Restore a propagated trace context around task execution and record
    the execute span. Installed in the coroutine-local task context so
    nested ``.remote()`` submissions from user code parent to this
    execution (executor threads see it via copy_context handoff)."""
    if ctx is None and not _enabled:
        yield
        return
    span_ctx = {
        "trace_id": (ctx or {}).get("trace_id") or _root_trace(),
        "span_id": _new_id(),
    }
    token = _task_context.set(span_ctx)
    start = time.perf_counter()
    wall = time.time()
    try:
        yield
    finally:
        _task_context.reset(token)
        _record_span(
            name, "ray_tpu.execute", wall, time.perf_counter() - start,
            span_ctx["trace_id"], span_ctx["span_id"],
            (ctx or {}).get("span_id", ""), attrs,
        )


def new_trace_context(trace_id: Optional[str] = None) -> Dict[str, str]:
    """Mint a root request context (proxy ingress: honor an inbound
    X-Trace-Id or start a fresh trace). The empty span_id marks it a trace
    root; the first span opened under it becomes the top of the tree."""
    return {"trace_id": trace_id or _new_id(), "span_id": ""}


@contextmanager
def request_span(name: str, ctx: Optional[Dict[str, str]],
                 category: str = "serve", **attrs):
    """Adopt a propagated request context (or mint one when this process
    traces statically) around one serve-request stage, recording the stage
    span. Yields the active span context so callers can read the trace_id
    for histogram exemplars / response headers. Installed in the
    coroutine-local task context, so nested ``.remote()`` submissions and
    ``trace_span`` blocks opened downstream parent to this stage — the
    serve-side twin of ``task_execution_span``.

    ``ctx is None`` with static tracing off is the untraced hot path: no
    allocation, no span, yields None.
    """
    if ctx is None and not _enabled:
        yield None
        return
    span_ctx = {
        "trace_id": (ctx or {}).get("trace_id") or _root_trace(),
        "span_id": _new_id(),
    }
    token = _task_context.set(span_ctx)
    start = time.perf_counter()
    wall = time.time()
    try:
        yield span_ctx
    finally:
        _task_context.reset(token)
        _record_span(
            name, category, wall, time.perf_counter() - start,
            span_ctx["trace_id"], span_ctx["span_id"],
            (ctx or {}).get("span_id", ""), attrs,
        )


def child_context(ctx: Optional[Dict[str, str]]) -> Optional[Dict[str, str]]:
    """Mint a child span context under ``ctx`` (or the root trace) WITHOUT
    touching the coroutine-local task context — for async generators,
    where a set/reset token pair cannot legally bracket the yields (each
    step may run in a different caller context). Children parent to the
    returned ctx as it streams; :func:`emit_closed_span` records the span
    itself once the stream ends. None on the untraced path."""
    if ctx is None and not _enabled:
        return None
    return {
        "trace_id": (ctx or {}).get("trace_id") or _root_trace(),
        "span_id": _new_id(),
    }


def emit_closed_span(name: str, span_ctx: Dict[str, str],
                     parent_ctx: Optional[Dict[str, str]], start_wall: float,
                     dur_s: float, category: str = "serve", **attrs) -> None:
    """Record a span whose identity (:func:`child_context`) was minted
    before it closed, so spans emitted while it was open could already
    parent to it."""
    _record_span(
        name, category, start_wall, dur_s,
        span_ctx["trace_id"], span_ctx["span_id"],
        (parent_ctx or {}).get("span_id", ""), attrs,
    )


def emit_span(name: str, ctx: Optional[Dict[str, str]], start_wall: float,
              dur_s: float, category: str = "serve",
              **attrs) -> Optional[str]:
    """Record one already-completed span against an explicit parent
    context. For stages whose start and end happen on different threads
    (the continuous-batching engine admits and retires requests under its
    lock on whichever caller thread steps it), where no context manager
    can bracket the interval. Returns the new span_id (usable as a parent
    for follow-on stages), or None when the span was not recorded."""
    if ctx is None:
        if not _enabled:
            return None
        ctx = {"trace_id": _root_trace(), "span_id": ""}
    span_id = _new_id()
    _record_span(
        name, category, start_wall, dur_s,
        ctx.get("trace_id") or _root_trace(), span_id,
        ctx.get("span_id", ""), attrs,
    )
    return span_id


def _record_span(name, category, wall, dur_s, trace_id, span_id, parent_id,
                 attrs):
    span = {
        "name": name,
        "cat": category,
        "ph": "X",
        "ts": wall * 1e6,
        "dur": dur_s * 1e6,
        "pid": os.getpid(),
        "tid": threading.get_ident() % 100000,
        "trace_id": trace_id,
        "span_id": span_id,
        "parent_id": parent_id,
        "args": {**attrs, "trace_id": trace_id, "span_id": span_id,
                 "parent_id": parent_id},
    }
    global _flush_cursor
    with _lock:
        _spans.append(span)
        if len(_spans) > _spans_cap:
            # backstop when no pusher can drain (no core worker yet):
            # drop the oldest spans, keeping the flush cursor aligned
            drop = len(_spans) - _spans_cap
            del _spans[:drop]
            _flush_cursor = max(0, _flush_cursor - drop)
    _ensure_span_pusher()


def get_spans() -> List[dict]:
    with _lock:
        return list(_spans)


def clear_spans():
    global _flush_cursor
    with _lock:
        _spans.clear()
        _flush_cursor = 0


# -- span streaming to the GCS span store -----------------------------------


def flush_spans():
    """Push spans recorded since the last flush to the GCS span store and
    trim the flushed prefix from the local buffer (flushed spans live in
    the GCS store; keeping them here would leak for the worker's lifetime).
    Called from the background pusher; also public so a short-lived task
    can flush deterministically before returning."""
    global _flush_cursor
    from .. import _worker_api

    worker = _worker_api.maybe_get_core_worker()
    if worker is None:
        return
    # one flusher at a time: concurrent read-push-trim would double-push
    # the same batch (consuming the capped GCS store with duplicates)
    with _flush_lock:
        with _lock:
            batch = _spans[_flush_cursor:]
            cursor = len(_spans)
        if not batch:
            return
        try:
            _worker_api.run_on_worker_loop(
                worker.client_pool.get(*worker.gcs_address).call(
                    "report_spans", batch
                ),
                timeout=5,
            )
            with _lock:
                # clear_spans may have raced the push; never trim past the
                # current buffer
                del _spans[: min(cursor, len(_spans))]
                _flush_cursor = 0
        except Exception:
            pass  # spans are best-effort observability


def _ensure_span_pusher():
    """Background thread streaming finished spans to the GCS (reference:
    worker-side TaskEventBuffer flushes; here for spans, so a WORKER's
    spans outlive its process and join the cluster timeline)."""
    global _span_pusher_started
    with _lock:
        if _span_pusher_started:
            return
        _span_pusher_started = True

    def _loop():
        while True:
            time.sleep(1.0)
            flush_spans()

    threading.Thread(target=_loop, daemon=True, name="span-push").start()


def export_spans(filename: str):
    """Write this process's spans as a chrome trace."""
    with open(filename, "w") as f:
        json.dump({"traceEvents": get_spans()}, f)


def build_chrome_trace(events: List[dict]) -> List[dict]:
    """GCS task-event records -> chrome-trace complete ("X") events.
    Shared by ``timeline()`` and the dashboard's /api/timeline."""
    trace: List[dict] = []
    for ev in events:
        start = ev.get("ts_running")
        if start is None:
            continue
        end = ev.get("ts_finished") or ev.get("ts_failed") or time.time()
        trace.append(
            {
                "name": ev.get("name", ev.get("task_id", "?")),
                "cat": ev.get("type", "TASK"),
                "ph": "X",
                "ts": start * 1e6,
                "dur": max(end - start, 0.0) * 1e6,
                "pid": ev.get("node_id", "node"),
                "tid": ev.get("worker_pid", 0),
                "args": {
                    "task_id": ev.get("task_id"),
                    "state": ev.get("state"),
                    "attempt": ev.get("attempt", 0),
                },
            }
        )
    return trace


def merge_span_events(trace: List[dict], *span_lists: List[dict]) -> List[dict]:
    """Append span lists onto a chrome trace, deduplicating by span_id (a
    driver's spans exist both locally and in the GCS store). Shared by
    ``timeline()`` and the dashboard's /api/timeline."""
    seen = set()
    for spans in span_lists:
        for span in spans:
            sid = span.get("span_id")
            if sid and sid in seen:
                continue
            if sid:
                seen.add(sid)
            trace.append(span)
    return trace


def timeline(filename: Optional[str] = None) -> List[dict]:
    """Cluster-wide timeline as chrome-trace events: GCS task-state events
    plus EVERY node's spans from the GCS span store, plus this process's
    not-yet-flushed spans (reference: `ray timeline` building a chrome
    trace from profile events). Returns the events; also writes
    ``filename`` if given."""
    from .. import _worker_api

    worker = _worker_api.get_core_worker()
    gcs = worker.client_pool.get(*worker.gcs_address)
    events = _worker_api.run_on_worker_loop(
        gcs.call("list_task_events", None, 100000)
    )
    trace = build_chrome_trace(events)
    try:
        cluster_spans = _worker_api.run_on_worker_loop(
            gcs.call("list_spans", 100000)
        )
    except Exception:
        cluster_spans = []
    merge_span_events(trace, cluster_spans, get_spans())
    if filename:
        with open(filename, "w") as f:
            json.dump({"traceEvents": trace}, f)
    return trace


# -- device profiling (TPU): jax.profiler passthrough -----------------------


def start_device_trace(log_dir: str = "/tmp/ray_tpu_trace"):
    """Start a jax.profiler trace capturing XLA/TPU activity (the
    TPU-native role of the reference's NVTX/torch profiler flags)."""
    import jax

    jax.profiler.start_trace(log_dir)
    return log_dir


def stop_device_trace():
    import jax

    jax.profiler.stop_trace()


@contextmanager
def device_trace(log_dir: str = "/tmp/ray_tpu_trace"):
    start_device_trace(log_dir)
    try:
        yield log_dir
    finally:
        stop_device_trace()


@contextmanager
def device_profile(logdir: str, *, host_tracer_level: int = 2):
    """Capture a device (TPU/XLA) profile around a block of jax work
    (SURVEY §5: 'jax.profiler traces + XPlane export' as the TPU analogue of
    the reference's NVTX/torch profiling flags). Writes an XPlane trace a
    TensorBoard profiler plugin can open:

        with ray_tpu.util.tracing.device_profile("/tmp/prof"):
            train_step(...)
    """
    import jax

    jax.profiler.start_trace(
        logdir, create_perfetto_link=False, create_perfetto_trace=False
    )
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def annotate_device_trace(name: str):
    """Named region inside a device profile (jax.profiler.TraceAnnotation):
    shows up in the XPlane timeline around the annotated host-side dispatch."""
    import jax

    return jax.profiler.TraceAnnotation(name)
