"""Serializability inspection.

Role-equivalent of the reference's ``ray.util.inspect_serializability``
(util/check_serialize.py): recursively locates the members of an object that
fail to pickle, so users can find the offending closure capture / attribute
instead of staring at a raw pickle error.
"""

from __future__ import annotations

import inspect
from typing import Any, Optional, Set, Tuple

from .._internal import serialization


class FailTuple:
    def __init__(self, obj: Any, name: str, parent: Any):
        self.obj = obj
        self.name = name
        self.parent = parent

    def __repr__(self):
        return f"FailTuple({self.name} [obj={self.obj!r}, parent={self.parent!r}])"

    def __eq__(self, other):
        return isinstance(other, FailTuple) and self.name == other.name

    def __hash__(self):
        return hash(self.name)


def _is_serializable(obj: Any) -> bool:
    try:
        serialization.dumps(obj)
        return True
    except Exception:
        return False


def inspect_serializability(
    obj: Any,
    name: Optional[str] = None,
    depth: int = 3,
    _failures: Optional[Set[FailTuple]] = None,
    _seen: Optional[Set[int]] = None,
) -> Tuple[bool, Set[FailTuple]]:
    """Returns (serializable, failures). Walks closures, globals-used, and
    attributes up to ``depth`` levels looking for the leaf objects that fail."""
    name = name or getattr(obj, "__name__", str(obj))
    failures: Set[FailTuple] = set() if _failures is None else _failures
    seen: Set[int] = set() if _seen is None else _seen

    if _is_serializable(obj):
        return True, failures
    if id(obj) in seen or depth <= 0:
        failures.add(FailTuple(obj, name, None))
        return False, failures
    seen.add(id(obj))

    found_deeper = False
    members: list = []
    if inspect.isfunction(obj):
        # closure cells
        closure = getattr(obj, "__closure__", None) or ()
        freevars = getattr(obj.__code__, "co_freevars", ())
        for var, cell in zip(freevars, closure):
            try:
                members.append((var, cell.cell_contents))
            except ValueError:
                pass
        # referenced globals
        gl = getattr(obj, "__globals__", {})
        for gname in getattr(obj.__code__, "co_names", ()):
            if gname in gl:
                members.append((gname, gl[gname]))
    else:
        for attr, val in list(getattr(obj, "__dict__", {}).items()):
            members.append((attr, val))

    for mname, member in members:
        if not _is_serializable(member):
            ok, _ = inspect_serializability(
                member, f"{name}.{mname}", depth - 1, failures, seen
            )
            if not ok:
                found_deeper = True

    if not found_deeper:
        failures.add(FailTuple(obj, name, None))
    return False, failures
