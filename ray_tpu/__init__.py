"""ray_tpu: a TPU-native distributed computing framework.

A brand-new system with the capabilities of Ray (tasks, actors, objects with
distributed ownership, placement groups, collective communication, Train/Data/
Serve/Tune libraries) designed TPU-first: chips, hosts, and ICI-connected
slices are first-class scheduling primitives, the tensor plane is XLA
collectives over ICI, and trainers compile to pjit/GSPMD.
"""

from .actor import method
from .api import (
    available_resources,
    cancel,
    cluster_resources,
    get,
    get_actor,
    init,
    is_initialized,
    kill,
    nodes,
    put,
    remote,
    shutdown,
    wait,
)
from .object_ref import ObjectRef
from . import exceptions

__version__ = "0.1.0"

__all__ = [
    "init",
    "shutdown",
    "is_initialized",
    "remote",
    "method",
    "get",
    "put",
    "wait",
    "kill",
    "cancel",
    "get_actor",
    "nodes",
    "cluster_resources",
    "available_resources",
    "ObjectRef",
    "exceptions",
    "__version__",
]
