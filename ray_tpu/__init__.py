"""ray_tpu: a TPU-native distributed computing framework.

A brand-new system with the capabilities of Ray (tasks, actors, objects with
distributed ownership, placement groups, collective communication, Train/Data/
Serve/Tune libraries) designed TPU-first: chips, hosts, and ICI-connected
slices are first-class scheduling primitives, the tensor plane is XLA
collectives over ICI, and trainers compile to pjit/GSPMD.
"""

from .actor import method
from .api import (
    available_resources,
    cancel,
    cluster_resources,
    get,
    get_actor,
    init,
    is_initialized,
    kill,
    nodes,
    put,
    remote,
    shutdown,
    wait,
)
from ._internal.ids import (
    ActorID,
    JobID,
    NodeID,
    ObjectID,
    PlacementGroupID,
    TaskID,
    UniqueID,
    WorkerID,
)
from .object_ref import ObjectRef, ObjectRefGenerator
from .runtime_context import get_runtime_context
from . import exceptions

__version__ = "0.5.0"


def get_tpu_ids():
    """Chip indices allocated to the current worker (reference role:
    ray.get_gpu_ids, _private/worker.py:1170, for the TPU resource)."""
    import os

    raw = os.environ.get("TPU_VISIBLE_CHIPS", "")
    return [int(x) for x in raw.split(",") if x.strip().isdigit()]


def get_gpu_ids():
    """GPU analogue kept for API familiarity; this framework schedules TPU
    chips (see get_tpu_ids)."""
    import os

    raw = os.environ.get("CUDA_VISIBLE_DEVICES", "")
    return [int(x) for x in raw.split(",") if x.strip().isdigit()]


def timeline(filename=None):
    """Chrome-trace export of the cluster task timeline (reference:
    ray.timeline)."""
    from .util.tracing import timeline as _timeline

    return _timeline(filename)


# Lazy subpackages (PEP 562): `import ray_tpu; ray_tpu.data...` works like
# the reference's eager subpackage attributes without importing the heavy
# jax-dependent libraries at top-level import time.
_LAZY_SUBMODULES = (
    "autoscaler", "client", "collective", "dag", "data", "experimental",
    "kvcache", "llm", "models", "ops", "parallel", "rllib", "serve",
    "testing", "train", "tune", "util", "cross_language",
)


def __getattr__(name):
    if name in _LAZY_SUBMODULES:
        import importlib

        module = importlib.import_module(f".{name}", __name__)
        globals()[name] = module
        return module
    raise AttributeError(f"module 'ray_tpu' has no attribute {name!r}")


def __dir__():
    return sorted(list(globals()) + list(_LAZY_SUBMODULES))

__all__ = [
    "init",
    "shutdown",
    "is_initialized",
    "remote",
    "method",
    "get",
    "put",
    "wait",
    "kill",
    "cancel",
    "get_actor",
    "nodes",
    "cluster_resources",
    "available_resources",
    "ObjectRef",
    "ObjectRefGenerator",
    "get_runtime_context",
    "get_tpu_ids",
    "get_gpu_ids",
    "timeline",
    "ActorID",
    "TaskID",
    "ObjectID",
    "NodeID",
    "JobID",
    "WorkerID",
    "PlacementGroupID",
    "UniqueID",
    "exceptions",
    "__version__",
]
