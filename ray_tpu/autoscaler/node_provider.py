"""Node providers: the cloud-facing side of the autoscaler.

Role-equivalent of the reference's NodeProvider interface
(python/ray/autoscaler/node_provider.py) and the FakeMultiNodeProvider
(autoscaler/_private/fake_multi_node/node_provider.py:237) that "launches"
nodes as local processes so the full autoscaler loop is testable on one
machine. Here a fake-launched node is an in-process raylet (runtime.node.
Node) joined to the head GCS — the same substrate cluster_utils.Cluster
uses for multi-node tests.
"""

from __future__ import annotations

import abc
import itertools
import threading
from typing import Dict, List, Optional, Tuple


class NodeInstance:
    """Provider-side record of one launched node."""

    #: True while the instance's future capacity should be SYNTHESIZED by
    #: the scheduler (still provisioning / not yet registered as live GCS
    #: nodes). In-process providers register instantly, so False here;
    #: async cloud providers override.
    provisioning = False

    def __init__(self, instance_id: str, node_type: str):
        self.instance_id = instance_id
        self.node_type = node_type


class NodeProvider(abc.ABC):
    """Minimal provider surface the reconciler drives (reference:
    node_provider.py create_node/terminate_node/non_terminated_nodes)."""

    @abc.abstractmethod
    def create_node(self, node_type_name: str) -> NodeInstance: ...

    @abc.abstractmethod
    def terminate_node(self, instance_id: str) -> None: ...

    @abc.abstractmethod
    def non_terminated_nodes(self) -> List[NodeInstance]: ...


class FakeMultiNodeProvider(NodeProvider):
    """Launches worker nodes as in-process raylets against a live cluster
    (reference: FakeMultiNodeProvider launching local processes)."""

    def __init__(self, cluster, config):
        self._cluster = cluster  # cluster_utils.Cluster
        self._config = config  # AutoscalingConfig
        self._counter = itertools.count()
        self._lock = threading.Lock()
        self._instances: Dict[str, tuple] = {}  # instance_id -> (NodeInstance, Node)

    def create_node(self, node_type_name: str) -> NodeInstance:
        node_type = self._config.type_by_name(node_type_name)
        if node_type is None:
            raise ValueError(f"unknown node type {node_type_name!r}")
        node = self._cluster.add_node(
            resources=dict(node_type.resources),
            labels={**node_type.labels, "ray.io/node-type": node_type_name},
        )
        instance_id = f"fake-{node_type_name}-{next(self._counter)}"
        inst = NodeInstance(instance_id, node_type_name)
        with self._lock:
            self._instances[instance_id] = (inst, node)
        return inst

    def terminate_node(self, instance_id: str) -> None:
        with self._lock:
            entry = self._instances.pop(instance_id, None)
        if entry is not None:
            self._cluster.remove_node(entry[1], graceful=True)

    def non_terminated_nodes(self) -> List[NodeInstance]:
        with self._lock:
            return [inst for inst, _node in self._instances.values()]

    def node_id_of(self, instance_id: str):
        """Raylet NodeID for an instance (used to match GCS idle state)."""
        with self._lock:
            entry = self._instances.get(instance_id)
        return entry[1].node_id if entry else None


class TpuSliceProvider(NodeProvider):
    """Slice-granular TPU provider (reference: the GCP provider's TPU-pod
    node groups, autoscaler/_private/gcp/node_provider.py:63 +
    _private/accelerators/tpu.py:213): one instance = one whole
    ICI-connected slice. ``create_node`` launches EVERY host of the slice —
    per-host TPU chips, topology labels (slice name / worker id / pod
    type), the slice-claim head resource on worker 0 — and
    ``terminate_node`` retires the slice atomically, so the cluster only
    ever holds complete ICI domains. Backed by in-process raylets here; a
    real GCE/GKE backend is a thin adapter swapping the launch calls."""

    def __init__(self, cluster, config):
        self._cluster = cluster
        self._config = config
        self._counter = itertools.count()
        self._lock = threading.Lock()
        self._instances: Dict[str, tuple] = {}  # id -> (NodeInstance, [Node])

    def create_node(self, node_type_name: str) -> NodeInstance:
        from .._internal.accelerators import (
            TPU_POD_TYPE_LABEL,
            TPU_SLICE_NAME_LABEL,
            TPU_WORKER_ID_LABEL,
        )

        node_type = self._config.type_by_name(node_type_name)
        if node_type is None:
            raise ValueError(f"unknown node type {node_type_name!r}")
        n = next(self._counter)
        pod_type = node_type.labels.get(TPU_POD_TYPE_LABEL, node_type_name)
        slice_name = f"{pod_type}-as-{n}"
        nodes = []
        try:
            for worker_id in range(node_type.group_size):
                resources = dict(node_type.resources)
                if worker_id == 0:
                    resources.update(node_type.head_resources)
                nodes.append(
                    self._cluster.add_node(
                        resources=resources,
                        labels={
                            **node_type.labels,
                            "ray.io/node-type": node_type_name,
                            TPU_SLICE_NAME_LABEL: slice_name,
                            TPU_WORKER_ID_LABEL: str(worker_id),
                        },
                    )
                )
        except Exception:
            # atomic: a partial slice is useless — roll back launched hosts
            for node in nodes:
                try:
                    self._cluster.remove_node(node, graceful=False)
                except Exception:
                    pass
            raise
        instance_id = f"slice-{slice_name}"
        inst = NodeInstance(instance_id, node_type_name)
        with self._lock:
            self._instances[instance_id] = (inst, nodes)
        return inst

    def terminate_node(self, instance_id: str) -> None:
        with self._lock:
            entry = self._instances.pop(instance_id, None)
        if entry is not None:
            for node in entry[1]:
                try:
                    self._cluster.remove_node(node, graceful=True)
                except Exception:
                    pass

    def non_terminated_nodes(self) -> List[NodeInstance]:
        with self._lock:
            return [inst for inst, _nodes in self._instances.values()]

    def node_ids_of(self, instance_id: str) -> List:
        """All raylet NodeIDs of a slice — an instance is idle only when
        EVERY host is idle."""
        with self._lock:
            entry = self._instances.get(instance_id)
        return [n.node_id for n in entry[1]] if entry else []

    def node_id_of(self, instance_id: str):
        ids = self.node_ids_of(instance_id)
        return ids[0] if ids else None
