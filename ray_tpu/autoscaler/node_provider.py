"""Node providers: the cloud-facing side of the autoscaler.

Role-equivalent of the reference's NodeProvider interface
(python/ray/autoscaler/node_provider.py) and the FakeMultiNodeProvider
(autoscaler/_private/fake_multi_node/node_provider.py:237) that "launches"
nodes as local processes so the full autoscaler loop is testable on one
machine. Here a fake-launched node is an in-process raylet (runtime.node.
Node) joined to the head GCS — the same substrate cluster_utils.Cluster
uses for multi-node tests.
"""

from __future__ import annotations

import abc
import itertools
import threading
from typing import Dict, List, Optional, Tuple


class NodeInstance:
    """Provider-side record of one launched node."""

    def __init__(self, instance_id: str, node_type: str):
        self.instance_id = instance_id
        self.node_type = node_type


class NodeProvider(abc.ABC):
    """Minimal provider surface the reconciler drives (reference:
    node_provider.py create_node/terminate_node/non_terminated_nodes)."""

    @abc.abstractmethod
    def create_node(self, node_type_name: str) -> NodeInstance: ...

    @abc.abstractmethod
    def terminate_node(self, instance_id: str) -> None: ...

    @abc.abstractmethod
    def non_terminated_nodes(self) -> List[NodeInstance]: ...


class FakeMultiNodeProvider(NodeProvider):
    """Launches worker nodes as in-process raylets against a live cluster
    (reference: FakeMultiNodeProvider launching local processes)."""

    def __init__(self, cluster, config):
        self._cluster = cluster  # cluster_utils.Cluster
        self._config = config  # AutoscalingConfig
        self._counter = itertools.count()
        self._lock = threading.Lock()
        self._instances: Dict[str, tuple] = {}  # instance_id -> (NodeInstance, Node)

    def create_node(self, node_type_name: str) -> NodeInstance:
        node_type = self._config.type_by_name(node_type_name)
        if node_type is None:
            raise ValueError(f"unknown node type {node_type_name!r}")
        node = self._cluster.add_node(
            resources=dict(node_type.resources),
            labels={**node_type.labels, "ray.io/node-type": node_type_name},
        )
        instance_id = f"fake-{node_type_name}-{next(self._counter)}"
        inst = NodeInstance(instance_id, node_type_name)
        with self._lock:
            self._instances[instance_id] = (inst, node)
        return inst

    def terminate_node(self, instance_id: str) -> None:
        with self._lock:
            entry = self._instances.pop(instance_id, None)
        if entry is not None:
            self._cluster.remove_node(entry[1], graceful=True)

    def non_terminated_nodes(self) -> List[NodeInstance]:
        with self._lock:
            return [inst for inst, _node in self._instances.values()]

    def node_id_of(self, instance_id: str):
        """Raylet NodeID for an instance (used to match GCS idle state)."""
        with self._lock:
            entry = self._instances.get(instance_id)
        return entry[1].node_id if entry else None
