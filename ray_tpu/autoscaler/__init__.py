"""Autoscaler v2: demand-driven cluster scaling.

Role-equivalent of the reference's autoscaler v2
(python/ray/autoscaler/v2/): a head-side monitor polls the GCS for the
cluster resource state (nodes + pending demands + pending placement
groups), a resource scheduler bin-packs the unmet demand onto configured
node types, and an instance manager reconciles the desired node set through
a pluggable NodeProvider. TPU twist: node types are slice-granular — a
"v5e-8" node type carries the whole host's chips and its slice labels, so
gang demands (placement groups with TPU bundles) scale whole ICI-connected
slices instead of individual VMs.
"""

from .config import NodeTypeConfig, AutoscalingConfig, tpu_slice_node_type
from .node_provider import NodeProvider, FakeMultiNodeProvider, TpuSliceProvider
from .gce_tpu_provider import (
    GceTpuQueuedResourceProvider,
    NodeLaunchError,
    QuotaExceededError,
)
from .scheduler import ResourceScheduler, SchedulingDecision
from .autoscaler import Autoscaler, AutoscalerMonitor

__all__ = [
    "NodeTypeConfig",
    "AutoscalingConfig",
    "NodeProvider",
    "FakeMultiNodeProvider",
    "TpuSliceProvider",
    "tpu_slice_node_type",
    "ResourceScheduler",
    "SchedulingDecision",
    "Autoscaler",
    "AutoscalerMonitor",
]
