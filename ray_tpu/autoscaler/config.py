"""Autoscaling configuration.

Role-equivalent of the reference's cluster-config node_types section
(python/ray/autoscaler/v2/schema.py NodeTypeConfig / ClusterConfig): each
node type declares the resources and labels one launched node contributes,
with min/max counts. TPU slice types set ``labels`` to the slice topology
keys (ray.io/tpu-pod-type etc., reference: common/constants.h:131-142) so
label-selector demands scale the right slice kind.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional


@dataclass
class NodeTypeConfig:
    name: str
    resources: Dict[str, float]
    labels: Dict[str, str] = field(default_factory=dict)
    min_workers: int = 0
    max_workers: int = 10


@dataclass
class AutoscalingConfig:
    node_types: List[NodeTypeConfig]
    max_workers: int = 20  # cluster-wide cap, excluding the head
    idle_timeout_s: float = 60.0
    update_interval_s: float = 1.0

    def type_by_name(self, name: str) -> Optional[NodeTypeConfig]:
        for t in self.node_types:
            if t.name == name:
                return t
        return None
