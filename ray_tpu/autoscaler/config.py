"""Autoscaling configuration.

Role-equivalent of the reference's cluster-config node_types section
(python/ray/autoscaler/v2/schema.py NodeTypeConfig / ClusterConfig): each
node type declares the resources and labels one launched node contributes,
with min/max counts. TPU slice types set ``labels`` to the slice topology
keys (ray.io/tpu-pod-type etc., reference: common/constants.h:131-142) so
label-selector demands scale the right slice kind.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional


@dataclass
class NodeTypeConfig:
    name: str
    resources: Dict[str, float]
    labels: Dict[str, str] = field(default_factory=dict)
    min_workers: int = 0
    max_workers: int = 10
    # Atomic launch groups (reference: the TPU provider's slice-granular
    # node groups, _private/accelerators/tpu.py:213 + gcp/node_provider.py):
    # one create_node launches ``group_size`` hosts that live and die
    # together — a whole ICI-connected slice. ``resources`` is PER HOST;
    # ``head_resources`` lands only on host 0 (the slice-claim resource).
    group_size: int = 1
    head_resources: Dict[str, float] = field(default_factory=dict)


def tpu_slice_node_type(
    pod_type: str,
    *,
    cpus_per_host: float = 2.0,
    min_slices: int = 0,
    max_slices: int = 4,
) -> NodeTypeConfig:
    """Node type for whole-slice scale units of one TPU pod type: min/max
    count SLICES, each launch contributes every host of one slice with the
    topology labels and head resource reserve_tpu_slice() pins to."""
    from .._internal.accelerators import (
        TPU_POD_TYPE_LABEL,
        chips_per_host,
        pod_type_num_hosts,
        tpu_head_resource,
    )

    return NodeTypeConfig(
        name=f"tpu-slice-{pod_type}",
        resources={
            "TPU": float(chips_per_host(pod_type)),
            "CPU": cpus_per_host,
        },
        labels={TPU_POD_TYPE_LABEL: pod_type},
        min_workers=min_slices,
        max_workers=max_slices,
        group_size=pod_type_num_hosts(pod_type),
        head_resources={tpu_head_resource(pod_type): 1.0},
    )


@dataclass
class AutoscalingConfig:
    node_types: List[NodeTypeConfig]
    max_workers: int = 20  # cluster-wide cap, excluding the head
    idle_timeout_s: float = 60.0
    update_interval_s: float = 1.0

    def type_by_name(self, name: str) -> Optional[NodeTypeConfig]:
        for t in self.node_types:
            if t.name == name:
                return t
        return None
