"""GCE TPU queued-resources node provider.

Role-equivalent of the reference's GCP node provider
(autoscaler/_private/gcp/node_provider.py:63) specialized to the TPU
``queuedResources`` API, which is how v4/v5 slices are actually obtained:
create returns immediately and the resource moves through
``WAITING_FOR_RESOURCES -> PROVISIONING -> ACTIVE`` (or ``FAILED``)
asynchronously; creates hit quota (429) under contention; reads are
eventually consistent (a just-created resource can 404 for a while); a
slice can be preempted (ACTIVE -> FAILED) at any time.

The HTTP layer is injectable — ``http(method, path, body) -> (status,
dict)`` — so the full retry/backoff/eventual-consistency/partial-slice
behavior is unit-testable against a mock API (the reference tests its GCP
provider the same way), and a production binding is one function closing
over google-auth credentials.

Lifecycle mapping to the NodeProvider contract:
- ``create_node`` POSTs the queued resource (bounded quota retries with
  exponential backoff) and registers a PENDING instance.
- ``non_terminated_nodes`` polls pending instances: ACTIVE with all hosts
  ready becomes ACTIVE; FAILED (quota revoked, preempted, stockout) is
  deleted remotely and dropped locally so the reconciler's next tick
  relaunches; a 404 inside the consistency grace window is tolerated.
  PENDING and ACTIVE instances both count as non-terminated — the
  reconciler must not double-launch while a slice is provisioning.
- ``terminate_node`` DELETEs with bounded retries.
"""

from __future__ import annotations

import itertools
import logging
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from .node_provider import NodeInstance, NodeProvider

logger = logging.getLogger(__name__)

HttpFn = Callable[[str, str, Optional[dict]], Tuple[int, dict]]

_RETRYABLE = (429, 500, 503)


class QuotaExceededError(Exception):
    pass


class NodeLaunchError(Exception):
    pass


class GceTpuInstance(NodeInstance):
    def __init__(self, instance_id: str, node_type: str,
                 registration_grace_s: float = 120.0):
        super().__init__(instance_id, node_type)
        self.status = "PENDING"  # PENDING | ACTIVE
        self.created_at = time.time()
        self.activated_at: Optional[float] = None
        self.first_seen = False  # a successful GET clears the 404 grace
        self._registration_grace_s = registration_grace_s

    @property
    def provisioning(self) -> bool:
        """Synthesize this instance's capacity while it provisions AND for
        a bounded grace after ACTIVE (hosts boot + raylets register). The
        grace is a ceiling, not a latch: a slice whose hosts die later is
        only phantom capacity until the grace expires, then its demand
        relaunches — the failure mode a permanent not-yet-registered
        heuristic would turn into a stall."""
        if self.status == "PENDING":
            return True
        return (
            self.activated_at is not None
            and time.time() - self.activated_at < self._registration_grace_s
        )


class GceTpuQueuedResourceProvider(NodeProvider):
    def __init__(
        self,
        config,
        http: HttpFn,
        *,
        project: str = "project",
        zone: str = "zone",
        create_retries: int = 4,
        delete_retries: int = 4,
        backoff_s: float = 0.5,
        consistency_grace_s: float = 30.0,
        registration_grace_s: float = 120.0,
        sleep: Callable[[float], None] = time.sleep,
    ):
        self._config = config
        self._http = http
        self._base = f"/projects/{project}/locations/{zone}/queuedResources"
        self._create_retries = create_retries
        self._delete_retries = delete_retries
        self._backoff_s = backoff_s
        self._consistency_grace_s = consistency_grace_s
        self._registration_grace_s = registration_grace_s
        self._sleep = sleep
        self._counter = itertools.count()
        self._lock = threading.Lock()
        self._instances: Dict[str, GceTpuInstance] = {}

    # -- NodeProvider ------------------------------------------------------

    def create_node(self, node_type_name: str) -> NodeInstance:
        node_type = self._config.type_by_name(node_type_name)
        if node_type is None:
            raise ValueError(f"unknown node type {node_type_name!r}")
        name = f"qr-{node_type_name}-{next(self._counter)}"
        body = {
            "tpu": {
                "node_spec": {
                    "node": {
                        "accelerator_type": node_type.labels.get(
                            "ray.io/tpu-pod-type", node_type_name
                        ),
                    },
                    "node_count": max(
                        int(getattr(node_type, "group_size", 1) or 1), 1
                    ),
                }
            }
        }
        last = None
        for attempt in range(self._create_retries):
            status, resp = self._http(
                "POST", f"{self._base}?queued_resource_id={name}", body
            )
            if status == 200:
                inst = GceTpuInstance(
                    name, node_type_name,
                    registration_grace_s=self._registration_grace_s,
                )
                with self._lock:
                    self._instances[name] = inst
                return inst
            last = (status, resp)
            if status in _RETRYABLE:
                # quota/stockout: exponential backoff before the NEXT try
                # (no pointless sleep after the final attempt)
                if attempt < self._create_retries - 1:
                    self._sleep(self._backoff_s * (2 ** attempt))
                continue
            raise NodeLaunchError(f"create {name}: HTTP {status}: {resp}")
        raise QuotaExceededError(f"create {name} exhausted retries: {last}")

    def terminate_node(self, instance_id: str) -> None:
        with self._lock:
            self._instances.pop(instance_id, None)
        for attempt in range(self._delete_retries):
            status, _ = self._http(
                "DELETE", f"{self._base}/{instance_id}", None
            )
            if status in (200, 404):  # 404: already gone — fine
                return
            if status in _RETRYABLE:
                if attempt < self._delete_retries - 1:
                    self._sleep(self._backoff_s * (2 ** attempt))
                continue
            break
        logger.warning("delete of %s did not confirm; orphan possible",
                       instance_id)

    def non_terminated_nodes(self) -> List[NodeInstance]:
        self._poll()
        with self._lock:
            return list(self._instances.values())

    # -- lifecycle polling -------------------------------------------------

    def _poll(self) -> None:
        with self._lock:
            pending = [
                i for i in self._instances.values() if i.status == "PENDING"
            ]
        for inst in pending:
            try:
                status, resp = self._http(
                    "GET", f"{self._base}/{inst.instance_id}", None
                )
            except Exception:
                logger.exception("poll of %s failed", inst.instance_id)
                continue
            if status == 404:
                if inst.first_seen or (
                    time.time() - inst.created_at > self._consistency_grace_s
                ):
                    # was visible before (or grace expired) and is now gone:
                    # DELETE anyway (tolerates 404) — if the 404 was only
                    # read-path lag, the resource would otherwise surface
                    # later as an untracked, quota-eating orphan
                    logger.warning("queued resource %s vanished",
                                   inst.instance_id)
                    self.terminate_node(inst.instance_id)
                continue  # eventual consistency: not visible yet
            if status != 200:
                continue  # transient API error; retry next tick
            inst.first_seen = True
            state = resp.get("state", "")
            if state == "ACTIVE":
                # partial-slice guard: a multi-host slice only becomes
                # usable when EVERY host is up; the API can report ACTIVE
                # with hosts still joining
                ready = resp.get("ready_node_count")
                want = resp.get("node_count", 1)
                if ready is not None and ready < want:
                    continue
                inst.status = "ACTIVE"
                inst.activated_at = time.time()
            elif state in ("FAILED", "SUSPENDED"):
                logger.warning(
                    "queued resource %s entered %s: deleting for relaunch",
                    inst.instance_id, state,
                )
                self.terminate_node(inst.instance_id)
            # WAITING_FOR_RESOURCES / PROVISIONING / ACCEPTED: keep waiting

    # ACTIVE slices can be preempted later; surface that too
    def check_preemptions(self) -> List[str]:
        """Re-poll ACTIVE instances; drop (and DELETE) any the API reports
        FAILED/missing. Returns dropped instance ids (chaos path: a slice
        dying mid-life must free the reconciler to replace it)."""
        with self._lock:
            active = [
                i for i in self._instances.values() if i.status == "ACTIVE"
            ]
        dropped = []
        for inst in active:
            try:
                status, resp = self._http(
                    "GET", f"{self._base}/{inst.instance_id}", None
                )
            except Exception:
                continue
            if status == 404 or (
                status == 200 and resp.get("state") in ("FAILED", "SUSPENDED")
            ):
                self.terminate_node(inst.instance_id)
                dropped.append(inst.instance_id)
        return dropped
