"""The autoscaler reconcile loop.

Role-equivalent of the reference's Autoscaler + Reconciler + monitor
process (python/ray/autoscaler/v2/autoscaler.py:47 update_autoscaling_state,
v2/monitor.py:53 AutoscalerMonitor, v2/instance_manager/reconciler.py):
every tick it pulls GetClusterResourceState from the GCS, asks the
ResourceScheduler what to launch, enforces min/max workers, terminates
nodes idle past the timeout, and reports its state back to the GCS for
observability (ReportAutoscalingState, autoscaler.proto:199).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict, Optional

from .config import AutoscalingConfig
from .node_provider import NodeProvider
from .scheduler import ResourceScheduler

logger = logging.getLogger(__name__)


class Autoscaler:
    def __init__(
        self,
        config: AutoscalingConfig,
        provider: NodeProvider,
        gcs_call,
    ):
        """``gcs_call(method, *args)`` is a sync bridge to GCS RPC — the
        monitor supplies one bound to the head's address."""
        self._config = config
        self._provider = provider
        self._gcs_call = gcs_call
        self._scheduler = ResourceScheduler(config)
        self._idle_since: Dict[str, float] = {}  # instance_id -> ts

    def update(self) -> dict:
        """One reconcile tick (reference: autoscaler.py:169
        update_autoscaling_state)."""
        state = self._gcs_call("get_cluster_resource_state")
        # providers that can lose ACTIVE capacity mid-life (preempted GCE
        # slices) surface it here so the freed slot is replaceable this tick
        preempt_check = getattr(self._provider, "check_preemptions", None)
        if preempt_check is not None:
            dropped = preempt_check()
            if dropped:
                logger.warning("preempted instances dropped: %s", dropped)
        instances = self._provider.non_terminated_nodes()
        counts: Dict[str, int] = {}
        pending: Dict[str, int] = {}
        for inst in instances:
            counts[inst.node_type] = counts.get(inst.node_type, 0) + 1
            # still-provisioning instances get synthetic future capacity in
            # the scheduler; providers without lifecycle states register
            # their nodes ~immediately and report provisioning=False
            if getattr(inst, "provisioning", False):
                pending[inst.node_type] = pending.get(inst.node_type, 0) + 1

        # enforce min_workers
        launches: Dict[str, int] = {}
        for t in self._config.node_types:
            deficit = t.min_workers - counts.get(t.name, 0)
            if deficit > 0:
                launches[t.name] = deficit

        decision = self._scheduler.schedule(
            state,
            {**counts, **{k: counts.get(k, 0) + v for k, v in launches.items()}},
            pending_counts={
                k: pending.get(k, 0) + launches.get(k, 0)
                for k in set(pending) | set(launches)
            },
        )
        for name, n in decision.launches.items():
            launches[name] = launches.get(name, 0) + n

        launched = []
        for name, n in launches.items():
            for _ in range(n):
                try:
                    inst = self._provider.create_node(name)
                    launched.append(inst.instance_id)
                except Exception:
                    logger.exception("launch of %s failed", name)

        terminated = self._terminate_idle(state, instances, counts)

        report = {
            "ts": time.time(),
            "launches": launches,
            "launched": launched,
            "terminated": terminated,
            "infeasible": decision.infeasible,
            "node_count": len(instances) + len(launched) - len(terminated),
        }
        try:
            self._gcs_call("report_autoscaling_state", report)
        except Exception:
            pass
        return report

    def _terminate_idle(self, state, instances, counts) -> list:
        """Scale down nodes idle past the timeout, respecting min_workers
        (reference: instance_manager termination for idle nodes)."""
        now = time.time()
        # idle = all resources available == total (nothing running/leased)
        idle_node_ids = set()
        for node in state.get("nodes", []):
            if not node.get("alive") or node.get("is_head"):
                continue
            total = node.get("resources_total", {})
            avail = node.get("available", {})
            if total and all(
                abs(avail.get(k, 0.0) - v) < 1e-9 for k, v in total.items()
            ):
                idle_node_ids.add(node["node_id"])

        terminated = []
        for inst in instances:
            # grouped instances (TPU slices) are idle only when EVERY host
            # is idle — scale-down retires whole ICI domains or nothing
            ids_of = getattr(self._provider, "node_ids_of", None)
            if ids_of is not None:
                node_ids = ids_of(inst.instance_id)
            else:
                node_id = getattr(
                    self._provider, "node_id_of", lambda _i: None
                )(inst.instance_id)
                node_ids = [node_id] if node_id is not None else []
            if not node_ids or not all(n in idle_node_ids for n in node_ids):
                self._idle_since.pop(inst.instance_id, None)
                continue
            since = self._idle_since.setdefault(inst.instance_id, now)
            if now - since < self._config.idle_timeout_s:
                continue
            node_type = self._config.type_by_name(inst.node_type)
            if (
                node_type is not None
                and counts.get(inst.node_type, 0) <= node_type.min_workers
            ):
                continue
            try:
                self._provider.terminate_node(inst.instance_id)
                counts[inst.node_type] = counts.get(inst.node_type, 1) - 1
                terminated.append(inst.instance_id)
                self._idle_since.pop(inst.instance_id, None)
            except Exception:
                logger.exception("terminate of %s failed", inst.instance_id)
        return terminated


class AutoscalerMonitor:
    """Background thread running the reconcile loop against a live GCS
    (reference: v2/monitor.py:53 — the head-node monitor process)."""

    def __init__(self, config: AutoscalingConfig, provider: NodeProvider,
                 gcs_address):
        self._gcs_address = tuple(gcs_address)
        self._interval = config.update_interval_s
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        from .._internal.event_loop import LoopThread

        self._loop_thread = LoopThread("autoscaler-monitor")
        self.autoscaler = Autoscaler(config, provider, self._gcs_call)

    def _gcs_call(self, method, *args):
        from .._internal.rpc import RpcClient

        async def _call():
            client = RpcClient(*self._gcs_address, name="autoscaler")
            try:
                return await client.call(method, *args, timeout=10.0)
            finally:
                await client.close()

        return self._loop_thread.run(_call(), timeout=15.0)

    def start(self):
        self._thread = threading.Thread(
            target=self._run, name="autoscaler", daemon=True
        )
        self._thread.start()

    def _run(self):
        while not self._stop.wait(self._interval):
            try:
                self.autoscaler.update()
            except Exception:
                logger.exception("autoscaler update failed")

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        self._loop_thread.stop()
