"""Resource scheduler: bin-pack pending demand onto node types.

Role-equivalent of the reference's IResourceScheduler
(python/ray/autoscaler/v2/scheduler.py:88): given the current cluster
state and the unmet resource demands, decide which node types to launch.
The bin-packing mirrors the reference's approach — first fit demands onto
existing free capacity, then onto already-planned launches, then open a new
node of the smallest feasible type. Placement-group demands are handled
gang-wise: all bundles of a pending group must fit on the planned node set
or the group contributes launches for every bundle (STRICT_SPREAD gets one
node per bundle).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

from .config import AutoscalingConfig, NodeTypeConfig


@dataclass
class SchedulingDecision:
    launches: Dict[str, int] = field(default_factory=dict)  # node type -> count
    infeasible: List[dict] = field(default_factory=list)

    def total_launches(self) -> int:
        return sum(self.launches.values())


def _fits(capacity: Dict[str, float], demand: Dict[str, float]) -> bool:
    return all(capacity.get(k, 0.0) >= v - 1e-9 for k, v in demand.items())


def _labels_match(labels: Dict[str, str], selector: Dict[str, str]) -> bool:
    return all(labels.get(k) == v for k, v in (selector or {}).items())


def _consume(capacity: Dict[str, float], demand: Dict[str, float]):
    for k, v in demand.items():
        capacity[k] = capacity.get(k, 0.0) - v


class _PlannedNode:
    __slots__ = ("type_name", "capacity", "labels")

    def __init__(self, type_name: str, capacity: Dict[str, float], labels):
        self.type_name = type_name
        self.capacity = dict(capacity)
        self.labels = dict(labels)


class ResourceScheduler:
    def __init__(self, config: AutoscalingConfig):
        self._config = config

    def schedule(
        self,
        cluster_state: dict,
        current_counts: Dict[str, int],
        pending_counts: Optional[Dict[str, int]] = None,
    ) -> SchedulingDecision:
        """cluster_state is the GCS GetClusterResourceState reply;
        current_counts is every provider instance per type (so max_workers
        caps hold); pending_counts is the subset still PROVISIONING — their
        future capacity is synthesized so the same unmet demand doesn't
        relaunch every tick, but ONLY for instances the provider itself
        reports pending: a dead-but-listed instance must NOT contribute
        phantom capacity (that would stall its replacement forever)."""
        decision = SchedulingDecision()

        # Free capacity on live nodes.
        free: List[_PlannedNode] = []
        for node in cluster_state.get("nodes", []):
            if not node.get("alive"):
                continue
            free.append(
                _PlannedNode("__existing__", node.get("available", {}),
                             node.get("labels", {}))
            )
        planned: List[_PlannedNode] = []
        planned_counts: Dict[str, int] = dict(current_counts)

        # In-flight capacity (async providers — a GCE queued resource
        # provisions for minutes): synthesize the future hosts of
        # still-PENDING instances so their demand doesn't relaunch per tick.
        for t in self._config.node_types:
            labels = {**t.labels, "ray.io/node-type": t.name}
            for _ in range((pending_counts or {}).get(t.name, 0)):
                for host_idx in range(t.group_size):
                    capacity = dict(t.resources)
                    if host_idx == 0:
                        capacity.update(t.head_resources)
                    planned.append(_PlannedNode(t.name, capacity, labels))

        def try_place(resources: Dict[str, float], selector) -> bool:
            for node in free + planned:
                if _labels_match(node.labels, selector) and _fits(
                    node.capacity, resources
                ):
                    _consume(node.capacity, resources)
                    return True
            return self._open_node(resources, selector, planned,
                                   planned_counts, decision) is not None

        # Plain task/actor demands.
        for demand in cluster_state.get("pending_demands", []):
            resources = demand.get("resources", {})
            selector = demand.get("label_selector", {})
            for _ in range(demand.get("count", 1)):
                if not try_place(resources, selector):
                    decision.infeasible.append(demand)
                    break

        # Pending placement groups: place each bundle; STRICT_SPREAD means
        # one fresh planned node per bundle (reference: bundle PACK/SPREAD
        # policies, policy/bundle_scheduling_policy.h:29-97).
        for pg in cluster_state.get("pending_placement_groups", []):
            strategy = str(pg.get("strategy", ""))
            strict_spread = "STRICT_SPREAD" in strategy.upper()
            used: List[_PlannedNode] = []
            for bundle in pg.get("bundles", []):
                placed = False
                pool = free + planned
                if strict_spread:
                    pool = [n for n in pool if n not in used]
                for node in pool:
                    if _fits(node.capacity, bundle):
                        _consume(node.capacity, bundle)
                        used.append(node)
                        placed = True
                        break
                if not placed:
                    opened = self._open_node(bundle, {}, planned,
                                             planned_counts, decision)
                    if opened is not None:
                        # the host the bundle actually landed on (for
                        # grouped slice types this is host 0, not the last
                        # host appended) — spread exclusion must track it
                        used.append(opened)
                    else:
                        decision.infeasible.append({"resources": bundle})
        return decision

    def _open_node(self, resources, selector, planned, planned_counts,
                   decision) -> Optional[_PlannedNode]:
        """Launch the smallest feasible node type for this demand; returns
        the planned host the demand landed on (None if infeasible). Grouped
        types (TPU slices) launch atomically: one decision contributes every
        host of the slice to the planned pool, with the head resource on
        host 0 — so a slice-claim bundle opens exactly one slice and the
        remaining hosts absorb the worker-gang bundles."""
        candidates: List[NodeTypeConfig] = []
        for t in self._config.node_types:
            labels = {**t.labels, "ray.io/node-type": t.name}
            if not _labels_match(labels, selector):
                continue
            host0 = {**t.resources, **t.head_resources}
            if not (_fits(dict(host0), resources)
                    or _fits(dict(t.resources), resources)):
                continue
            if planned_counts.get(t.name, 0) >= t.max_workers:
                continue
            candidates.append(t)
        if not candidates:
            return None
        total_planned = sum(planned_counts.values())
        if total_planned >= self._config.max_workers:
            return None
        best = min(
            candidates,
            key=lambda t: sum(t.resources.values()) * t.group_size,
        )
        planned_counts[best.name] = planned_counts.get(best.name, 0) + 1
        decision.launches[best.name] = decision.launches.get(best.name, 0) + 1
        labels = {**best.labels, "ray.io/node-type": best.name}
        hosts = []
        for host_idx in range(best.group_size):
            capacity = dict(best.resources)
            if host_idx == 0:
                capacity.update(best.head_resources)
            node = _PlannedNode(best.name, capacity, labels)
            planned.append(node)
            hosts.append(node)
        for node in hosts:
            if _fits(node.capacity, resources):
                _consume(node.capacity, resources)
                return node
        return hosts[0]
