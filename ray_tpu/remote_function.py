"""@remote functions.

Role-equivalent of the reference's RemoteFunction (python/ray/remote_function.py):
a decorated function gains ``.remote(...)`` / ``.options(...)``; the pickled
definition ships once per process through the GCS function table and tasks are
submitted through the core worker.
"""

from __future__ import annotations

import hashlib
from typing import Any, Dict, List, Optional

from . import _worker_api
from ._internal import args as arglib
from ._internal import serialization
from ._internal.ids import ObjectID
from .runtime.gcs import keys as gcs_keys
from ._internal.protocol import (
    DefaultSchedulingStrategy,
    FunctionDescriptor,
    TaskArg,
    TaskSpec,
    TaskType,
)
from .object_ref import ObjectRef

_DEFAULT_TASK_OPTIONS = dict(
    num_returns=1,
    num_cpus=1.0,
    resources=None,
    max_retries=3,
    retry_exceptions=False,
    scheduling_strategy=None,
    label_selector=None,
    runtime_env=None,
    name=None,
)


def build_resources(options: Dict[str, Any]) -> Dict[str, float]:
    resources = dict(options.get("resources") or {})
    num_cpus = options.get("num_cpus")
    if num_cpus is None:
        num_cpus = 1.0
    if num_cpus:
        resources["CPU"] = float(num_cpus)
    num_tpus = options.get("num_tpus")
    if num_tpus:
        resources["TPU"] = float(num_tpus)
    num_gpus = options.get("num_gpus")
    if num_gpus:
        resources["GPU"] = float(num_gpus)
    return resources


def _normalize_runtime_env(runtime_env, worker):
    """Package + validate a runtime_env option at submission time, merging
    the job-level default under it (reference: runtime-env upload in
    remote_function/_private + JobConfig default merging)."""
    job_env = getattr(worker, "job_runtime_env", None)
    if job_env:
        merged = dict(job_env)
        merged.update(runtime_env or {})
        env_vars = {**(job_env.get("env_vars") or {}),
                    **((runtime_env or {}).get("env_vars") or {})}
        if env_vars:
            merged["env_vars"] = env_vars
        runtime_env = merged
    if not runtime_env:
        return None
    from ._internal.runtime_env import normalize_cached

    return normalize_cached(runtime_env, worker)


def prepare_args(worker, args: tuple, kwargs: dict) -> List[TaskArg]:
    """Flatten into TaskArgs: slot 0 is the pickled structure, the rest are
    top-level by-reference args, then pin-only entries for refs nested
    inside containers (nested-ref containment, reference_counter.h:44 — the
    owner keeps them alive for the task's flight; the executor resolves them
    from the structure and registers as their borrower on deserialize)."""
    structure, extracted = arglib.flatten(args, kwargs)
    with serialization.collect_refs() as nested:
        packed = serialization.pack(structure)
    from .util import metrics

    metrics.record_object_serialization("task_arg", len(packed))
    task_args = [TaskArg(value=packed)]
    for ref in extracted:
        owner = ref.owner_address or worker.address
        task_args.append(TaskArg(object_id=ref.id, owner_address=owner))
    for ref in nested:
        owner = ref.owner_address or worker.address
        task_args.append(
            TaskArg(object_id=ref.id, owner_address=owner, nested=True)
        )
    return task_args


class RemoteFunction:
    def __init__(self, function, task_options: Dict[str, Any]):
        self._function = function
        self._options = {**_DEFAULT_TASK_OPTIONS, **task_options}
        self._pickled: Optional[bytes] = None
        self._hash: Optional[str] = None
        # processes in which the definition has been exported
        self._exported_for: Optional[int] = None
        self.__name__ = getattr(function, "__name__", "remote_function")
        self.__doc__ = getattr(function, "__doc__", None)

    def __call__(self, *a, **kw):
        raise TypeError(
            f"Remote function {self.__name__} cannot be called directly; use "
            f"{self.__name__}.remote()."
        )

    def options(self, **task_options) -> "_BoundRemoteFunction":
        merged = {**self._options, **task_options}
        return _BoundRemoteFunction(self, merged)

    def remote(self, *args, **kwargs):
        return self._remote(args, kwargs, self._options)

    def bind(self, *args, **kwargs):
        """Build a DAG node instead of executing (reference:
        remote_function.py bind -> dag.FunctionNode)."""
        from .dag import FunctionNode

        return FunctionNode(self, args, kwargs)

    # -- internals ---------------------------------------------------------

    def _ensure_exported(self, worker) -> str:
        if self._pickled is None:
            self._pickled = serialization.dumps(self._function)
            self._hash = hashlib.sha1(self._pickled).hexdigest()
        if self._exported_for != id(worker):
            _worker_api.run_on_worker_loop(
                worker.client_pool.get(*worker.gcs_address).call(
                    "kv_put", gcs_keys.FUNCTION.key(self._hash), self._pickled, True
                )
            )
            self._exported_for = id(worker)
        return self._hash

    def _remote(self, args: tuple, kwargs: dict, options: Dict[str, Any]):
        from .util import tracing

        if tracing.is_tracing_enabled():
            with tracing.trace_span(
                f"submit:{self.__name__}", category="ray_tpu.task"
            ):
                return self._remote_impl(args, kwargs, options)
        return self._remote_impl(args, kwargs, options)

    def _remote_impl(self, args: tuple, kwargs: dict, options: Dict[str, Any]):
        worker = _worker_api.get_core_worker()
        fn_hash = self._ensure_exported(worker)
        task_args = prepare_args(worker, args, kwargs)
        num_returns = options["num_returns"]
        # streaming generators: yielded items become their own objects as
        # they are produced (reference: num_returns="streaming" ->
        # ObjectRefGenerator, _private/object_ref_generator.py:32)
        streaming = num_returns == "streaming"
        if streaming:
            num_returns = 0
        from .util.scheduling_strategies import to_protocol_strategy

        strategy = to_protocol_strategy(options.get("scheduling_strategy"))
        pg_id = None
        bundle_index = -1
        from ._internal.protocol import PlacementGroupSchedulingStrategy

        if isinstance(strategy, PlacementGroupSchedulingStrategy):
            pg_id = strategy.placement_group_id
            bundle_index = strategy.bundle_index
        spec = TaskSpec(
            task_id=worker.next_task_id(),
            job_id=worker.job_id,
            task_type=TaskType.NORMAL_TASK,
            function=FunctionDescriptor(
                module=getattr(self._function, "__module__", "") or "",
                qualname=self.__name__,
                function_hash=fn_hash,
            ),
            args=task_args,
            num_returns=num_returns,
            resources=build_resources(options),
            owner_worker_id=worker.worker_id,
            owner_address=worker.address,
            scheduling_strategy=strategy,
            label_selector=dict(options.get("label_selector") or {}),
            max_retries=options["max_retries"],
            retry_exceptions=bool(options["retry_exceptions"]),
            placement_group_id=pg_id,
            placement_group_bundle_index=bundle_index,
            is_streaming_generator=streaming,
            runtime_env=_normalize_runtime_env(options.get("runtime_env"), worker),
        )
        from .util import tracing

        spec.trace_context = tracing.inject_context()
        return_ids = _worker_api.run_on_worker_loop(worker.submit_task(spec))
        if streaming:
            from .object_ref import ObjectRefGenerator

            return ObjectRefGenerator(spec.task_id)
        refs = [ObjectRef(oid, worker.address) for oid in return_ids]
        if num_returns == 0:
            return None
        if num_returns == 1:
            return refs[0]
        return refs


class _BoundRemoteFunction:
    """Result of fn.options(...): only exposes .remote()."""

    def __init__(self, base: RemoteFunction, options: Dict[str, Any]):
        self._base = base
        self._options = options
        self.__name__ = base.__name__

    def remote(self, *args, **kwargs):
        return self._base._remote(args, kwargs, self._options)

    def bind(self, *args, **kwargs):
        from .dag import FunctionNode

        return FunctionNode(self._base, args, kwargs, options=self._options)


def make_remote_function(function, **task_options) -> RemoteFunction:
    return RemoteFunction(function, task_options)
