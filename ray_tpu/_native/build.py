"""Build the native store library (g++ -> libray_tpu_store.so).

Invoked lazily on import of ray_tpu._native.lib (and manually:
``python ray_tpu/_native/build.py``). Rebuilds when the source is newer
than the library. No external deps — plain g++ + pthread.
"""

from __future__ import annotations

import os
import subprocess
import sys

_DIR = os.path.dirname(os.path.abspath(__file__))
SRC = os.path.join(_DIR, "store.cc")
LIB = os.path.join(_DIR, "libray_tpu_store.so")


XLANG_SRC = os.path.join(_DIR, "xlang_client.cc")
XLANG_BIN = os.path.join(_DIR, "ray_tpu_xlang")
XLANG_LIB = os.path.join(_DIR, "libray_tpu_xlang.so")


def _compile(cmd, out):
    subprocess.run(cmd + ["-o", out + ".tmp"], check=True, capture_output=True)
    os.replace(out + ".tmp", out)  # atomic: concurrent builders race safely
    return out


def _stale(out, src):
    return not os.path.exists(out) or os.path.getmtime(out) < os.path.getmtime(src)


def build(force: bool = False) -> str:
    """Compile the store library if missing/stale; returns the path."""
    if not force and not _stale(LIB, SRC):
        return LIB
    return _compile(
        ["g++", "-std=c++17", "-O2", "-shared", "-fPIC", "-pthread", SRC], LIB
    )


def build_xlang(force: bool = False) -> tuple:
    """Compile the C++ frontend (CLI binary + ctypes lib); returns paths."""
    if force or _stale(XLANG_BIN, XLANG_SRC):
        _compile(
            ["g++", "-std=c++17", "-O2", "-DRAY_TPU_XLANG_MAIN", XLANG_SRC],
            XLANG_BIN,
        )
    if force or _stale(XLANG_LIB, XLANG_SRC):
        _compile(
            ["g++", "-std=c++17", "-O2", "-shared", "-fPIC", XLANG_SRC],
            XLANG_LIB,
        )
    return XLANG_BIN, XLANG_LIB


if __name__ == "__main__":
    force = "--force" in sys.argv
    print(build(force=force))
    for p in build_xlang(force=force):
        print(p)
