"""Build the native store library (g++ -> libray_tpu_store.so).

Invoked lazily on import of ray_tpu._native.lib (and manually:
``python ray_tpu/_native/build.py``). Rebuilds when the source is newer
than the library. No external deps — plain g++ + pthread.
"""

from __future__ import annotations

import os
import subprocess
import sys

_DIR = os.path.dirname(os.path.abspath(__file__))
SRC = os.path.join(_DIR, "store.cc")
LIB = os.path.join(_DIR, "libray_tpu_store.so")


def build(force: bool = False) -> str:
    """Compile if missing/stale; returns the library path."""
    if (
        not force
        and os.path.exists(LIB)
        and os.path.getmtime(LIB) >= os.path.getmtime(SRC)
    ):
        return LIB
    cmd = [
        "g++",
        "-std=c++17",
        "-O2",
        "-shared",
        "-fPIC",
        "-pthread",
        "-o",
        LIB + ".tmp",
        SRC,
    ]
    subprocess.run(cmd, check=True, capture_output=True)
    os.replace(LIB + ".tmp", LIB)  # atomic: concurrent builders race safely
    return LIB


if __name__ == "__main__":
    path = build(force="--force" in sys.argv)
    print(path)
