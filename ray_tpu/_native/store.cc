// Native shared-memory object store core.
//
// Role-equivalent of the reference's Plasma store internals
// (src/ray/object_manager/plasma/store.h, object_store.h, eviction_policy.h,
// dlmalloc-over-mmap arenas): ONE file-backed mmap arena per node, a
// first-fit free-list allocator with coalescing, an object table with
// pin counts and primary-copy protection, and LRU eviction of sealed,
// unpinned objects when an allocation needs space.
//
// Exposed as a C API consumed via ctypes from the raylet process (the only
// writer of the table); workers mmap the same arena file and read/write at
// offsets handed to them over the raylet RPC — the zero-copy path the
// reference gets from fd-passing (plasma fling.cc).
//
// Build: g++ -O2 -shared -fPIC -o libray_tpu_store.so store.cc

#include <cstdint>
#include <cstring>
#include <ctime>
#include <map>
#include <mutex>
#include <string>
#include <unordered_map>
#include <vector>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

struct Entry {
  uint64_t offset = 0;
  uint64_t size = 0;
  bool sealed = false;
  bool primary = false;
  int32_t pins = 0;
  uint64_t last_access = 0;  // monotonically increasing logical clock
};

struct Arena {
  int fd = -1;
  uint8_t* base = nullptr;
  uint64_t capacity = 0;
  uint64_t used = 0;
  uint64_t clock = 0;
  std::string path;
  // free list keyed by offset -> length; invariant: no two adjacent blocks
  std::map<uint64_t, uint64_t> free_blocks;
  std::unordered_map<std::string, Entry> objects;
  std::mutex mu;
};

std::mutex g_mu;
std::vector<Arena*> g_arenas;

constexpr uint64_t kAlign = 64;  // cache-line align objects

uint64_t align_up(uint64_t n) { return (n + kAlign - 1) & ~(kAlign - 1); }

Arena* arena(int h) {
  std::lock_guard<std::mutex> l(g_mu);
  if (h < 0 || h >= static_cast<int>(g_arenas.size())) return nullptr;
  return g_arenas[h];
}

// first-fit allocation from the free list
int64_t alloc_block(Arena* a, uint64_t need) {
  for (auto it = a->free_blocks.begin(); it != a->free_blocks.end(); ++it) {
    if (it->second >= need) {
      uint64_t off = it->first;
      uint64_t len = it->second;
      a->free_blocks.erase(it);
      if (len > need) a->free_blocks.emplace(off + need, len - need);
      a->used += need;
      return static_cast<int64_t>(off);
    }
  }
  return -1;
}

// return a block, coalescing with neighbors
void free_block(Arena* a, uint64_t off, uint64_t len) {
  a->used -= len;
  auto next = a->free_blocks.lower_bound(off);
  // merge with previous block if adjacent
  if (next != a->free_blocks.begin()) {
    auto prev = std::prev(next);
    if (prev->first + prev->second == off) {
      off = prev->first;
      len += prev->second;
      a->free_blocks.erase(prev);
    }
  }
  // merge with next block if adjacent
  if (next != a->free_blocks.end() && off + len == next->first) {
    len += next->second;
    a->free_blocks.erase(next);
  }
  a->free_blocks.emplace(off, len);
}

// evict sealed, unpinned, non-primary objects in LRU order until a block of
// `need` bytes can be carved (reference: EvictionPolicy::ChooseObjectsToEvict)
bool evict_until(Arena* a, uint64_t need) {
  while (true) {
    // retry after every eviction: coalescing may have opened a large block
    for (auto& kv : a->free_blocks)
      if (kv.second >= need) return true;
    const std::string* victim = nullptr;
    uint64_t oldest = UINT64_MAX;
    for (auto& kv : a->objects) {
      const Entry& e = kv.second;
      if (e.sealed && e.pins == 0 && !e.primary && e.last_access < oldest) {
        oldest = e.last_access;
        victim = &kv.first;
      }
    }
    if (victim == nullptr) return false;
    auto it = a->objects.find(*victim);
    free_block(a, it->second.offset, it->second.size);
    a->objects.erase(it);
  }
}

}  // namespace

extern "C" {

// Create (or overwrite) the arena file and mmap it shared. Returns a handle
// >= 0, or -1 on failure.
int rt_store_open(const char* path, uint64_t capacity) {
  int fd = ::open(path, O_RDWR | O_CREAT, 0644);
  if (fd < 0) return -1;
  if (::ftruncate(fd, static_cast<off_t>(capacity)) != 0) {
    ::close(fd);
    return -1;
  }
  void* base =
      ::mmap(nullptr, capacity, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  if (base == MAP_FAILED) {
    ::close(fd);
    return -1;
  }
  Arena* a = new Arena();
  a->fd = fd;
  a->base = static_cast<uint8_t*>(base);
  a->capacity = capacity;
  a->path = path;
  a->free_blocks.emplace(0, capacity);
  std::lock_guard<std::mutex> l(g_mu);
  g_arenas.push_back(a);
  return static_cast<int>(g_arenas.size()) - 1;
}

void rt_store_close(int h) {
  Arena* a = arena(h);
  if (!a) return;
  ::munmap(a->base, a->capacity);
  ::close(a->fd);
  ::unlink(a->path.c_str());
  {
    std::lock_guard<std::mutex> l(g_mu);
    g_arenas[h] = nullptr;
  }
  delete a;
}

// Allocate space for an object. Returns the offset, or:
//   -1 out of memory (even after eviction), -2 already exists
int64_t rt_create(int h, const char* oid, uint64_t size) {
  Arena* a = arena(h);
  if (!a) return -1;
  std::lock_guard<std::mutex> l(a->mu);
  std::string key(oid);
  if (a->objects.count(key)) return -2;
  uint64_t need = align_up(size == 0 ? 1 : size);
  if (need > a->capacity) return -1;
  int64_t off = alloc_block(a, need);
  if (off < 0) {
    if (!evict_until(a, need)) return -1;
    off = alloc_block(a, need);
    if (off < 0) return -1;
  }
  Entry e;
  e.offset = static_cast<uint64_t>(off);
  e.size = need;
  e.last_access = ++a->clock;
  a->objects.emplace(std::move(key), e);
  return off;
}

int rt_seal(int h, const char* oid) {
  Arena* a = arena(h);
  if (!a) return -1;
  std::lock_guard<std::mutex> l(a->mu);
  auto it = a->objects.find(oid);
  if (it == a->objects.end()) return -1;
  it->second.sealed = true;
  it->second.last_access = ++a->clock;
  return 0;
}

// Pin + locate. 0 ok, -1 missing, -2 not sealed yet.
int rt_get(int h, const char* oid, uint64_t* offset, uint64_t* size) {
  Arena* a = arena(h);
  if (!a) return -1;
  std::lock_guard<std::mutex> l(a->mu);
  auto it = a->objects.find(oid);
  if (it == a->objects.end()) return -1;
  if (!it->second.sealed) return -2;
  it->second.pins++;
  it->second.last_access = ++a->clock;
  *offset = it->second.offset;
  *size = it->second.size;
  return 0;
}

void rt_release(int h, const char* oid) {
  Arena* a = arena(h);
  if (!a) return;
  std::lock_guard<std::mutex> l(a->mu);
  auto it = a->objects.find(oid);
  if (it != a->objects.end() && it->second.pins > 0) it->second.pins--;
}

void rt_pin_primary(int h, const char* oid) {
  Arena* a = arena(h);
  if (!a) return;
  std::lock_guard<std::mutex> l(a->mu);
  auto it = a->objects.find(oid);
  if (it != a->objects.end()) it->second.primary = true;
}

int rt_contains(int h, const char* oid) {
  Arena* a = arena(h);
  if (!a) return 0;
  std::lock_guard<std::mutex> l(a->mu);
  auto it = a->objects.find(oid);
  return (it != a->objects.end() && it->second.sealed) ? 1 : 0;
}

int rt_free(int h, const char* oid) {
  Arena* a = arena(h);
  if (!a) return -1;
  std::lock_guard<std::mutex> l(a->mu);
  auto it = a->objects.find(oid);
  if (it == a->objects.end()) return -1;
  free_block(a, it->second.offset, it->second.size);
  a->objects.erase(it);
  return 0;
}

// Free only when no reader holds a pin: the spill path must not reallocate
// a block a concurrent get just handed out. 0 freed, -1 missing, -2 pinned.
int rt_free_if_unpinned(int h, const char* oid) {
  Arena* a = arena(h);
  if (!a) return -1;
  std::lock_guard<std::mutex> l(a->mu);
  auto it = a->objects.find(oid);
  if (it == a->objects.end()) return -1;
  if (it->second.pins > 0) return -2;
  free_block(a, it->second.offset, it->second.size);
  a->objects.erase(it);
  return 0;
}

uint64_t rt_used(int h) {
  Arena* a = arena(h);
  if (!a) return 0;
  std::lock_guard<std::mutex> l(a->mu);
  return a->used;
}

uint64_t rt_num_objects(int h) {
  Arena* a = arena(h);
  if (!a) return 0;
  std::lock_guard<std::mutex> l(a->mu);
  return a->objects.size();
}

// LRU spill victim: primary copies are exempt from eviction, so when the
// arena fills with live primaries the raylet spills them to disk instead
// (reference: LocalObjectManager::SpillObjects, local_object_manager.h:115).
// Writes the victim's id into out (NUL-terminated). Returns 1 if found.
int rt_lru_spillable(int h, char* out, int out_len) {
  Arena* a = arena(h);
  if (!a) return 0;
  std::lock_guard<std::mutex> l(a->mu);
  const std::string* victim = nullptr;
  uint64_t oldest = UINT64_MAX;
  for (auto& kv : a->objects) {
    const Entry& e = kv.second;
    if (e.sealed && e.pins == 0 && e.primary && e.last_access < oldest) {
      oldest = e.last_access;
      victim = &kv.first;
    }
  }
  if (victim == nullptr ||
      static_cast<int>(victim->size()) + 1 > out_len)
    return 0;
  std::memcpy(out, victim->c_str(), victim->size() + 1);
  return 1;
}

}  // extern "C"
