// Native shared-memory object store core.
//
// Role-equivalent of the reference's Plasma store internals
// (src/ray/object_manager/plasma/store.h, object_store.h, eviction_policy.h,
// dlmalloc-over-mmap arenas): ONE file-backed mmap arena per node, a
// first-fit free-list allocator with coalescing, an object table with
// pin counts and primary-copy protection, and LRU eviction of sealed,
// unpinned objects when an allocation needs space.
//
// Exposed as a C API consumed via ctypes from the raylet process (the only
// writer of the table); workers mmap the same arena file and read/write at
// offsets handed to them over the raylet RPC — the zero-copy path the
// reference gets from fd-passing (plasma fling.cc).
//
// Build: g++ -O2 -shared -fPIC -o libray_tpu_store.so store.cc

#include <cstdint>
#include <cstring>
#include <ctime>
#include <map>
#include <mutex>
#include <string>
#include <unordered_map>
#include <vector>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

struct Entry {
  uint64_t offset = 0;
  uint64_t size = 0;       // padded (allocation) size
  uint64_t true_size = 0;  // caller-requested payload size
  bool sealed = false;
  bool primary = false;
  int32_t pins = 0;
  uint64_t last_access = 0;  // monotonically increasing logical clock
};

struct Arena {
  int fd = -1;
  uint8_t* base = nullptr;
  uint64_t capacity = 0;
  uint64_t used = 0;
  uint64_t clock = 0;
  std::string path;
  // free list keyed by offset -> length; invariant: no two adjacent blocks
  std::map<uint64_t, uint64_t> free_blocks;
  std::unordered_map<std::string, Entry> objects;
  std::mutex mu;
};

std::mutex g_mu;
std::vector<Arena*> g_arenas;

constexpr uint64_t kAlign = 64;  // cache-line align objects

uint64_t align_up(uint64_t n) { return (n + kAlign - 1) & ~(kAlign - 1); }

Arena* arena(int h) {
  std::lock_guard<std::mutex> l(g_mu);
  if (h < 0 || h >= static_cast<int>(g_arenas.size())) return nullptr;
  return g_arenas[h];
}

// first-fit allocation from the free list
int64_t alloc_block(Arena* a, uint64_t need) {
  for (auto it = a->free_blocks.begin(); it != a->free_blocks.end(); ++it) {
    if (it->second >= need) {
      uint64_t off = it->first;
      uint64_t len = it->second;
      a->free_blocks.erase(it);
      if (len > need) a->free_blocks.emplace(off + need, len - need);
      a->used += need;
      return static_cast<int64_t>(off);
    }
  }
  return -1;
}

// return a block, coalescing with neighbors
void free_block(Arena* a, uint64_t off, uint64_t len) {
  a->used -= len;
  auto next = a->free_blocks.lower_bound(off);
  // merge with previous block if adjacent
  if (next != a->free_blocks.begin()) {
    auto prev = std::prev(next);
    if (prev->first + prev->second == off) {
      off = prev->first;
      len += prev->second;
      a->free_blocks.erase(prev);
    }
  }
  // merge with next block if adjacent
  if (next != a->free_blocks.end() && off + len == next->first) {
    len += next->second;
    a->free_blocks.erase(next);
  }
  a->free_blocks.emplace(off, len);
}

// evict sealed, unpinned, non-primary objects in LRU order until a block of
// `need` bytes can be carved (reference: EvictionPolicy::ChooseObjectsToEvict)
bool evict_until(Arena* a, uint64_t need) {
  while (true) {
    // retry after every eviction: coalescing may have opened a large block
    for (auto& kv : a->free_blocks)
      if (kv.second >= need) return true;
    const std::string* victim = nullptr;
    uint64_t oldest = UINT64_MAX;
    for (auto& kv : a->objects) {
      const Entry& e = kv.second;
      if (e.sealed && e.pins == 0 && !e.primary && e.last_access < oldest) {
        oldest = e.last_access;
        victim = &kv.first;
      }
    }
    if (victim == nullptr) return false;
    auto it = a->objects.find(*victim);
    free_block(a, it->second.offset, it->second.size);
    a->objects.erase(it);
  }
}

}  // namespace

extern "C" {

// Create (or overwrite) the arena file and mmap it shared. Returns a handle
// >= 0, or -1 on failure.
int rt_store_open(const char* path, uint64_t capacity) {
  int fd = ::open(path, O_RDWR | O_CREAT, 0644);
  if (fd < 0) return -1;
  if (::ftruncate(fd, static_cast<off_t>(capacity)) != 0) {
    ::close(fd);
    return -1;
  }
  void* base =
      ::mmap(nullptr, capacity, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  if (base == MAP_FAILED) {
    ::close(fd);
    return -1;
  }
  Arena* a = new Arena();
  a->fd = fd;
  a->base = static_cast<uint8_t*>(base);
  a->capacity = capacity;
  a->path = path;
  a->free_blocks.emplace(0, capacity);
  std::lock_guard<std::mutex> l(g_mu);
  g_arenas.push_back(a);
  return static_cast<int>(g_arenas.size()) - 1;
}

void rt_store_close(int h) {
  Arena* a = arena(h);
  if (!a) return;
  ::munmap(a->base, a->capacity);
  ::close(a->fd);
  ::unlink(a->path.c_str());
  {
    std::lock_guard<std::mutex> l(g_mu);
    g_arenas[h] = nullptr;
  }
  delete a;
}

// Allocate space for an object. Returns the offset, or:
//   -1 out of memory (even after eviction), -2 already exists
int64_t rt_create(int h, const char* oid, uint64_t size) {
  Arena* a = arena(h);
  if (!a) return -1;
  std::lock_guard<std::mutex> l(a->mu);
  std::string key(oid);
  if (a->objects.count(key)) return -2;
  uint64_t need = align_up(size == 0 ? 1 : size);
  if (need > a->capacity) return -1;
  int64_t off = alloc_block(a, need);
  if (off < 0) {
    if (!evict_until(a, need)) return -1;
    off = alloc_block(a, need);
    if (off < 0) return -1;
  }
  Entry e;
  e.offset = static_cast<uint64_t>(off);
  e.size = need;
  e.true_size = size;
  e.last_access = ++a->clock;
  a->objects.emplace(std::move(key), e);
  return off;
}

int rt_seal(int h, const char* oid) {
  Arena* a = arena(h);
  if (!a) return -1;
  std::lock_guard<std::mutex> l(a->mu);
  auto it = a->objects.find(oid);
  if (it == a->objects.end()) return -1;
  it->second.sealed = true;
  it->second.last_access = ++a->clock;
  return 0;
}

// Pin + locate. 0 ok, -1 missing, -2 not sealed yet.
int rt_get(int h, const char* oid, uint64_t* offset, uint64_t* size) {
  Arena* a = arena(h);
  if (!a) return -1;
  std::lock_guard<std::mutex> l(a->mu);
  auto it = a->objects.find(oid);
  if (it == a->objects.end()) return -1;
  if (!it->second.sealed) return -2;
  it->second.pins++;
  it->second.last_access = ++a->clock;
  *offset = it->second.offset;
  *size = it->second.size;
  return 0;
}

void rt_release(int h, const char* oid) {
  Arena* a = arena(h);
  if (!a) return;
  std::lock_guard<std::mutex> l(a->mu);
  auto it = a->objects.find(oid);
  if (it != a->objects.end() && it->second.pins > 0) it->second.pins--;
}

void rt_pin_primary(int h, const char* oid) {
  Arena* a = arena(h);
  if (!a) return;
  std::lock_guard<std::mutex> l(a->mu);
  auto it = a->objects.find(oid);
  if (it != a->objects.end()) it->second.primary = true;
}

int rt_contains(int h, const char* oid) {
  Arena* a = arena(h);
  if (!a) return 0;
  std::lock_guard<std::mutex> l(a->mu);
  auto it = a->objects.find(oid);
  return (it != a->objects.end() && it->second.sealed) ? 1 : 0;
}

int rt_free(int h, const char* oid) {
  Arena* a = arena(h);
  if (!a) return -1;
  std::lock_guard<std::mutex> l(a->mu);
  auto it = a->objects.find(oid);
  if (it == a->objects.end()) return -1;
  free_block(a, it->second.offset, it->second.size);
  a->objects.erase(it);
  return 0;
}

// Free only when no reader holds a pin: the spill path must not reallocate
// a block a concurrent get just handed out. 0 freed, -1 missing, -2 pinned.
int rt_free_if_unpinned(int h, const char* oid) {
  Arena* a = arena(h);
  if (!a) return -1;
  std::lock_guard<std::mutex> l(a->mu);
  auto it = a->objects.find(oid);
  if (it == a->objects.end()) return -1;
  if (it->second.pins > 0) return -2;
  free_block(a, it->second.offset, it->second.size);
  a->objects.erase(it);
  return 0;
}

uint64_t rt_used(int h) {
  Arena* a = arena(h);
  if (!a) return 0;
  std::lock_guard<std::mutex> l(a->mu);
  return a->used;
}

uint64_t rt_num_objects(int h) {
  Arena* a = arena(h);
  if (!a) return 0;
  std::lock_guard<std::mutex> l(a->mu);
  return a->objects.size();
}

// True payload size of a sealed object (0 if missing/unsealed).
uint64_t rt_true_size(int h, const char* oid) {
  Arena* a = arena(h);
  if (!a) return 0;
  std::lock_guard<std::mutex> l(a->mu);
  auto it = a->objects.find(oid);
  if (it == a->objects.end() || !it->second.sealed) return 0;
  return it->second.true_size;
}

// LRU spill victim: primary copies are exempt from eviction, so when the
// arena fills with live primaries the raylet spills them to disk instead
// (reference: LocalObjectManager::SpillObjects, local_object_manager.h:115).
// Writes the victim's id into out (NUL-terminated). Returns 1 if found.
int rt_lru_spillable(int h, char* out, int out_len) {
  Arena* a = arena(h);
  if (!a) return 0;
  std::lock_guard<std::mutex> l(a->mu);
  const std::string* victim = nullptr;
  uint64_t oldest = UINT64_MAX;
  for (auto& kv : a->objects) {
    const Entry& e = kv.second;
    if (e.sealed && e.pins == 0 && e.primary && e.last_access < oldest) {
      oldest = e.last_access;
      victim = &kv.first;
    }
  }
  if (victim == nullptr ||
      static_cast<int>(victim->size()) + 1 > out_len)
    return 0;
  std::memcpy(out, victim->c_str(), victim->size() + 1);
  return 1;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Node-to-node object transfer plane.
//
// Role-equivalent of the reference's ObjectManager push/pull data path
// (src/ray/object_manager/object_manager.h:128, pull_manager.h:50,
// push_manager.h:28 — chunked gRPC there; a dedicated TCP stream here,
// which moves the raylet's bulk-byte path out of the Python RPC framing).
//
// Wire protocol (little-endian, same-arch cluster):
//   request : u32 magic "RTX1" | u16 token_len | token | u16 key_len | key
//   response: u8 status (0 ok, 1 not found, 2 auth) | u64 payload_size | raw
//
// The server pins the object (rt_get) for the whole send, so LRU eviction
// and free_if_unpinned cannot reallocate the block mid-stream. The client
// allocates straight into its local arena (rt_create) and streams into the
// mapping — no intermediate userland copies on either side beyond the
// kernel socket buffers.

#include <arpa/inet.h>
#include <atomic>
#include <cerrno>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <sys/socket.h>
#include <thread>

namespace {

constexpr uint32_t kMagic = 0x31585452;  // "RTX1"

struct TransferServer {
  int listen_fd = -1;
  int arena_handle = -1;
  int port = 0;
  std::string token;
  std::thread accept_thread;
  std::atomic<bool> stopping{false};
  // live connection handlers: rt_transfer_stop must not return (and the
  // caller must not munmap the arena) while one is still streaming
  std::atomic<int> active{0};
};

std::mutex g_tmu;
std::vector<TransferServer*> g_tservers;

bool send_all(int fd, const void* buf, size_t len) {
  const uint8_t* p = static_cast<const uint8_t*>(buf);
  while (len > 0) {
    ssize_t n = ::send(fd, p, len, MSG_NOSIGNAL);
    if (n <= 0) return false;
    p += n;
    len -= static_cast<size_t>(n);
  }
  return true;
}

bool recv_all(int fd, void* buf, size_t len) {
  uint8_t* p = static_cast<uint8_t*>(buf);
  while (len > 0) {
    ssize_t n = ::recv(fd, p, len, 0);
    if (n <= 0) return false;
    p += n;
    len -= static_cast<size_t>(n);
  }
  return true;
}

void set_io_timeout(int fd, int seconds) {
  struct timeval tv = {seconds, 0};
  ::setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
  ::setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof(tv));
}

void handle_conn(int fd, TransferServer* s) {
  // accept_loop incremented `active` before spawning us; every exit path
  // must decrement it or rt_transfer_stop spins its full drain backoff
  struct ActiveGuard {
    TransferServer* srv;
    ~ActiveGuard() { srv->active.fetch_sub(1); }
  } guard{s};
  const int arena_handle = s->arena_handle;
  const std::string& token = s->token;
  set_io_timeout(fd, 60);
  int one = 1;
  ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  uint32_t magic = 0;
  uint16_t tlen = 0, klen = 0;
  std::string req_token, key;
  bool ok = recv_all(fd, &magic, 4) && magic == kMagic &&
            recv_all(fd, &tlen, 2) && tlen <= 512;
  if (ok) {
    req_token.resize(tlen);
    ok = (tlen == 0 || recv_all(fd, &req_token[0], tlen)) &&
         recv_all(fd, &klen, 2) && klen > 0 && klen <= 256;
  }
  if (ok) {
    key.resize(klen);
    ok = recv_all(fd, &key[0], klen);
  }
  if (!ok) {
    ::close(fd);
    return;
  }
  uint8_t status;
  uint64_t payload = 0;
  if (req_token != token) {
    status = 2;
    send_all(fd, &status, 1) && send_all(fd, &payload, 8);
    ::close(fd);
    return;
  }
  uint64_t off = 0, padded = 0;
  if (rt_get(arena_handle, key.c_str(), &off, &padded) != 0) {
    status = 1;
    send_all(fd, &status, 1) && send_all(fd, &payload, 8);
    ::close(fd);
    return;
  }
  // pinned from here: stream straight out of the arena mapping
  Arena* a = arena(arena_handle);
  payload = rt_true_size(arena_handle, key.c_str());
  status = 0;
  if (a != nullptr && send_all(fd, &status, 1) && send_all(fd, &payload, 8)) {
    send_all(fd, a->base + off, payload);
  }
  rt_release(arena_handle, key.c_str());
  ::close(fd);
}

void accept_loop(TransferServer* s) {
  while (!s->stopping.load()) {
    int fd = ::accept(s->listen_fd, nullptr, nullptr);
    if (fd < 0) {
      if (s->stopping.load()) return;
      // persistent failure (e.g. EMFILE under fd exhaustion): back off
      // instead of spinning a core
      ::usleep(10000);
      continue;
    }
    // count BEFORE spawning: stop must see the handler even if the thread
    // hasn't started running yet
    s->active.fetch_add(1);
    std::thread(handle_conn, fd, s).detach();
  }
}

}  // namespace

extern "C" {

// Start a transfer server for an open arena. port 0 = ephemeral. Binds the
// given host (the address the raylet itself serves on) — NOT INADDR_ANY:
// the payload plane must never be reachable on interfaces the control
// plane isn't. Null/empty/unparseable host falls back to loopback.
// Returns the bound port (> 0) or -1.
int rt_transfer_serve(int h, const char* token, int port, const char* host) {
  if (arena(h) == nullptr) return -1;
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return -1;
  int one = 1;
  ::setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  if (host == nullptr || host[0] == '\0' ||
      ::inet_pton(AF_INET, host, &addr.sin_addr) != 1) {
    addr.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
  }
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (::bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0 ||
      ::listen(fd, 64) != 0) {
    ::close(fd);
    return -1;
  }
  socklen_t alen = sizeof(addr);
  ::getsockname(fd, reinterpret_cast<sockaddr*>(&addr), &alen);
  TransferServer* s = new TransferServer();
  s->listen_fd = fd;
  s->arena_handle = h;
  s->port = ntohs(addr.sin_port);
  s->token = token ? token : "";
  s->accept_thread = std::thread(accept_loop, s);
  std::lock_guard<std::mutex> l(g_tmu);
  g_tservers.push_back(s);
  return s->port;
}

void rt_transfer_stop(int port) {
  TransferServer* victim = nullptr;
  {
    std::lock_guard<std::mutex> l(g_tmu);
    for (auto*& s : g_tservers) {
      if (s != nullptr && s->port == port) {
        victim = s;
        s = nullptr;
        break;
      }
    }
  }
  if (victim == nullptr) return;
  victim->stopping.store(true);
  ::shutdown(victim->listen_fd, SHUT_RDWR);
  ::close(victim->listen_fd);
  if (victim->accept_thread.joinable()) victim->accept_thread.join();
  // wait for in-flight handlers: the caller munmaps the arena right after
  // this returns. Handler IO timeouts cap each at ~60s; wait a bit longer,
  // then leak the server struct rather than free memory a wedged thread
  // still references.
  for (int i = 0; i < 6500 && victim->active.load() > 0; ++i) {
    ::usleep(10000);
  }
  if (victim->active.load() == 0) delete victim;
}

// Fetch an object from a peer's transfer server straight into the local
// arena. On success writes (offset, true_size) and returns 0. Errors:
//   -1 connect/protocol failure   -2 peer does not have the object
//   -3 local allocation failed    -4 object already present locally
//   -5 peer rejected the auth token
int rt_transfer_fetch(int h, const char* host, int port, const char* oid,
                      const char* token, uint64_t* out_off,
                      uint64_t* out_size) {
  Arena* a = arena(h);
  if (a == nullptr) return -1;
  struct addrinfo hints{}, *res = nullptr;
  hints.ai_family = AF_INET;
  hints.ai_socktype = SOCK_STREAM;
  char portstr[16];
  std::snprintf(portstr, sizeof(portstr), "%d", port);
  if (::getaddrinfo(host, portstr, &hints, &res) != 0 || res == nullptr)
    return -1;
  int fd = ::socket(res->ai_family, res->ai_socktype, res->ai_protocol);
  if (fd < 0) {
    ::freeaddrinfo(res);
    return -1;
  }
  // bounded connect (10s): a stale cached port on a hung host must fail
  // fast so the caller can fall back to the RPC path, not block minutes
  // in the kernel's default connect timeout
  int flags = ::fcntl(fd, F_GETFL, 0);
  ::fcntl(fd, F_SETFL, flags | O_NONBLOCK);
  int crc = ::connect(fd, res->ai_addr, res->ai_addrlen);
  if (crc != 0 && errno == EINPROGRESS) {
    struct pollfd pfd = {fd, POLLOUT, 0};
    int prc = ::poll(&pfd, 1, 10000);
    int soerr = 0;
    socklen_t slen = sizeof(soerr);
    if (prc <= 0 ||
        ::getsockopt(fd, SOL_SOCKET, SO_ERROR, &soerr, &slen) != 0 ||
        soerr != 0)
      crc = -1;
    else
      crc = 0;
  }
  ::fcntl(fd, F_SETFL, flags);
  ::freeaddrinfo(res);
  if (crc != 0) {
    ::close(fd);
    return -1;
  }
  set_io_timeout(fd, 60);
  int one = 1;
  ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  std::string tok = token ? token : "";
  std::string key = oid ? oid : "";
  uint16_t tlen = static_cast<uint16_t>(tok.size());
  uint16_t klen = static_cast<uint16_t>(key.size());
  bool ok = send_all(fd, &kMagic, 4) && send_all(fd, &tlen, 2) &&
            (tlen == 0 || send_all(fd, tok.data(), tlen)) &&
            send_all(fd, &klen, 2) && send_all(fd, key.data(), klen);
  uint8_t status = 0;
  uint64_t payload = 0;
  ok = ok && recv_all(fd, &status, 1) && recv_all(fd, &payload, 8);
  if (!ok) {
    ::close(fd);
    return -1;
  }
  if (status == 1) {
    ::close(fd);
    return -2;
  }
  if (status == 2) {
    ::close(fd);
    return -5;
  }
  int64_t off = rt_create(h, oid, payload);
  if (off == -2) {
    ::close(fd);
    return -4;
  }
  if (off < 0) {
    ::close(fd);
    return -3;
  }
  if (!recv_all(fd, a->base + off, payload)) {
    ::close(fd);
    rt_free(h, oid);
    return -1;
  }
  ::close(fd);
  *out_off = static_cast<uint64_t>(off);
  *out_size = payload;
  return 0;  // caller seals (it also maintains python-side mirrors/waiters)
}

}  // extern "C"
