// C++ frontend for the ray_tpu cluster.
//
// Role-equivalent of the reference's C++ API frontend (cpp/include/ray/api —
// ray::Init / ray::Task(F).Remote()) combined with its cross-language call
// path (python/ray/cross_language.py, msgpack-serialized calls): a C++
// program connects to the ray:// client server, submits a *named Python
// function* with JSON arguments over the cluster's length-prefixed frame
// protocol, and receives a JSON reply. The wire payload is a hand-written
// minimal pickle (protocol 2 writer / subset reader) — the response side is
// parseable because the server's xlang handler always replies with a plain
// (int, bool, str) tuple.
//
// Build (see build.py):
//   g++ -std=c++17 -O2 -o ray_tpu_xlang xlang_client.cc -DRAY_TPU_XLANG_MAIN
//   g++ -std=c++17 -O2 -shared -fPIC -o libray_tpu_xlang.so xlang_client.cc

#include <arpa/inet.h>
#include <netinet/in.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cerrno>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <stdexcept>
#include <string>
#include <vector>

namespace ray_tpu {

// ---------------------------------------------------------------------------
// Minimal pickle protocol-2 writer (requests are fully under our control).
// ---------------------------------------------------------------------------

class Pickler {
 public:
  Pickler() { buf_ += "\x80\x02"; }  // PROTO 2

  void Mark() { buf_ += '('; }
  void TupleFromMark() { buf_ += 't'; }
  void None() { buf_ += 'N'; }
  void EmptyDict() { buf_ += '}'; }
  void SetItemsFromMark() { buf_ += 'u'; }

  void Int(int64_t v) {
    // BININT (i32) covers request ids and sizes we use
    buf_ += 'J';
    AppendLE32(static_cast<uint32_t>(static_cast<int32_t>(v)));
  }

  void Str(const std::string& s) {
    buf_ += 'X';  // BINUNICODE, u32 length
    AppendLE32(static_cast<uint32_t>(s.size()));
    buf_ += s;
  }

  void Double(double v) {
    buf_ += 'G';  // BINFLOAT, big-endian IEEE 754
    uint64_t bits;
    std::memcpy(&bits, &v, 8);
    for (int i = 7; i >= 0; --i)
      buf_ += static_cast<char>((bits >> (i * 8)) & 0xff);
  }

  std::string Finish() {
    std::string out = buf_;
    out += '.';  // STOP
    return out;
  }

 private:
  void AppendLE32(uint32_t v) {
    for (int i = 0; i < 4; ++i) buf_ += static_cast<char>((v >> (i * 8)) & 0xff);
  }
  std::string buf_;
};

// ---------------------------------------------------------------------------
// Minimal pickle reader for responses shaped (int, bool, str|None).
// Handles the opcode subset CPython's pickler emits for that tuple at any
// protocol <= 5 (PROTO/FRAME/MEMOIZE wrappers included).
// ---------------------------------------------------------------------------

struct Value {
  enum class Kind { kNone, kBool, kInt, kStr, kTuple } kind = Kind::kNone;
  bool b = false;
  int64_t i = 0;
  std::string s;
  std::vector<Value> items;
};

class Unpickler {
 public:
  explicit Unpickler(const std::string& data) : data_(data) {}

  Value Parse() {
    size_t pos = 0;
    std::vector<Value> stack;
    std::vector<size_t> marks;
    while (pos < data_.size()) {
      uint8_t op = static_cast<uint8_t>(data_[pos++]);
      switch (op) {
        case 0x80:  // PROTO
          pos += 1;
          break;
        case 0x95:  // FRAME (8-byte length)
          pos += 8;
          break;
        case 0x94:  // MEMOIZE — ignore the memo
          break;
        case 'q':  // BINPUT (1-byte memo index)
          pos += 1;
          break;
        case 'r':  // LONG_BINPUT
          pos += 4;
          break;
        case 'N':
          stack.push_back(Value{});
          break;
        case 0x88: {  // NEWTRUE
          Value v; v.kind = Value::Kind::kBool; v.b = true;
          stack.push_back(v);
          break;
        }
        case 0x89: {  // NEWFALSE
          Value v; v.kind = Value::Kind::kBool; v.b = false;
          stack.push_back(v);
          break;
        }
        case 'K': {  // BININT1
          Value v; v.kind = Value::Kind::kInt;
          v.i = static_cast<uint8_t>(data_[pos++]);
          stack.push_back(v);
          break;
        }
        case 'M': {  // BININT2
          Value v; v.kind = Value::Kind::kInt;
          v.i = ReadLE(pos, 2); pos += 2;
          stack.push_back(v);
          break;
        }
        case 'J': {  // BININT (signed i32)
          Value v; v.kind = Value::Kind::kInt;
          v.i = static_cast<int32_t>(ReadLE(pos, 4)); pos += 4;
          stack.push_back(v);
          break;
        }
        case 0x8c: {  // SHORT_BINUNICODE
          size_t n = static_cast<uint8_t>(data_[pos++]);
          PushStr(stack, pos, n);
          break;
        }
        case 'X': {  // BINUNICODE (u32)
          size_t n = ReadLE(pos, 4); pos += 4;
          PushStr(stack, pos, n);
          break;
        }
        case 0x8d: {  // BINUNICODE8
          size_t n = static_cast<size_t>(ReadLE(pos, 8)); pos += 8;
          PushStr(stack, pos, n);
          break;
        }
        case '(':  // MARK
          marks.push_back(stack.size());
          break;
        case 't': {  // TUPLE (from mark)
          size_t m = marks.back(); marks.pop_back();
          Value v; v.kind = Value::Kind::kTuple;
          v.items.assign(stack.begin() + m, stack.end());
          stack.resize(m);
          stack.push_back(v);
          break;
        }
        case 0x85: case 0x86: case 0x87: {  // TUPLE1..TUPLE3
          size_t n = op - 0x84;
          Value v; v.kind = Value::Kind::kTuple;
          v.items.assign(stack.end() - n, stack.end());
          stack.resize(stack.size() - n);
          stack.push_back(v);
          break;
        }
        case '.':  // STOP
          if (stack.empty()) throw std::runtime_error("pickle: empty stack");
          return stack.back();
        default:
          throw std::runtime_error(
              "pickle: unsupported opcode 0x" + ToHex(op) +
              " (server reply was not a plain (int, bool, str) tuple)");
      }
    }
    throw std::runtime_error("pickle: no STOP");
  }

 private:
  uint64_t ReadLE(size_t pos, int n) {
    uint64_t v = 0;
    for (int i = 0; i < n; ++i)
      v |= static_cast<uint64_t>(static_cast<uint8_t>(data_[pos + i])) << (i * 8);
    return v;
  }
  void PushStr(std::vector<Value>& stack, size_t& pos, size_t n) {
    Value v; v.kind = Value::Kind::kStr;
    v.s = data_.substr(pos, n); pos += n;
    stack.push_back(v);
  }
  static std::string ToHex(uint8_t b) {
    const char* d = "0123456789abcdef";
    return std::string() + d[b >> 4] + d[b & 0xf];
  }
  const std::string& data_;
};

// ---------------------------------------------------------------------------
// Client
// ---------------------------------------------------------------------------

class XlangClient {
 public:
  XlangClient(const std::string& host, int port, const std::string& auth_token = "")
      : fd_(-1) {
    fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
    if (fd_ < 0) throw std::runtime_error("socket() failed");
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons(static_cast<uint16_t>(port));
    if (::inet_pton(AF_INET, host.c_str(), &addr.sin_addr) != 1)
      throw std::runtime_error("bad host " + host);
    if (::connect(fd_, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0)
      throw std::runtime_error("connect to " + host + " failed");
    if (!auth_token.empty()) {
      SendAuthPreamble(auth_token);
      Register(auth_token);
    }
  }

  ~XlangClient() {
    if (fd_ >= 0) ::close(fd_);
  }

  // Submit module.qualname(*json.loads(args_json)) as a cluster task; the
  // reply is the server's JSON envelope {"ok": ..., "value"/"error": ...}.
  std::string Call(const std::string& module, const std::string& qualname,
                   const std::string& args_json, double timeout_s = 120.0) {
    SetRecvTimeout(timeout_s);
    int req_id = next_req_id_++;
    Pickler p;
    p.Mark();
    p.Int(req_id);
    p.Str("xlang_task");
    p.Mark();
    p.Str(module);
    p.Str(qualname);
    p.Str(args_json);
    p.TupleFromMark();
    p.EmptyDict();
    p.TupleFromMark();
    WriteFrame(p.Finish());

    while (true) {
      Value reply = Unpickler(ReadFrame()).Parse();
      if (reply.kind != Value::Kind::kTuple || reply.items.size() != 3)
        throw std::runtime_error("malformed reply frame");
      if (reply.items[0].i != req_id) continue;  // not ours (multiplexing)
      if (reply.items[1].kind == Value::Kind::kBool && !reply.items[1].b)
        throw std::runtime_error("server error (see server logs)");
      return reply.items[2].s;
    }
  }

 private:
  // Pre-pickle handshake: servers with auth enabled read [magic]["RTA1"]
  // [u32le len][token] as the connection's first bytes, BEFORE parsing any
  // pickle frame (mirrors _check_auth_preamble in _internal/rpc.py).
  void SendAuthPreamble(const std::string& token) {
    SendAll("RTA1", 4);
    uint32_t n = static_cast<uint32_t>(token.size());
    char hdr[4];
    for (int i = 0; i < 4; ++i) hdr[i] = static_cast<char>((n >> (i * 8)) & 0xff);
    SendAll(hdr, 4);
    SendAll(token.data(), token.size());
  }

  void Register(const std::string& token) {
    Pickler p;
    p.Mark();
    p.Int(-1);
    p.Str("__register__");
    p.Mark();
    p.TupleFromMark();
    p.EmptyDict();
    p.Mark();
    p.Str("auth_token");
    p.Str(token);
    p.SetItemsFromMark();
    p.TupleFromMark();
    WriteFrame(p.Finish());
  }

  void WriteFrame(const std::string& payload) {
    uint32_t n = static_cast<uint32_t>(payload.size());
    char hdr[4];
    for (int i = 0; i < 4; ++i) hdr[i] = static_cast<char>((n >> (i * 8)) & 0xff);
    SendAll(hdr, 4);
    SendAll(payload.data(), payload.size());
  }

  std::string ReadFrame() {
    char hdr[4];
    RecvAll(hdr, 4);
    uint32_t n = 0;
    for (int i = 0; i < 4; ++i)
      n |= static_cast<uint32_t>(static_cast<uint8_t>(hdr[i])) << (i * 8);
    std::string body(n, '\0');
    RecvAll(&body[0], n);
    return body;
  }

  void SendAll(const char* p, size_t n) {
    while (n > 0) {
      ssize_t w = ::send(fd_, p, n, 0);
      if (w <= 0) throw std::runtime_error("send failed");
      p += w;
      n -= static_cast<size_t>(w);
    }
  }

  void SetRecvTimeout(double timeout_s) {
    timeval tv{};
    tv.tv_sec = static_cast<long>(timeout_s);
    tv.tv_usec = static_cast<long>((timeout_s - tv.tv_sec) * 1e6);
    ::setsockopt(fd_, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
  }

  void RecvAll(char* p, size_t n) {
    while (n > 0) {
      ssize_t r = ::recv(fd_, p, n, 0);
      if (r < 0 && (errno == EAGAIN || errno == EWOULDBLOCK))
        throw std::runtime_error("recv timed out");
      if (r <= 0) throw std::runtime_error("connection closed");
      p += r;
      n -= static_cast<size_t>(r);
    }
  }

  int fd_;
  int next_req_id_ = 1;
};

}  // namespace ray_tpu

// -- C ABI for ctypes bindings ----------------------------------------------

extern "C" {

void* ray_tpu_xlang_connect(const char* host, int port, const char* token) {
  try {
    return new ray_tpu::XlangClient(host, port, token ? token : "");
  } catch (...) {
    return nullptr;
  }
}

// Returns a malloc'd C string the caller must free(); nullptr on error.
char* ray_tpu_xlang_call(void* client, const char* module, const char* fn,
                         const char* args_json) {
  try {
    auto* c = static_cast<ray_tpu::XlangClient*>(client);
    std::string out = c->Call(module, fn, args_json);
    char* buf = static_cast<char*>(::malloc(out.size() + 1));
    std::memcpy(buf, out.c_str(), out.size() + 1);
    return buf;
  } catch (...) {
    return nullptr;
  }
}

void ray_tpu_xlang_disconnect(void* client) {
  delete static_cast<ray_tpu::XlangClient*>(client);
}

}  // extern "C"

#ifdef RAY_TPU_XLANG_MAIN
#include <cstdio>

int main(int argc, char** argv) {
  if (argc < 6) {
    std::fprintf(
        stderr,
        "usage: %s <host> <port> <module> <function> <args_json>\n"
        "       (auth token read from RAY_TPU_CLUSTER_AUTH_TOKEN — env only:\n"
        "        argv is world-readable via /proc/<pid>/cmdline)\n",
        argv[0]);
    return 2;
  }
  try {
    const char* env_token = std::getenv("RAY_TPU_CLUSTER_AUTH_TOKEN");
    std::string token = env_token ? env_token : "";
    ray_tpu::XlangClient client(argv[1], std::atoi(argv[2]), token);
    std::string out = client.Call(argv[3], argv[4], argv[5]);
    std::printf("%s\n", out.c_str());
    return 0;
  } catch (const std::exception& e) {
    std::fprintf(stderr, "error: %s\n", e.what());
    return 1;
  }
}
#endif
