"""ctypes binding for the native store (reference role: plasma client.h).

``load()`` builds (if needed) and loads libray_tpu_store.so; returns None
when no C++ toolchain is available so callers can fall back to the pure-
Python store.
"""

from __future__ import annotations

import ctypes
import logging
from typing import Optional

logger = logging.getLogger(__name__)

_lib = None
_load_failed = False


def load() -> Optional[ctypes.CDLL]:
    global _lib, _load_failed
    if _lib is not None:
        return _lib
    if _load_failed:
        return None
    try:
        from .build import build

        path = build()
        lib = ctypes.CDLL(path)
    except Exception as e:  # toolchain missing / build failure
        logger.warning("native store unavailable, using python store: %s", e)
        _load_failed = True
        return None
    lib.rt_store_open.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
    lib.rt_store_open.restype = ctypes.c_int
    lib.rt_store_close.argtypes = [ctypes.c_int]
    lib.rt_create.argtypes = [ctypes.c_int, ctypes.c_char_p, ctypes.c_uint64]
    lib.rt_create.restype = ctypes.c_int64
    lib.rt_seal.argtypes = [ctypes.c_int, ctypes.c_char_p]
    lib.rt_seal.restype = ctypes.c_int
    lib.rt_get.argtypes = [
        ctypes.c_int,
        ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_uint64),
        ctypes.POINTER(ctypes.c_uint64),
    ]
    lib.rt_get.restype = ctypes.c_int
    lib.rt_release.argtypes = [ctypes.c_int, ctypes.c_char_p]
    lib.rt_pin_primary.argtypes = [ctypes.c_int, ctypes.c_char_p]
    lib.rt_contains.argtypes = [ctypes.c_int, ctypes.c_char_p]
    lib.rt_contains.restype = ctypes.c_int
    lib.rt_free.argtypes = [ctypes.c_int, ctypes.c_char_p]
    lib.rt_free.restype = ctypes.c_int
    lib.rt_free_if_unpinned.argtypes = [ctypes.c_int, ctypes.c_char_p]
    lib.rt_free_if_unpinned.restype = ctypes.c_int
    lib.rt_used.argtypes = [ctypes.c_int]
    lib.rt_used.restype = ctypes.c_uint64
    lib.rt_num_objects.argtypes = [ctypes.c_int]
    lib.rt_num_objects.restype = ctypes.c_uint64
    lib.rt_lru_spillable.argtypes = [
        ctypes.c_int,
        ctypes.c_char_p,
        ctypes.c_int,
    ]
    lib.rt_lru_spillable.restype = ctypes.c_int
    lib.rt_true_size.argtypes = [ctypes.c_int, ctypes.c_char_p]
    lib.rt_true_size.restype = ctypes.c_uint64
    lib.rt_transfer_serve.argtypes = [
        ctypes.c_int,
        ctypes.c_char_p,
        ctypes.c_int,
        ctypes.c_char_p,
    ]
    lib.rt_transfer_serve.restype = ctypes.c_int
    lib.rt_transfer_stop.argtypes = [ctypes.c_int]
    lib.rt_transfer_fetch.argtypes = [
        ctypes.c_int,
        ctypes.c_char_p,
        ctypes.c_int,
        ctypes.c_char_p,
        ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_uint64),
        ctypes.POINTER(ctypes.c_uint64),
    ]
    lib.rt_transfer_fetch.restype = ctypes.c_int
    _lib = lib
    return _lib
