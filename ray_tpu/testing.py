"""Chaos/fault-injection test utilities.

Role-equivalent of the reference's test harness killers
(_private/test_utils.py:1372,1458,1606 — ResourceKillerActor,
NodeKillerBase, WorkerKillerActor) adapted to the in-process cluster: a
background thread SIGKILLs random busy workers (or removes whole nodes from
a cluster_utils.Cluster) at an interval, while the workload runs — retries,
actor restarts, and lineage reconstruction must absorb the damage. RPC-level
chaos is separate (``_system_config={"testing_rpc_failure": ...}``,
_internal/rpc.py set_rpc_chaos).
"""

from __future__ import annotations

import logging
import os
import random
import signal
import threading
import time
from typing import List, Optional

logger = logging.getLogger(__name__)


class WorkerKiller:
    """Kills random registered (busy-or-idle) worker processes of the given
    nodes' raylets until stopped or ``max_kills`` is reached."""

    def __init__(
        self,
        nodes,
        interval_s: float = 0.5,
        max_kills: int = 5,
        seed: int = 0,
        busy_only: bool = True,
    ):
        self._nodes = list(nodes)
        self._interval = interval_s
        self._max_kills = max_kills
        self._rng = random.Random(seed)
        self._busy_only = busy_only
        self.kills: List[int] = []  # pids killed
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _candidates(self) -> List[int]:
        # snapshot with list(): the raylet loop thread mutates these dicts
        # concurrently (that churn is exactly what this killer causes)
        pids = []
        for node in self._nodes:
            raylet = node.raylet
            if self._busy_only:
                pids.extend(
                    lease.worker.pid
                    for lease in list(raylet._leases.values())
                )
            elif raylet.worker_pool is not None:
                pids.extend(
                    h.pid
                    for h in list(raylet.worker_pool._registered.values())
                )
        return pids

    def _run(self):
        while not self._stop.is_set() and len(self.kills) < self._max_kills:
            # event-based wait: stop() during the interval must prevent the
            # kill that would otherwise land after the chaos window closed
            if self._stop.wait(self._interval):
                return
            try:
                pids = self._candidates()
                if not pids:
                    continue
                pid = self._rng.choice(pids)
                os.kill(pid, signal.SIGKILL)
                self.kills.append(pid)
                logger.info("WorkerKiller: killed worker pid %s", pid)
            except ProcessLookupError:
                pass
            except Exception:
                # a racing snapshot must not silently end the chaos thread
                logger.exception("WorkerKiller tick failed; continuing")

    def start(self) -> "WorkerKiller":
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="worker-killer"
        )
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()


class KillWorkerAtStep:
    """Deterministic train-chaos injector: SIGKILL the train worker holding
    ``rank`` the first time any rank reports index >= ``step``.

    Duck-typed TrainCallback (all five controller hooks present, no import
    of ray_tpu.train at module scope): pass it in ``RunConfig.callbacks``.
    The kill is delivered from the controller process to the worker's OS
    pid, exactly like a chip/host loss — the raylet notices the connection
    drop, reports the death to the GCS, and the GCS aborts the rank's
    collective group so survivors unblock.

        RunConfig(failure_config=FailureConfig(elastic=True),
                  callbacks=[KillWorkerAtStep(rank=3, step=2)])
    """

    def __init__(self, rank: int, step: int, max_kills: int = 1):
        self.rank = rank
        self.step = step
        self.max_kills = max_kills
        self.kills: List[dict] = []  # {"rank", "pid", "at_report"}
        self._wg = None

    def before_worker_group_start(self, scaling_config):
        return None

    def after_worker_group_start(self, worker_group):
        self._wg = worker_group

    def on_report(self, report):
        if (
            len(self.kills) >= self.max_kills
            or self._wg is None
            or report.index < self.step
        ):
            return
        for w in self._wg.workers:
            if w.world_rank == self.rank:
                pid = w.metadata.get("pid")
                if not pid:
                    return
                try:
                    os.kill(pid, signal.SIGKILL)
                except ProcessLookupError:
                    return
                self.kills.append(
                    {"rank": self.rank, "pid": pid, "at_report": report.index}
                )
                logger.info(
                    "KillWorkerAtStep: killed rank %d (pid %d) at report %d",
                    self.rank, pid, report.index,
                )
                return

    def before_worker_group_shutdown(self, worker_group):
        pass

    def after_run(self, result):
        pass


def list_serve_replicas(app_name: str = "default"):
    """Replica inventory rows ({deployment, replica_id, state, pid,
    queue_len}) from the live serve controller (None if no controller)."""
    from . import api
    from .serve.controller import CONTROLLER_NAME

    try:
        controller = api.get_actor(CONTROLLER_NAME)
    except Exception:
        return []
    try:
        return api.get(
            controller.list_replica_info.remote(app_name), timeout=10
        )
    except Exception:
        return []


def kill_serve_replica(app_name: str = "default",
                       deployment: Optional[str] = None,
                       replica_id: Optional[str] = None,
                       sig: int = signal.SIGKILL):
    """Serve-chaos primitive: SIGKILL (or SIGSTOP, for a pause) one replica
    process of the app, exactly like losing its host — the controller's
    health poll replaces it and in-flight requests fail over through the
    handle's retry envelope. Picks the first RUNNING replica matching the
    filters; returns (replica_id, pid) or (None, None) when nothing
    matched (no replica up yet, or pid not yet polled)."""
    for row in list_serve_replicas(app_name):
        if row.get("state") != "RUNNING" or not row.get("pid"):
            continue
        if deployment is not None and row["deployment"] != deployment:
            continue
        if replica_id is not None and row["replica_id"] != replica_id:
            continue
        pid = row["pid"]
        try:
            os.kill(pid, sig)
        except ProcessLookupError:
            continue
        logger.info(
            "kill_serve_replica: sent signal %s to replica %s (pid %d)",
            sig, row["replica_id"], pid,
        )
        return row["replica_id"], pid
    return None, None


def kill_serve_proxy(proxy_id: Optional[str] = None,
                     sig: int = signal.SIGKILL):
    """Ingress-chaos primitive: SIGKILL one proxy process from the GCS
    proxy registry — like losing a front-end host. Surviving proxies on
    the shared SO_REUSEPORT listener keep accepting; the controller's
    health poll deregisters the corpse. Returns (proxy_id, pid) or
    (None, None) when nothing matched."""
    from .util.state import list_proxies

    for row in list_proxies():
        if proxy_id is not None and row.get("proxy_id") != proxy_id:
            continue
        pid = row.get("pid")
        if not pid:
            continue
        try:
            os.kill(pid, sig)
        except ProcessLookupError:
            continue
        logger.info(
            "kill_serve_proxy: sent signal %s to proxy %s (pid %d)",
            sig, row.get("proxy_id"), pid,
        )
        return row.get("proxy_id"), pid
    return None, None


def _gcs_kv(method, *args):
    from . import _worker_api

    worker = _worker_api.get_core_worker()
    client = worker.client_pool.get(*worker.gcs_address)
    return _worker_api.run_on_worker_loop(
        client.call(method, *args, timeout=10.0)
    )


def set_network_chaos(spec: dict):
    """Network-chaos primitive: publish a structured chaos-mesh spec (see
    ``_internal.rpc.set_rpc_chaos``) to the GCS KV so every process in the
    cluster — raylets, workers, drivers — applies it within ~1 poll period.
    The programmatic twin of ``ray_tpu chaos net``."""
    import json as _json

    from .runtime.gcs import keys as gcs_keys

    _gcs_kv(
        "kv_put", gcs_keys.CHAOS_NET_SPEC,
        _json.dumps(spec).encode(), True,
    )


def clear_network_chaos():
    """Remove the cluster chaos-mesh spec; every process heals (reverts to
    no injected faults) within ~1 poll period."""
    from .runtime.gcs import keys as gcs_keys

    _gcs_kv("kv_del", gcs_keys.CHAOS_NET_SPEC)


class NodeKiller:
    """Removes random non-head nodes from a cluster_utils.Cluster at an
    interval (reference: NodeKillerBase killing raylets during chaos
    tests)."""

    def __init__(self, cluster, interval_s: float = 1.0, max_kills: int = 1,
                 seed: int = 0):
        self._cluster = cluster
        self._interval = interval_s
        self._max_kills = max_kills
        self._rng = random.Random(seed)
        self.killed: List[str] = []  # node id hexes
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _run(self):
        while not self._stop.is_set() and len(self.killed) < self._max_kills:
            if self._stop.wait(self._interval):
                return
            victims = [
                n for n in self._cluster.list_nodes() if not n.head
            ]
            if not victims:
                continue
            node = self._rng.choice(victims)
            node_id = node.node_id.hex()
            try:
                self._cluster.remove_node(node, graceful=False)
                self.killed.append(node_id)
                logger.info("NodeKiller: removed node %s", node_id)
            except Exception:
                logger.exception("NodeKiller: removal failed")

    def start(self) -> "NodeKiller":
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="node-killer"
        )
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
