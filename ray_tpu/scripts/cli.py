"""CLI: ``python -m ray_tpu.scripts.cli <command>``.

Role-equivalent of the reference's ray CLI (python/ray/scripts/scripts.py —
ray start :684 / stop :1227 / status, plus `ray list ...` from the state
CLI util/state/state_cli.py). ``start --head`` runs a standalone head node
(GCS + raylet) that remote drivers join with
``ray_tpu.init(address="host:port")``; ``start --address`` joins an
existing head as a worker node.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import time


def cmd_start(args):
    from .._internal.config import Config
    from ..runtime.node import Node

    resources = json.loads(args.resources) if args.resources else {}
    if args.num_cpus is not None:
        resources["CPU"] = float(args.num_cpus)
    if args.num_tpus is not None:
        resources["TPU"] = float(args.num_tpus)
    labels = json.loads(args.labels) if args.labels else {}

    config = Config()
    if args.head:
        config.client_server_port = args.ray_client_server_port
        config.client_server_host = args.ray_client_server_host
        node = Node(
            config,
            head=True,
            resources=resources,
            labels=labels,
            object_store_memory=args.object_store_memory,
        )
        host, port = node.gcs_address
        print(f"ray_tpu head started; connect with:")
        print(f'  ray_tpu.init(address="{host}:{port}")')
        if node.client_server is not None:
            chost, cport = node.client_server.address
            print(f'  ray_tpu.init(address="ray://{chost}:{cport}")  # client mode')
        if not args.no_dashboard:
            from ..dashboard import DashboardServer

            dash = DashboardServer(
                node.gcs_address, port=args.dashboard_port
            )
            dash.start()
            print(f"dashboard + job API at {dash.url}")
    else:
        if not args.address:
            print("worker nodes need --address host:port", file=sys.stderr)
            return 1
        host, port = args.address.rsplit(":", 1)
        node = Node(
            config,
            head=False,
            gcs_address=(host, int(port)),
            resources=resources,
            labels=labels,
            object_store_memory=args.object_store_memory,
        )
        print(f"ray_tpu node joined {args.address}")
    if args.block:
        stop = []
        signal.signal(signal.SIGTERM, lambda *_: stop.append(1))
        signal.signal(signal.SIGINT, lambda *_: stop.append(1))
        while not stop:
            time.sleep(0.5)
        node.stop()
        return 0
    print(f"(pid {os.getpid()} keeps the node alive; kill it to stop)")
    while True:  # non-daemonized v1: block regardless
        time.sleep(3600)


def cmd_stop(args):
    """Stop all local ray_tpu processes (reference: `ray stop` — scans for
    ray process cmdlines and terminates them)."""
    me = os.getpid()
    # exact argv-token matching (NUL-split), not substring over the joined
    # line: `grep worker_main ...` or an editor on that path must survive
    ray_modules = {
        "ray_tpu.scripts.cli", "ray_tpu.runtime.worker.worker_main",
    }
    killed = []
    for pid_dir in os.listdir("/proc"):
        if not pid_dir.isdigit() or int(pid_dir) == me:
            continue
        try:
            with open(f"/proc/{pid_dir}/cmdline", "rb") as f:
                argv = [
                    a.decode(errors="replace")
                    for a in f.read().split(b"\0") if a
                ]
        except OSError:
            continue
        is_ours = False
        for i, tok in enumerate(argv):
            if tok == "-m" and i + 1 < len(argv) and argv[i + 1] in ray_modules:
                # `cli` only counts when it is a `start` invocation
                if argv[i + 1].endswith("worker_main") or "start" in argv[i + 2 : i + 3]:
                    is_ours = True
                break
        if is_ours:
            try:
                os.kill(int(pid_dir), signal.SIGTERM)
                killed.append(int(pid_dir))
            except OSError:
                pass
    print(f"stopped {len(killed)} process(es): {killed}")
    return 0


def _connected(args):
    import ray_tpu

    # reuse a live driver when one exists in-process (tests drive commands
    # through main() against their own cluster)
    ray_tpu.init(address=args.address, ignore_reinit_error=True)
    return ray_tpu


def cmd_microbenchmark(args):
    from .._internal.perf import (
        json_results,
        print_results,
        run_microbenchmarks,
    )

    results = run_microbenchmarks(small=args.small)
    if getattr(args, "json", False):
        print(json_results(results))
    else:
        print_results(results)
    return 0


def cmd_status(args):
    _connected(args)
    from ..util import state

    summary = state.cluster_summary()
    print(json.dumps(summary, indent=2, default=str))
    return 0


def cmd_list(args):
    _connected(args)
    from ..util import state

    fn = {
        "nodes": state.list_nodes,
        "actors": state.list_actors,
        "tasks": state.list_tasks,
        "jobs": state.list_jobs,
        "placement-groups": state.list_placement_groups,
        "objects": state.list_objects,
        "weights": state.list_weights,
        "replicas": state.list_replicas,
    }[args.what]
    rows = fn()
    print(json.dumps(rows, indent=2, default=str))
    return 0


def cmd_logs(args):
    _connected(args)
    from ..util import state

    if args.filename:
        print(state.get_log(args.filename, node_id=args.node_id, tail=args.tail))
    else:
        print(json.dumps(state.list_logs(node_id=args.node_id), indent=2))
    return 0


def cmd_debug(args):
    _connected(args)
    from ..util import debug

    if not args.session:
        sessions = debug.list_sessions()
        if not sessions:
            print("no active debug sessions")
        else:
            for sid, info in sessions.items():
                print(
                    f"{sid}  pid={info.get('pid')}  {info.get('host')}:"
                    f"{info.get('port')}  {info.get('reason')}  "
                    f"task={info.get('task_id')}"
                )
        return 0
    if not debug.attach(args.session):
        print(f"unknown debug session: {args.session}", file=sys.stderr)
        return 1
    return 0


def cmd_summary(args):
    _connected(args)
    from ..util import state

    print(json.dumps(state.summarize_tasks(), indent=2))
    return 0


def cmd_metrics(args):
    _connected(args)
    if getattr(args, "summary", False):
        from ..util import state

        print(json.dumps(state.metrics_summary(), indent=2, default=str))
        return 0
    from ..util.metrics import prometheus_text

    print(prometheus_text())
    return 0


def cmd_kvcache(args):
    """`ray_tpu kvcache`: cluster-wide KV-cache plane stats — prefix-hit
    vs computed prefill tokens, block pool occupancy, evictions,
    admission backpressure, and TTFT by hit/miss (state API rollup of the
    `kvcache_*` metrics every paged engine pushes)."""
    _connected(args)
    from ..util import state

    print(json.dumps(state.metrics_summary()["kvcache"], indent=2, default=str))
    return 0


def cmd_kvtier(args):
    """`ray_tpu kvtier`: cluster KV-tier stats — resolution outcomes
    (hit / peer_pull / recompute), logical vs wire transfer bytes (the
    int8 shipment codec's compression split), and TTFT by tier
    (local / peer / miss) read off the kvcache histogram's tier tag."""
    _connected(args)
    from ..util import state

    print(json.dumps(state.metrics_summary()["kvtier"], indent=2, default=str))
    return 0


def cmd_adapters(args):
    """`ray_tpu adapters`: the multi-tenant LoRA adapter plane — lease
    hit rate vs cold attaches (is max_live sized right?), LRU evictions
    (thrash indicator), live slots, and cold-attach latency percentiles
    (the TTFT tax of a tenant's first request on a replica)."""
    _connected(args)
    from ..util import state

    print(json.dumps(
        state.metrics_summary()["adapters"], indent=2, default=str
    ))
    return 0


def cmd_autoscale(args):
    """`ray_tpu autoscale`: the SLO autoscaler's decision record.

    - ``log``: most recent scale-up/down decision events (direction,
      replica counts, triggering reasons, breach age, the signal snapshot
      at decision time) from the controller's GCS KV mirror.
    - ``status``: cluster rollup of the ``autoscale_*`` metrics —
      scale-up/down totals per deployment and decision-latency quantiles.
    """
    _connected(args)
    from ..util import state

    if args.autoscale_action == "log":
        print(json.dumps(
            state.autoscale_log(limit=args.limit), indent=2, default=str
        ))
    else:
        print(json.dumps(
            state.metrics_summary()["autoscale"], indent=2, default=str
        ))
    return 0


def cmd_events(args):
    """`ray_tpu events`: the cluster flight recorder — structured events
    (replica state transitions, autoscale decisions, collective epochs,
    admission blocks, retries, watchdog stack captures) streamed by every
    process into the GCS event store. Works post-mortem: a SIGKILLed
    process's last ~second of events is already in the store."""
    _connected(args)
    from ..util import state

    print(json.dumps(
        state.list_events(
            limit=args.limit, name=args.name,
            since=getattr(args, "since", None),
        ),
        indent=2, default=str,
    ))
    return 0


def cmd_top(args):
    """`ray_tpu top`: live per-worker training table, sorted by step-time
    deviation from the group median — the straggler hunt's first screen.
    Rows come from the GCS timeseries store's MAD verdicts; ``--watch``
    refreshes until interrupted."""
    _connected(args)
    import time as _time

    from ..util import state

    def _render():
        rows = state.straggler_verdicts()
        if getattr(args, "json", False):
            print(json.dumps(rows, indent=2, default=str))
            return
        if not rows:
            print("no step-time series yet (is a training run reporting?)")
            return
        header = (
            f"{'GROUP':<14} {'RANK':>4} {'WORKER':<14} {'STEP s':>9} "
            f"{'GROUP s':>9} {'DEV %':>8}  STATUS"
        )
        print(header)
        for v in rows:
            print(
                f"{str(v.get('group') or '?')[:14]:<14} "
                f"{str(v.get('rank') if v.get('rank') is not None else '?'):>4} "
                f"{str(v.get('worker_id') or '')[:14]:<14} "
                f"{v.get('median_s', 0.0):>9.4f} "
                f"{v.get('group_median_s', 0.0):>9.4f} "
                f"{100.0 * v.get('deviation', 0.0):>8.1f}  "
                f"{'STRAGGLER' if v.get('straggler') else 'ok'}"
            )

    if getattr(args, "watch", False):
        try:
            while True:
                print(f"\n-- {_time.strftime('%H:%M:%S')} --")
                _render()
                _time.sleep(args.interval)
        except KeyboardInterrupt:
            return 0
    else:
        _render()
    return 0


def cmd_alerts(args):
    """`ray_tpu alerts`: the alerting engine's surface — active alerts,
    declared rules, recent firing/resolved transitions, and straggler
    verdicts, straight off the GCS ``alerts_snapshot`` RPC. ``--events``
    tails the alert/straggler flight-recorder stream instead;
    ``--set-rule`` / ``--delete-rule`` manage the rule registry."""
    _connected(args)
    from ..util import state

    if getattr(args, "set_rule", None):
        rule = json.loads(args.set_rule)
        print(json.dumps(state.set_alert_rule(rule), indent=2, default=str))
        return 0
    if getattr(args, "delete_rule", None):
        ok = state.delete_alert_rule(args.delete_rule)
        print(json.dumps({"deleted": ok}))
        return 0 if ok else 1
    if getattr(args, "events", False):
        out = []
        for name in (
            "alert_firing", "alert_resolved",
            "straggler_detected", "straggler_resolved",
        ):
            out.extend(state.list_events(
                limit=args.limit, name=name,
                since=getattr(args, "since", None),
            ))
        out.sort(key=lambda e: e.get("ts", 0))
        print(json.dumps(out[-args.limit:], indent=2, default=str))
        return 0
    snapshot = state.alerts_snapshot()
    if getattr(args, "rules", False):
        print(json.dumps(snapshot["rules"], indent=2, default=str))
        return 0
    print(json.dumps(snapshot, indent=2, default=str))
    return 0


def cmd_proxies(args):
    """`ray_tpu proxies`: the ingress data plane — live proxy registry
    (``proxy:*`` GCS records: kind, host:port, pid, node) joined with the
    per-proxy traffic rollup (requests by outcome, inflight, latency
    p50/p99) from the pushed metrics plane."""
    _connected(args)
    from ..util import state
    from ..util.metrics import fetch_metric_payloads, ingress_summary

    proxies = state.list_proxies()
    try:
        traffic = ingress_summary(
            fetch_metric_payloads(state._gcs_call)
        ).get("proxies", {})
    except Exception:  # noqa: BLE001 — registry still prints without metrics
        traffic = {}
    for row in proxies:
        row["traffic"] = traffic.get(row.get("proxy_id"), {})
    print(json.dumps(proxies, indent=2, default=str))
    return 0


def cmd_chaos(args):
    """`ray_tpu chaos`: fault injection against a live cluster — the
    operator-facing face of the elastic-training chaos layer.

    - ``list``: live train runs (``trainrun:*`` records: state, group,
      epoch, per-rank pids) plus recovery counters.
    - ``kill-rank``: SIGKILL one rank's worker process (same-host pids
      only) — deterministic chip/host-loss injection.
    - ``abort-group``: write the collective abort key so every member
      blocked in a rendezvous raises CollectiveAbortedError within ~1 s.
    - ``delay-collective``: make every op of a group sleep N seconds at
      entry (straggler injection); 0 clears.
    - ``kill-replica`` / ``pause-replica``: SIGKILL / SIGSTOP one serve
      replica process (same-host pids only) — replica-loss / stuck-replica
      injection; the handle retry envelope plus controller health polling
      must absorb it.
    - ``kill-proxy``: SIGKILL one ingress proxy process (same-host pids
      only) — front-end-loss injection; surviving proxies on the shared
      SO_REUSEPORT listener keep accepting and the controller's health
      poll deregisters the corpse.
    - ``drain``: gracefully drain one serve replica through the
      controller's DRAINING state machine (rolling-restart injection).
    - ``net``: cluster-wide network chaos mesh. Writes a structured spec
      (seed + rules: fail/delay/jitter/blackhole/disconnect, optionally
      scoped by ``--method``/``--src``/``--dst``) to the GCS KV; every
      process polls it, so partitions apply — and heal — everywhere
      within ~1 poll period. ``--clear`` removes it; with no spec flags
      the current spec is printed.
    """
    _connected(args)
    from ..util import state

    if args.chaos_action in ("abort-group", "delay-collective") and not args.group:
        print(f"{args.chaos_action} needs --group", file=sys.stderr)
        return 1

    def _kv(method, *cargs):
        from .. import _worker_api

        worker = _worker_api.get_core_worker()
        client = worker.client_pool.get(*worker.gcs_address)
        return _worker_api.run_on_worker_loop(client.call(method, *cargs))

    if args.chaos_action == "net":
        from ..runtime.gcs import keys as gcs_keys

        if args.clear:
            _kv("kv_del", gcs_keys.CHAOS_NET_SPEC)
            print("chaos-net spec cleared; processes heal within ~1 poll "
                  "period")
            return 0
        spec = None
        if args.spec:
            spec = json.loads(args.spec)
        elif args.spec_file:
            with open(args.spec_file) as f:
                spec = json.load(f)
        elif any((args.fail, args.delay_ms, args.jitter_ms, args.blackhole,
                  args.disconnect)):
            rule = {"method": args.method, "src": args.src, "dst": args.dst}
            if args.fail:
                rule["fail"] = args.fail
            if args.delay_ms:
                rule["delay_ms"] = args.delay_ms
            if args.jitter_ms:
                rule["jitter_ms"] = args.jitter_ms
            if args.blackhole:
                rule["blackhole"] = True
            if args.disconnect:
                rule["disconnect"] = args.disconnect
            spec = {"seed": args.seed, "rules": [rule]}
        if spec is None:
            raw = _kv("kv_get", gcs_keys.CHAOS_NET_SPEC)
            if raw:
                print(bytes(raw).decode("utf-8", "replace"))
            else:
                print("no chaos-net spec set")
            return 0
        _kv("kv_put", gcs_keys.CHAOS_NET_SPEC,
            json.dumps(spec).encode(), True)
        print(f"chaos-net spec set ({len(spec.get('rules', []))} rule(s), "
              f"seed {spec.get('seed', 0)}); every process applies it "
              f"within ~1 poll period")
        return 0
    if args.chaos_action == "list":
        from ..testing import list_serve_replicas

        summary = state.metrics_summary()
        out = {
            "runs": state.list_train_runs(),
            "train_ft": summary["train_ft"],
            "serve_replicas": list_serve_replicas(args.app),
            "serve_ft": summary.get("serve_ft", {}),
        }
        print(json.dumps(out, indent=2, default=str))
        return 0
    if args.chaos_action in ("kill-replica", "pause-replica"):
        from ..testing import kill_serve_replica

        sig = signal.SIGKILL if args.chaos_action == "kill-replica" \
            else signal.SIGSTOP
        rid, pid = kill_serve_replica(
            args.app, deployment=args.deployment, replica_id=args.replica,
            sig=sig,
        )
        if rid is None:
            print(f"no matching RUNNING replica in app {args.app!r} "
                  f"(pids are same-host only; see `ray_tpu chaos list`)",
                  file=sys.stderr)
            return 1
        verb = "killed" if sig == signal.SIGKILL else "paused"
        print(f"{verb} replica {rid} (pid {pid}) of app {args.app!r}")
        return 0
    if args.chaos_action == "kill-proxy":
        from ..testing import kill_serve_proxy

        proxy_id, pid = kill_serve_proxy(args.proxy)
        if proxy_id is None:
            print("no matching live proxy (pids are same-host only; see "
                  "`ray_tpu proxies`)", file=sys.stderr)
            return 1
        print(f"killed proxy {proxy_id} (pid {pid}); survivors on the "
              f"shared listener keep serving")
        return 0
    if args.chaos_action == "drain":
        from .. import api
        from ..serve.controller import CONTROLLER_NAME

        if not args.replica:
            print("drain needs --replica (see `ray_tpu chaos list`)",
                  file=sys.stderr)
            return 1
        try:
            controller = api.get_actor(CONTROLLER_NAME)
            ok = api.get(
                controller.drain_replica.remote(args.app, args.replica),
                timeout=10,
            )
        except Exception as e:  # noqa: BLE001
            print(f"drain failed: {e}", file=sys.stderr)
            return 1
        if not ok:
            print(f"replica {args.replica!r} not found (or not RUNNING) in "
                  f"app {args.app!r}", file=sys.stderr)
            return 1
        print(f"draining replica {args.replica} of app {args.app!r}; the "
              f"controller replaces it once in-flight requests finish")
        return 0
    if args.chaos_action == "kill-rank":
        runs = {r["name"]: r for r in state.list_train_runs()}
        rec = runs.get(args.run)
        if rec is None:
            print(f"no live train run {args.run!r}; see `ray_tpu chaos list`",
                  file=sys.stderr)
            return 1
        for w in rec.get("workers", []):
            if w.get("rank") == args.rank:
                pid = w.get("pid")
                try:
                    os.kill(int(pid), signal.SIGKILL)
                except (OSError, TypeError, ValueError) as e:
                    print(f"kill pid {pid} failed: {e} (kill-rank only "
                          f"reaches same-host pids)", file=sys.stderr)
                    return 1
                print(f"killed run {args.run!r} rank {args.rank} (pid {pid})")
                return 0
        print(f"run {args.run!r} has no rank {args.rank}", file=sys.stderr)
        return 1
    if args.chaos_action == "abort-group":
        from ..collective import abort_collective_group

        advanced = abort_collective_group(
            args.group, args.epoch, reason="cli abort"
        )
        print(f"abort {'written' if advanced else 'already >= requested'} "
              f"for group {args.group!r} epoch {args.epoch}")
        return 0
    if args.chaos_action == "delay-collective":
        from ..runtime.gcs import keys as gcs_keys

        key = gcs_keys.COLLECTIVE_DELAY.key(args.group)
        if args.seconds > 0:
            _kv("kv_put", key, str(args.seconds).encode(), True)
            print(f"group {args.group!r}: every op now sleeps "
                  f"{args.seconds}s at entry (TTL-cached ~2s in members)")
        else:
            _kv("kv_del", key)
            print(f"group {args.group!r}: delay cleared")
        return 0
    return 1


def cmd_lint(args):
    """`ray_tpu lint`: the project-invariant static-analysis pass.

    Runs the RT001..RT012 checkers (ray_tpu/analysis/) over the package —
    or the given paths — subtracts the committed baseline, and reports
    what's left. Exit codes: 0 clean, 1 findings (new or stale baseline),
    2 internal error. ``--baseline-update`` rewrites the baseline from the
    current findings (shrink-only policy: do this only to *remove* fixed
    entries, never to grandfather new code).
    """
    import os as _os

    from .. import analysis

    try:
        rules = args.rules.split(",") if args.rules else None
        pkg_root = _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))
        repo_root = _os.path.dirname(pkg_root)
        targets = args.paths or [pkg_root]
        findings = []
        files = 0
        parse_errors = []
        for target in targets:
            analyzer = analysis.Analyzer(
                target, rules=rules,
                rel_to=repo_root if _os.path.abspath(target).startswith(repo_root)
                else None,
            )
            result = analyzer.run()
            findings.extend(result.findings)
            files += result.files_scanned
            parse_errors.extend(result.parse_errors)

        if args.baseline_update:
            path = analysis.write_baseline(findings, args.baseline)
            print(f"baseline rewritten with {len(findings)} finding(s): {path}")
            return 0

        entries = [] if args.no_baseline else analysis.load_baseline(args.baseline)
        new, suppressed, stale = analysis.apply_baseline(findings, entries)

        if getattr(args, "json", False):
            print(json.dumps({
                "files_scanned": files,
                "parse_errors": parse_errors,
                "findings": [f.to_dict() for f in new],
                "baselined": len(suppressed),
                "stale_baseline": stale,
                "counts": {
                    rule: sum(1 for f in new if f.rule == rule)
                    for rule in sorted({f.rule for f in new})
                },
            }, indent=2))
        else:
            for f in new:
                print(f"{f.path}:{f.line}: {f.rule} {f.message}")
            for e in stale:
                print(
                    f"stale baseline entry (finding fixed — shrink the "
                    f"baseline): {e.get('rule')} {e.get('path')}: "
                    f"{e.get('message')}"
                )
            for err in parse_errors:
                print(f"parse error: {err}", file=sys.stderr)
            print(
                f"{files} file(s) scanned: {len(new)} finding(s), "
                f"{len(suppressed)} baselined, {len(stale)} stale "
                f"baseline entr{'y' if len(stale) == 1 else 'ies'}"
            )
        return 1 if (new or stale or parse_errors) else 0
    except SystemExit:
        raise
    except Exception as e:  # noqa: BLE001 — exit code 2 contract
        print(f"lint internal error: {type(e).__name__}: {e}", file=sys.stderr)
        return 2


def cmd_timeline(args):
    """`ray_tpu timeline`: export the cluster-wide chrome trace — GCS
    task-state bars merged with every traced node's spans (reference:
    `ray timeline` writing chrome://tracing JSON)."""
    _connected(args)
    from ..util import tracing

    events = tracing.timeline(args.output)
    print(
        f"wrote {len(events)} trace events to {args.output} "
        f"(open in chrome://tracing or https://ui.perfetto.dev)"
    )
    return 0


def cmd_job(args):
    """`ray_tpu job submit|status|logs|stop|list` (reference: `ray job`
    subcommands, dashboard/modules/job/cli.py)."""
    from ..job_submission import JobSubmissionClient

    address = args.address
    if not address.startswith("http"):
        address = f"http://{address}"
    client = JobSubmissionClient(address)
    if args.action == "submit":
        entrypoint = " ".join(a for a in args.entrypoint if a != "--")
        if not entrypoint:
            print("job submit needs an entrypoint", file=sys.stderr)
            return 1
        runtime_env = (
            {"working_dir": args.working_dir} if args.working_dir else None
        )
        sid = client.submit_job(
            entrypoint=entrypoint,
            submission_id=args.submission_id,
            runtime_env=runtime_env,
        )
        print(sid)
    elif args.action == "list":
        print(json.dumps(client.list_jobs(), indent=2))
    else:
        if not args.submission_id:
            print(f"job {args.action} needs --submission-id", file=sys.stderr)
            return 1
        if args.action == "status":
            print(client.get_job_status(args.submission_id))
        elif args.action == "logs":
            print(client.get_job_logs(args.submission_id), end="")
        elif args.action == "stop":
            print(client.stop_job(args.submission_id))
    return 0


def main(argv=None):
    parser = argparse.ArgumentParser(prog="ray_tpu")
    sub = parser.add_subparsers(dest="command", required=True)

    p = sub.add_parser("start", help="start a head or worker node")
    p.add_argument("--head", action="store_true")
    p.add_argument("--address", default=None, help="head host:port to join")
    p.add_argument("--num-cpus", type=int, default=None)
    p.add_argument("--num-tpus", type=int, default=None)
    p.add_argument("--resources", default=None, help="JSON resource map")
    p.add_argument("--labels", default=None, help="JSON label map")
    p.add_argument("--object-store-memory", type=int, default=None)
    p.add_argument("--block", action="store_true")
    p.add_argument("--no-dashboard", action="store_true")
    p.add_argument("--dashboard-port", type=int, default=8265)
    p.add_argument(
        "--ray-client-server-port", type=int, default=10001,
        help="port for ray:// clients (head only); -1 disables",
    )
    p.add_argument(
        "--ray-client-server-host", default="127.0.0.1",
        help="bind host for ray:// clients; 0.0.0.0 accepts remote machines",
    )
    p.set_defaults(fn=cmd_start)

    p = sub.add_parser(
        "stop", help="stop all local ray_tpu processes (reference: ray stop)"
    )
    p.set_defaults(fn=cmd_stop)

    p = sub.add_parser("job", help="submit and manage jobs")
    p.add_argument(
        "action", choices=["submit", "status", "logs", "stop", "list"]
    )
    p.add_argument("--address", required=True, help="dashboard URL")
    p.add_argument("--submission-id", default=None)
    p.add_argument("--working-dir", default=None)
    p.add_argument("entrypoint", nargs=argparse.REMAINDER)
    p.set_defaults(fn=cmd_job)

    for name, fn in (
        ("status", cmd_status),
        ("summary", cmd_summary),
    ):
        p = sub.add_parser(name)
        p.add_argument("--address", required=True, help="head host:port")
        p.set_defaults(fn=fn)

    p = sub.add_parser(
        "metrics", help="Prometheus exposition dump (or --summary JSON)"
    )
    p.add_argument("--address", required=True, help="head host:port")
    p.add_argument(
        "--summary", action="store_true",
        help="aggregated collective/step/HBM JSON instead of raw exposition",
    )
    p.set_defaults(fn=cmd_metrics)

    p = sub.add_parser(
        "kvcache", help="KV-cache plane stats (prefix hits, blocks, TTFT)"
    )
    p.add_argument("--address", required=True, help="head host:port")
    p.set_defaults(fn=cmd_kvcache)

    p = sub.add_parser(
        "kvtier",
        help="cluster KV-tier stats (hit/peer_pull/recompute, wire bytes)",
    )
    p.add_argument("--address", required=True, help="head host:port")
    p.set_defaults(fn=cmd_kvtier)

    p = sub.add_parser(
        "adapters",
        help="LoRA adapter-plane stats (hit rate, cold attaches, evictions)",
    )
    p.add_argument("--address", required=True, help="head host:port")
    p.set_defaults(fn=cmd_adapters)

    p = sub.add_parser(
        "autoscale",
        help="SLO autoscaler decision log and scale-up/down counters",
    )
    p.add_argument("autoscale_action", choices=["log", "status"])
    p.add_argument("--address", required=True, help="head host:port")
    p.add_argument(
        "--limit", type=int, default=100,
        help="max decision events to show (log)",
    )
    p.set_defaults(fn=cmd_autoscale)

    p = sub.add_parser(
        "events",
        help="flight-recorder query: cluster-wide structured events "
             "(state transitions, retries, watchdog stack captures)",
    )
    p.add_argument("--address", required=True, help="head host:port")
    p.add_argument(
        "--limit", type=int, default=100, help="max events to show"
    )
    p.add_argument(
        "--name", default=None,
        help="filter to one event name (e.g. replica_state, request_retry)",
    )
    p.add_argument(
        "--since", type=float, default=None,
        help="only events with ts >= this unix timestamp",
    )
    p.set_defaults(fn=cmd_events)

    p = sub.add_parser(
        "top",
        help="live per-worker training table sorted by step-time "
             "deviation (straggler hunt)",
    )
    p.add_argument("--address", required=True, help="head host:port")
    p.add_argument("--json", action="store_true", help="raw verdict rows")
    p.add_argument(
        "--watch", action="store_true", help="refresh until interrupted"
    )
    p.add_argument(
        "--interval", type=float, default=2.0,
        help="seconds between --watch refreshes",
    )
    p.set_defaults(fn=cmd_top)

    p = sub.add_parser(
        "alerts",
        help="alerting engine: active alerts, rules, transitions, "
             "straggler verdicts",
    )
    p.add_argument("--address", required=True, help="head host:port")
    p.add_argument(
        "--rules", action="store_true", help="list declared rules only"
    )
    p.add_argument(
        "--events", action="store_true",
        help="tail alert/straggler flight-recorder events instead",
    )
    p.add_argument(
        "--limit", type=int, default=100, help="max events (--events)"
    )
    p.add_argument(
        "--since", type=float, default=None,
        help="only events with ts >= this unix timestamp (--events)",
    )
    p.add_argument(
        "--set-rule", default=None, metavar="JSON",
        help='declare/replace a rule, e.g. \'{"name": "slow_ttft", '
             '"series": "serve_ttft_s", "threshold": 0.5}\'',
    )
    p.add_argument(
        "--delete-rule", default=None, metavar="NAME",
        help="remove a rule from the registry",
    )
    p.set_defaults(fn=cmd_alerts)

    p = sub.add_parser(
        "proxies",
        help="ingress data plane: live proxy registry + per-proxy "
             "traffic rollup",
    )
    p.add_argument("--address", required=True, help="head host:port")
    p.set_defaults(fn=cmd_proxies)

    p = sub.add_parser(
        "chaos",
        help="fault injection: kill ranks/replicas/proxies, abort/delay "
             "collectives, drain replicas, network chaos mesh",
    )
    p.add_argument(
        "chaos_action",
        choices=["list", "kill-rank", "abort-group", "delay-collective",
                 "kill-replica", "pause-replica", "kill-proxy", "drain",
                 "net"],
    )
    p.add_argument("--address", required=True, help="head host:port")
    p.add_argument("--run", default=None, help="train run name (kill-rank)")
    p.add_argument(
        "--app", default="default",
        help="serve app name (kill-replica/pause-replica/drain)",
    )
    p.add_argument(
        "--deployment", default=None,
        help="restrict kill-replica/pause-replica to one deployment",
    )
    p.add_argument(
        "--replica", default=None,
        help="replica id (required for drain; optional filter for "
             "kill-replica/pause-replica)",
    )
    p.add_argument(
        "--proxy", default=None,
        help="proxy id (optional filter for kill-proxy; see "
             "`ray_tpu proxies`)",
    )
    p.add_argument("--rank", type=int, default=0, help="world rank to kill")
    p.add_argument("--group", default=None, help="collective group name")
    p.add_argument(
        "--epoch", type=int, default=0,
        help="abort epochs <= this (abort-group)",
    )
    p.add_argument(
        "--seconds", type=float, default=0.0,
        help="per-op delay for delay-collective; 0 clears",
    )
    p.add_argument(
        "--spec", default=None,
        help="chaos-net: full structured spec as inline JSON",
    )
    p.add_argument(
        "--spec-file", default=None,
        help="chaos-net: path to a JSON spec file",
    )
    p.add_argument(
        "--clear", action="store_true",
        help="chaos-net: remove the cluster spec (heal all partitions)",
    )
    p.add_argument(
        "--method", default="*",
        help="chaos-net single-rule: RPC method to match (default: all)",
    )
    p.add_argument(
        "--src", default="*",
        help="chaos-net single-rule: caller node-id hex prefix "
             "(directional partition source; default: all)",
    )
    p.add_argument(
        "--dst", default="*",
        help="chaos-net single-rule: destination host:port (default: all)",
    )
    p.add_argument(
        "--fail", type=float, default=0.0,
        help="chaos-net single-rule: per-call failure probability",
    )
    p.add_argument(
        "--delay-ms", type=float, default=0.0,
        help="chaos-net single-rule: fixed per-call delay",
    )
    p.add_argument(
        "--jitter-ms", type=float, default=0.0,
        help="chaos-net single-rule: uniform extra delay on top of "
             "--delay-ms",
    )
    p.add_argument(
        "--blackhole", action="store_true",
        help="chaos-net single-rule: calls hang until the caller's "
             "deadline instead of erroring",
    )
    p.add_argument(
        "--disconnect", type=float, default=0.0,
        help="chaos-net single-rule: probability of mid-call transport "
             "disconnect",
    )
    p.add_argument(
        "--seed", type=int, default=0,
        help="chaos-net: deterministic rng seed for the spec",
    )
    p.set_defaults(fn=cmd_chaos)

    p = sub.add_parser(
        "lint",
        help="run the RT001..RT012 static-analysis pass "
             "(exit 0 clean / 1 findings / 2 internal error)",
    )
    p.add_argument(
        "paths", nargs="*",
        help="files or directories to scan (default: the ray_tpu package)",
    )
    p.add_argument(
        "--json", action="store_true", help="machine-readable report"
    )
    p.add_argument(
        "--rules", default=None,
        help="comma-separated rule ids to run (default: all)",
    )
    p.add_argument(
        "--baseline", default=None,
        help="baseline file (default: ray_tpu/analysis/baseline.json)",
    )
    p.add_argument(
        "--no-baseline", action="store_true",
        help="report every finding, ignoring the baseline",
    )
    p.add_argument(
        "--baseline-update", action="store_true",
        help="rewrite the baseline from current findings (shrink-only "
             "policy: use to drop fixed entries)",
    )
    p.set_defaults(fn=cmd_lint)

    p = sub.add_parser(
        "timeline", help="export the cluster chrome trace (ray timeline)"
    )
    p.add_argument("--address", required=True, help="head host:port")
    p.add_argument(
        "-o", "--output", default="/tmp/ray_tpu_timeline.json",
        help="output chrome-trace JSON path",
    )
    p.set_defaults(fn=cmd_timeline)

    p = sub.add_parser(
        "logs", help="list or tail session log files (reference: ray logs)"
    )
    p.add_argument("filename", nargs="?", help="log file name; omit to list")
    p.add_argument("--address", required=True, help="head host:port")
    p.add_argument("--node-id", default=None, help="node id hex prefix filter")
    p.add_argument("--tail", type=int, default=1000)
    p.set_defaults(fn=cmd_logs)

    p = sub.add_parser(
        "debug", help="list or attach to remote pdb sessions (ray debug)"
    )
    p.add_argument("session", nargs="?", help="session id prefix; omit to list")
    p.add_argument("--address", required=True, help="head host:port")
    p.set_defaults(fn=cmd_debug)

    p = sub.add_parser("list", help="list cluster state")
    p.add_argument(
        "what",
        choices=[
            "nodes", "actors", "tasks", "jobs", "placement-groups",
            "objects", "weights", "replicas",
        ],
    )
    p.add_argument("--address", required=True, help="head host:port")
    p.set_defaults(fn=cmd_list)

    # `perf` is the canonical name; `microbenchmark` stays as the
    # backward-compatible alias from earlier rounds
    for bench_name in ("perf", "microbenchmark"):
        p = sub.add_parser(
            bench_name, help="core-ops throughput suite "
            "(reference: release/microbenchmark)",
        )
        p.add_argument("--small", action="store_true")
        p.add_argument(
            "--json", action="store_true",
            help="emit one machine-readable JSON line (BENCH_LOG.md appends)",
        )
        p.set_defaults(fn=cmd_microbenchmark)

    args = parser.parse_args(argv)
    return args.fn(args) or 0


if __name__ == "__main__":
    sys.exit(main())
