"""DeploymentHandle + Router: the request data plane.

Role-equivalent of the reference's DeploymentHandle/Router
(python/ray/serve/handle.py, serve/_private/router.py) with the
power-of-two-choices replica picker
(request_router/pow_2_router.py:27): each call samples two running
replicas and routes to the one with the shorter queue, using queue lengths
from the controller's routing table (refreshed on a version poll). Works
from any process — handles serialize (controller handle + names only).
"""

from __future__ import annotations

import random
import threading
import time
import zlib
from typing import Any, Dict, Optional

from .. import api


def _prefix_affinity_key(args, kwargs, num_tokens: int) -> Optional[int]:
    """Stable hash of a request's leading prompt tokens, for cache-affine
    routing. Looks for the serving request dict convention ({"token_ids":
    ...} or {"prompt": ...}) in the call args; hashes the first
    ``num_tokens`` token ids (or 4x that many prompt characters — a rough
    token-length proxy). zlib.crc32, NOT hash(): the key must agree across
    processes and PYTHONHASHSEED randomizes str/bytes hashing per-process."""
    for value in list(args) + list(kwargs.values()):
        if not isinstance(value, dict):
            continue
        token_ids = value.get("token_ids")
        if token_ids is not None:
            try:
                head = ",".join(str(int(t)) for t in list(token_ids)[:num_tokens])
            except (TypeError, ValueError):
                continue
            return zlib.crc32(head.encode())
        prompt = value.get("prompt")
        if isinstance(prompt, str):
            return zlib.crc32(prompt[: 4 * num_tokens].encode())
    return None


class DeploymentResponse:
    """Future for one request (reference: serve/handle.py
    DeploymentResponse): .result() blocks; ._to_object_ref() exposes the ref
    for composition with ray_tpu.get/wait."""

    def __init__(self, ref):
        self._ref = ref

    def result(self, timeout_s: Optional[float] = None):
        return api.get(self._ref, timeout=timeout_s)

    def _to_object_ref(self):
        return self._ref


class DeploymentResponseGenerator:
    """Streaming response (reference: serve/handle.py:557
    DeploymentResponseGenerator): iterating yields each item the replica's
    generator produces, as soon as it is reported — the first item is
    consumable while the replica is still generating."""

    def __init__(self, ref_gen, timeout_s: Optional[float] = 60.0):
        self._ref_gen = ref_gen
        self._timeout_s = timeout_s

    def __iter__(self):
        return self

    def __next__(self):
        ref = next(self._ref_gen)  # raises StopIteration at end of stream
        return api.get(ref, timeout=self._timeout_s)

    def close(self):
        """Stop consuming; abandoning the underlying ObjectRefGenerator
        releases the owner's stream bookkeeping (object_ref.py __del__)."""
        close = getattr(self._ref_gen, "close", None)
        if close is not None:
            close()
        self._ref_gen = iter(())

    def _to_object_ref_gen(self):
        return self._ref_gen


class Router:
    """Per-process replica picker for one application."""

    _REFRESH_S = 1.0

    def __init__(self, controller, app_name: str):
        self._controller = controller
        self._app_name = app_name
        self._table: Dict[str, dict] = {}
        self._last_refresh = 0.0
        self._lock = threading.Lock()
        self._rr = 0

    def _refresh(self, force: bool = False):
        now = time.time()
        if not force and now - self._last_refresh < self._REFRESH_S:
            return
        table = api.get(
            self._controller.get_routing_table.remote(self._app_name),
            timeout=30,
        )
        with self._lock:
            self._table = table
            self._last_refresh = now

    # an affine replica keeps winning until its queue runs this many
    # requests longer than the random alternative's — cache reuse is worth
    # a little imbalance, but not a hot spot
    _AFFINITY_SLACK = 2

    def pick(self, deployment: str, affinity: Optional[int] = None):
        """Power-of-two-choices on reported queue length. With an
        ``affinity`` key (hash of the request's prompt prefix), the pick is
        biased: one candidate is always the key's preferred replica, which
        wins unless its queue is more than _AFFINITY_SLACK behind — so
        repeated prefixes land where their KV blocks already live, and
        overload still spills to the rest of the fleet."""
        self._refresh()
        deadline = time.time() + 30
        while True:
            with self._lock:
                entry = self._table.get(deployment)
                replicas = entry["replicas"] if entry else []
            if replicas:
                break
            if time.time() > deadline:
                raise RuntimeError(
                    f"no running replicas for deployment {deployment!r}"
                )
            time.sleep(0.1)
            self._refresh(force=True)
        if len(replicas) == 1:
            return replicas[0][1]
        if affinity is not None:
            # replica ids sorted so every process maps the key to the SAME
            # preferred replica regardless of table ordering
            ordered = sorted(replicas, key=lambda r: str(r[0]))
            preferred = ordered[affinity % len(ordered)]
            other = random.choice(
                [r for r in ordered if r is not preferred]
            )
            if preferred[2] <= other[2] + self._AFFINITY_SLACK:
                return preferred[1]
            return other[1]
        # two random candidates, shorter controller-reported queue wins;
        # round-robin counter breaks ties so equal queues still spread
        a, b = random.sample(replicas, 2)
        qa, qb = a[2], b[2]
        if qa == qb:
            self._rr += 1
            return (a if self._rr % 2 else b)[1]
        return (a if qa < qb else b)[1]


class DeploymentHandle:
    def __init__(self, controller, app_name: str, deployment: str,
                 method: str = "__call__", multiplexed_model_id: str = "",
                 stream: bool = False, prefix_affinity_tokens: int = 0,
                 _router: Optional[list] = None):
        self._controller = controller
        self._app_name = app_name
        self._deployment = deployment
        self._method = method
        self._multiplexed_model_id = multiplexed_model_id
        self._stream = stream
        # > 0: hash this many leading prompt tokens of each request and
        # bias replica picking toward the hash's replica (prefix-cache
        # affinity); 0 disables
        self._prefix_affinity_tokens = prefix_affinity_tokens
        # the router depends only on (controller, app_name), both immutable
        # across options()/method handles — a shared mutable holder means
        # whichever handle first routes a request creates the Router and all
        # derived handles reuse its cached routing table
        self._router_holder: list = _router if _router is not None else [None]

    def options(self, *, method_name: Optional[str] = None,
                multiplexed_model_id: Optional[str] = None,
                stream: Optional[bool] = None,
                prefix_affinity_tokens: Optional[int] = None) -> "DeploymentHandle":
        return DeploymentHandle(
            self._controller,
            self._app_name,
            self._deployment,
            method_name if method_name is not None else self._method,
            multiplexed_model_id
            if multiplexed_model_id is not None
            else self._multiplexed_model_id,
            stream if stream is not None else self._stream,
            prefix_affinity_tokens
            if prefix_affinity_tokens is not None
            else self._prefix_affinity_tokens,
            _router=self._router_holder,
        )

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        # handle.other_method.remote(...) sugar
        return DeploymentHandle(
            self._controller, self._app_name, self._deployment, name,
            self._multiplexed_model_id, self._stream,
            self._prefix_affinity_tokens,
            _router=self._router_holder,
        )

    def remote(self, *args, **kwargs):
        if self._router_holder[0] is None:
            self._router_holder[0] = Router(self._controller, self._app_name)
        affinity = None
        if self._prefix_affinity_tokens > 0:
            affinity = _prefix_affinity_key(
                args, kwargs, self._prefix_affinity_tokens
            )
        replica = self._router_holder[0].pick(self._deployment, affinity)
        metadata = None
        if self._multiplexed_model_id:
            metadata = {"multiplexed_model_id": self._multiplexed_model_id}
        # response chaining (reference: passing DeploymentResponse into a
        # downstream .remote — serve/handle.py): a response argument becomes
        # its ObjectRef, which the task-arg machinery resolves to the VALUE
        # before the replica method runs — no blocking .result() in between
        def chain(x):
            return x._to_object_ref() if isinstance(x, DeploymentResponse) else x

        args = tuple(chain(a) for a in args)
        kwargs = {k: chain(v) for k, v in kwargs.items()}
        if self._stream:
            # replica-side async generator shipped item-by-item through the
            # runtime's streaming-generator path (ObjectRefGenerator)
            ref_gen = replica.handle_request_stream.options(
                num_returns="streaming"
            ).remote(self._method, args, kwargs, metadata)
            return DeploymentResponseGenerator(ref_gen)
        ref = replica.handle_request.remote(self._method, args, kwargs, metadata)
        return DeploymentResponse(ref)

    def __reduce__(self):
        return (
            DeploymentHandle,
            (self._controller, self._app_name, self._deployment, self._method,
             self._multiplexed_model_id, self._stream,
             self._prefix_affinity_tokens),
        )
