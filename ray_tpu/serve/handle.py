"""DeploymentHandle + Router: the request data plane.

Role-equivalent of the reference's DeploymentHandle/Router
(python/ray/serve/handle.py, serve/_private/router.py) with the
power-of-two-choices replica picker
(request_router/pow_2_router.py:27): each call samples two running
replicas and routes to the one with the shorter queue, using queue lengths
from the controller's routing table (refreshed on a version poll). Works
from any process — handles serialize (controller handle + names only).

Fault tolerance: ``remote()`` wraps every submission in a retryable
envelope. A per-request deadline (``options(timeout_s=...)``, or the
deployment's ``RequestRouterConfig.default_timeout_s``) rides in the
request metadata so replicas can reject dead-on-arrival work; on replica
death, transport failure, a stale-table ``ReplicaDrainingError``, or (by
policy) a ``BackPressureError`` shed, the response force-refreshes the
routing table, excludes the failed replica, and resubmits — bounded by
``max_attempts`` and the remaining deadline budget. Streaming responses
retry only while no partial output has been consumed (the idempotency
guard: a half-delivered stream must not silently restart).
"""

from __future__ import annotations

import logging
import random
import threading
import time
import zlib
from typing import Any, Dict, FrozenSet, Optional, Set

from .. import api
from ..exceptions import (
    ActorDiedError,
    BackPressureError,
    DeadlineExceededError,
    NodeFencedError,
    ReplicaDrainingError,
    RpcError,
    WorkerCrashedError,
)
from ..util import events as _events
from ..util import tracing as _tracing
from .hash_ring import ReplicaRing

logger = logging.getLogger(__name__)


def _prefix_affinity_key(args, kwargs, num_tokens: int) -> Optional[int]:
    """Stable hash of a request's leading prompt tokens, for cache-affine
    routing. Looks for the serving request dict convention ({"token_ids":
    ...} or {"prompt": ...}) in the call args; hashes the first
    ``num_tokens`` token ids (or 4x that many prompt characters — a rough
    token-length proxy). zlib.crc32, NOT hash(): the key must agree across
    processes and PYTHONHASHSEED randomizes str/bytes hashing per-process."""
    for value in list(args) + list(kwargs.values()):
        if not isinstance(value, dict):
            continue
        token_ids = value.get("token_ids")
        if token_ids is not None:
            try:
                head = ",".join(str(int(t)) for t in list(token_ids)[:num_tokens])
            except (TypeError, ValueError):
                continue
            return zlib.crc32(head.encode())
        prompt = value.get("prompt")
        if isinstance(prompt, str):
            return zlib.crc32(prompt[: 4 * num_tokens].encode())
    return None


def _unwrap(exc: BaseException) -> BaseException:
    """User/replica exceptions travel wrapped as TaskError with ``.cause``
    set to the original; classification wants the original."""
    cause = getattr(exc, "cause", None)
    return cause if isinstance(cause, BaseException) else exc


_TYPED_SERVE_ERRORS = (
    BackPressureError, DeadlineExceededError, NodeFencedError,
    ReplicaDrainingError,
)


class _RequestContext:
    """Everything needed to resubmit one request to a different replica:
    the routing inputs, the failover policy from the deployment's
    RequestRouterConfig, and the mutable attempt state (current replica,
    replicas already tried). Shared by unary and streaming responses."""

    def __init__(self, router: "Router", deployment: str, method: str,
                 args: tuple, kwargs: dict, metadata: Optional[dict],
                 affinity: Optional[int], stream: bool,
                 deadline_ts: Optional[float], router_cfg: Dict[str, Any],
                 replica_id: str):
        self.router = router
        self.deployment = deployment
        self.method = method
        self.args = args
        self.kwargs = kwargs
        self.metadata = metadata
        self.affinity = affinity
        self.stream = stream
        self.deadline_ts = deadline_ts
        self.max_attempts = max(1, int(router_cfg.get("max_attempts", 3)))
        self.backoff_s = float(router_cfg.get("backoff_s", 0.05))
        self.retry_backpressure = bool(
            router_cfg.get("retry_backpressure", True)
        )
        self.attempt = 1
        self.replica_id = replica_id
        self.tried: Set[str] = {replica_id}

    def remaining_s(self) -> Optional[float]:
        if self.deadline_ts is None:
            return None
        return self.deadline_ts - time.time()

    def _retryable(self, exc: BaseException) -> bool:
        if isinstance(exc, (ActorDiedError, WorkerCrashedError, RpcError,
                            ReplicaDrainingError, NodeFencedError)):
            return True
        if isinstance(exc, BackPressureError):
            return self.retry_backpressure
        return False

    def classify(self, raw_exc: BaseException):
        """(exception to raise to the caller, retryable?). Typed serve
        errors surface unwrapped (callers/proxies except BackPressureError,
        not TaskError); everything else keeps its existing shape."""
        exc = _unwrap(raw_exc)
        to_raise = exc if isinstance(exc, _TYPED_SERVE_ERRORS) else raw_exc
        return to_raise, self._retryable(exc)

    def failover(self, raw_exc: BaseException):
        """Try to resubmit after ``raw_exc``. Returns the new submission
        (ref or ref-gen) or None when the error must surface (not
        retryable, attempts exhausted, or no deadline budget left)."""
        to_raise, retryable = self.classify(raw_exc)
        if not retryable or self.attempt >= self.max_attempts:
            return None
        remaining = self.remaining_s()
        backoff = self.backoff_s * self.attempt
        if remaining is not None and remaining <= backoff:
            return None
        cause = _unwrap(raw_exc)
        logger.info(
            "serve failover (%s attempt %d/%d): %s on replica %s; "
            "resubmitting", self.deployment, self.attempt, self.max_attempts,
            type(cause).__name__, self.replica_id,
        )
        attempt_wall = time.time()
        attempt_t0 = time.perf_counter()
        if backoff > 0:
            time.sleep(backoff)
        self.attempt += 1
        # the failed replica may be a fresh death the controller hasn't
        # noticed yet — exclude it explicitly so the refreshed table can't
        # hand it straight back
        try:
            rid, replica = self.router.pick(
                self.deployment, self.affinity,
                exclude=frozenset(self.tried), force_refresh=True,
                deadline_ts=self.deadline_ts,
            )
        except Exception:
            return None
        excluded = sorted(self.tried)
        self.replica_id = rid
        self.tried.add(rid)
        from ..util.metrics import record_serve_retry

        # the retry counter tags the OUTCOME replica (where the request
        # went), so it counts only after the pick succeeds
        record_serve_retry(self.deployment, type(cause).__name__, replica=rid)
        _events.record_event(
            _events.REQUEST_RETRY, deployment=self.deployment,
            reason=type(cause).__name__, attempt=self.attempt,
            replica=rid, excluded=excluded,
        )
        # sibling attempt span under the request's trace: one per failover,
        # tagged with the replicas already excluded and the backoff burned
        _tracing.emit_span(
            "serve.attempt", (self.metadata or {}).get("trace_ctx"),
            attempt_wall, time.perf_counter() - attempt_t0,
            deployment=self.deployment, attempt=self.attempt,
            reason=type(cause).__name__, replica=rid,
            excluded=excluded, backoff_s=backoff,
        )
        return _submit(replica, self)


def _submit(replica, ctx: "_RequestContext"):
    """One raw submission of the request to a replica actor."""
    if ctx.stream:
        return replica.handle_request_stream.options(
            num_returns="streaming"
        ).remote(ctx.method, ctx.args, ctx.kwargs, ctx.metadata)
    return replica.handle_request.remote(
        ctx.method, ctx.args, ctx.kwargs, ctx.metadata
    )


class DeploymentResponse:
    """Future for one request (reference: serve/handle.py
    DeploymentResponse): .result() blocks; ._to_object_ref() exposes the ref
    for composition with ray_tpu.get/wait.

    With a retry context, ``result()`` is where failover happens: the
    submission was eager (fire-and-forget callers never block), so a
    replica death is only observed — and absorbed — when the result is
    awaited."""

    def __init__(self, ref, ctx: Optional[_RequestContext] = None):
        self._ref = ref
        self._ctx = ctx

    def replica_id(self) -> Optional[str]:
        """The replica that served (or is serving) this request — AFTER
        failover, the replica the final resubmission landed on, not the
        one originally routed to. None for bare refs with no context."""
        return self._ctx.replica_id if self._ctx is not None else None

    def trace_id(self) -> Optional[str]:
        """The request's trace id (joins caller-side latency with the
        server-side spans); None when the request was not traced."""
        if self._ctx is None:
            return None
        tctx = (self._ctx.metadata or {}).get("trace_ctx")
        return tctx.get("trace_id") if tctx else None

    def result(self, timeout_s: Optional[float] = None):
        while True:
            wait_s = timeout_s
            if self._ctx is not None:
                remaining = self._ctx.remaining_s()
                if remaining is not None:
                    remaining = max(remaining, 0.001)
                    wait_s = remaining if wait_s is None \
                        else min(wait_s, remaining)
            try:
                return api.get(self._ref, timeout=wait_s)
            except BaseException as exc:  # noqa: BLE001
                if self._ctx is None:
                    raise
                new_ref = self._ctx.failover(exc)
                if new_ref is None:
                    to_raise, _ = self._ctx.classify(exc)
                    if to_raise is exc:
                        raise
                    raise to_raise from exc
                self._ref = new_ref

    def _to_object_ref(self):
        return self._ref


class DeploymentResponseGenerator:
    """Streaming response (reference: serve/handle.py:557
    DeploymentResponseGenerator): iterating yields each item the replica's
    generator produces, as soon as it is reported — the first item is
    consumable while the replica is still generating.

    Failover is guarded by consumption: once any item has been delivered
    to the caller, a mid-stream failure surfaces instead of retrying (a
    restarted stream would silently replay or skip output)."""

    def __init__(self, ref_gen, timeout_s: Optional[float] = 60.0,
                 ctx: Optional[_RequestContext] = None):
        self._ref_gen = ref_gen
        self._timeout_s = timeout_s
        self._ctx = ctx
        self._consumed = 0

    def replica_id(self) -> Optional[str]:
        """See DeploymentResponse.replica_id."""
        return self._ctx.replica_id if self._ctx is not None else None

    def trace_id(self) -> Optional[str]:
        """See DeploymentResponse.trace_id."""
        if self._ctx is None:
            return None
        tctx = (self._ctx.metadata or {}).get("trace_ctx")
        return tctx.get("trace_id") if tctx else None

    def __iter__(self):
        return self

    def _item_timeout(self) -> Optional[float]:
        if self._ctx is not None and self._ctx.deadline_ts is not None:
            return max(self._ctx.deadline_ts - time.time(), 0.001)
        return self._timeout_s

    def _maybe_failover(self, exc: BaseException) -> bool:
        """Replace the underlying stream with a fresh submission if the
        idempotency guard (zero items consumed) and retry policy allow."""
        if self._ctx is None or self._consumed > 0:
            return False
        new_gen = self._ctx.failover(exc)
        if new_gen is None:
            return False
        self.close()
        self._ref_gen = new_gen
        return True

    def __next__(self):
        while True:
            try:
                ref = next(self._ref_gen)  # StopIteration at end of stream
                return_value = api.get(ref, timeout=self._item_timeout())
            except StopIteration:
                raise
            except BaseException as exc:  # noqa: BLE001
                if self._maybe_failover(exc):
                    continue
                # release the owner's stream bookkeeping NOW — a leaked
                # half-consumed stream pins its reported items until GC
                self.close()
                if self._ctx is not None:
                    to_raise, _ = self._ctx.classify(exc)
                    if to_raise is not exc:
                        raise to_raise from exc
                raise
            self._consumed += 1
            return return_value

    def close(self):
        """Stop consuming; closing the underlying ObjectRefGenerator
        eagerly releases the owner's stream bookkeeping AND signals the
        producing replica to stop generating (object_ref.py close())."""
        close = getattr(self._ref_gen, "close", None)
        if close is not None:
            close()
        self._ref_gen = iter(())

    def _to_object_ref_gen(self):
        return self._ref_gen


class _DeploymentView:
    """One deployment's routing snapshot, generation-stamped.

    Built only when the controller-reported table ``version`` (replica
    membership) changes; between generations a refresh just rewrites the
    queue-length list in place. Replica rows are pre-split into parallel
    tuples and the rendezvous ring is precomputed, so the per-request pick
    is index arithmetic over immutable structure — no lock, no dict built,
    no sort."""

    __slots__ = ("generation", "ids", "handles", "queues", "ring",
                 "router_config", "index_of")

    def __init__(self, generation: int, replicas, router_config: dict):
        rows = sorted(replicas, key=lambda r: str(r[0]))
        self.generation = generation
        self.ids = tuple(str(r[0]) for r in rows)
        self.handles = tuple(r[1] for r in rows)
        # the one mutable field: refreshed in place between generations
        self.queues = [int(r[2]) for r in rows]
        # ring ids == self.ids (both sorted), so a ring index indexes the
        # parallel tuples directly
        self.ring = ReplicaRing(self.ids)
        self.router_config = router_config or {}
        self.index_of = {rid: i for i, rid in enumerate(self.ids)}


class Router:
    """Per-process replica picker for one application."""

    _REFRESH_S = 1.0
    _STALE_WARN_S = 10.0

    def __init__(self, controller, app_name: str):
        self._controller = controller
        self._app_name = app_name
        # deployment -> _DeploymentView; whole-dict reference swapped
        # atomically on refresh so pick() reads without the lock
        self._views: Dict[str, _DeploymentView] = {}
        self._last_refresh = 0.0
        self._ever_refreshed = False
        self._last_stale_warn = 0.0
        self._lock = threading.Lock()
        self._rr = 0
        # stats for the cross-proxy agreement tests and `ray_tpu proxies`:
        # picks must proceed with NO controller round-trip between the
        # periodic table polls
        self.table_fetches = 0
        self.picks = 0

    def _refresh(self, force: bool = False):
        """Pull the routing table from the controller. A slow or briefly
        unreachable controller must NOT fail the request path: on error we
        keep serving from the cached (stale) views with a rate-limited
        warning, and only raise if there has never been a successful
        refresh (nothing cached to fall back on)."""
        now = time.time()
        if not force and now - self._last_refresh < self._REFRESH_S:
            return
        try:
            table = api.get(
                self._controller.get_routing_table.remote(self._app_name),
                timeout=5,
            )
        except Exception as exc:
            with self._lock:
                if not self._ever_refreshed:
                    raise
                stale_s = now - self._last_refresh
                # back off further refresh attempts for one TTL so every
                # request doesn't eat the controller timeout serially
                self._last_refresh = now
                if now - self._last_stale_warn >= self._STALE_WARN_S:
                    self._last_stale_warn = now
                    logger.warning(
                        "serve controller unreachable (%s); routing %r "
                        "from routing table %.1fs stale",
                        type(exc).__name__, self._app_name, stale_s,
                    )
            return
        with self._lock:
            old_views = self._views
            views: Dict[str, _DeploymentView] = {}
            for dep_name, entry in table.items():
                replicas = entry.get("replicas") or []
                generation = int(entry.get("version", 0))
                old = old_views.get(dep_name)
                if (
                    old is not None
                    and old.generation == generation
                    and len(old.ids) == len(replicas)
                ):
                    # same membership generation: update queue lengths in
                    # place, keep the ring and tuples (the common case —
                    # membership changes are rare, queue drift is constant)
                    for rid, _handle, queue_len in replicas:
                        i = old.index_of.get(str(rid))
                        if i is not None:
                            old.queues[i] = int(queue_len)
                    old.router_config = entry.get("router_config") \
                        or old.router_config
                    views[dep_name] = old
                else:
                    views[dep_name] = _DeploymentView(
                        generation, replicas,
                        entry.get("router_config") or {},
                    )
            self._views = views
            self._last_refresh = now
            self._ever_refreshed = True
            self.table_fetches += 1

    def router_config(self, deployment: str) -> Dict[str, Any]:
        """The deployment's failover policy as distributed through the
        routing table; defaults when the table predates the field."""
        self._refresh()
        view = self._views.get(deployment)
        cfg = view.router_config if view is not None else None
        if not cfg:
            from .config import RequestRouterConfig

            cfg = RequestRouterConfig().as_dict()
        return cfg

    def stats(self) -> Dict[str, int]:
        """{picks, table_fetches}: the agreement tests assert picks advance
        while table_fetches stays flat (no per-request controller RPC)."""
        return {"picks": self.picks, "table_fetches": self.table_fetches}

    # an affine replica keeps winning until its queue runs this many
    # requests longer than the random alternative's — cache reuse is worth
    # a little imbalance, but not a hot spot
    _AFFINITY_SLACK = 2

    def pick(self, deployment: str, affinity: Optional[int] = None,
             exclude: FrozenSet[str] = frozenset(),
             force_refresh: bool = False,
             deadline_ts: Optional[float] = None):
        """Power-of-two-choices on reported queue length; returns
        ``(replica_id, handle)``. With an ``affinity`` key (hash of the
        request's prompt prefix), the pick is biased: one candidate is
        always the key's rendezvous-ring replica (serve/hash_ring.py — the
        SAME winner in every proxy/handle process, no controller round
        trip), which wins unless its queue is more than _AFFINITY_SLACK
        behind — so repeated prefixes land where their KV blocks already
        live, and overload still spills to the rest of the fleet.
        ``exclude`` drops replicas a failover already tried — unless that
        would leave no candidate (a 1-replica deployment's restart is
        still worth a retry)."""
        self._refresh(force=force_refresh)
        self.picks += 1
        view = self._views.get(deployment)
        if view is not None and view.ids and not exclude:
            return self._pick_fast(view, affinity)
        return self._pick_slow(deployment, affinity, exclude, deadline_ts)

    def _pick_fast(self, view: _DeploymentView, affinity: Optional[int]):
        """The per-request hot path: index arithmetic over the view's
        precomputed tuples. Deliberately allocates no dict (guarded by a
        dis()-based perf-smoke test) — at proxy saturation this runs tens
        of thousands of times a second per process."""
        ids = view.ids
        n = len(ids)
        if n == 1:
            return ids[0], view.handles[0]
        queues = view.queues
        if affinity is not None:
            i = view.ring.lookup_index(affinity)
            j = random.randrange(n - 1)
            if j >= i:
                j += 1
            if queues[i] <= queues[j] + self._AFFINITY_SLACK:
                return ids[i], view.handles[i]
            return ids[j], view.handles[j]
        # two random candidates, shorter controller-reported queue wins;
        # round-robin counter breaks ties so equal queues still spread
        a = random.randrange(n)
        b = random.randrange(n - 1)
        if b >= a:
            b += 1
        qa = queues[a]
        qb = queues[b]
        if qa == qb:
            self._rr += 1
            winner = a if self._rr % 2 else b
        else:
            winner = a if qa < qb else b
        return ids[winner], view.handles[winner]

    def _pick_slow(self, deployment: str, affinity: Optional[int],
                   exclude: FrozenSet[str],
                   deadline_ts: Optional[float]):
        """Failover / cold paths: exclusion sets and empty views (waiting
        for the first replica to come RUNNING, bounded by the request
        deadline)."""
        deadline = time.time() + 30
        if deadline_ts is not None:
            deadline = min(deadline, deadline_ts)
        while True:
            view = self._views.get(deployment)
            if view is not None and view.ids:
                kept = [
                    i for i in range(len(view.ids))
                    if view.ids[i] not in exclude
                ]
                if not kept:
                    # exclusion would leave no candidate: a 1-replica
                    # deployment's restart is still worth a retry
                    kept = list(range(len(view.ids)))
                if len(kept) == 1:
                    i = kept[0]
                    return view.ids[i], view.handles[i]
                if affinity is not None:
                    i = view.ring.lookup_excluding(affinity, exclude)
                    if i not in kept:
                        i = random.choice(kept)
                    j = random.choice([k for k in kept if k != i])
                    if view.queues[i] <= view.queues[j] + self._AFFINITY_SLACK:
                        return view.ids[i], view.handles[i]
                    return view.ids[j], view.handles[j]
                a, b = random.sample(kept, 2)
                qa, qb = view.queues[a], view.queues[b]
                if qa == qb:
                    self._rr += 1
                    winner = a if self._rr % 2 else b
                else:
                    winner = a if qa < qb else b
                return view.ids[winner], view.handles[winner]
            if time.time() > deadline:
                raise RuntimeError(
                    f"no running replicas for deployment {deployment!r}"
                )
            time.sleep(0.1)
            self._refresh(force=True)


class DeploymentHandle:
    def __init__(self, controller, app_name: str, deployment: str,
                 method: str = "__call__", multiplexed_model_id: str = "",
                 stream: bool = False, prefix_affinity_tokens: int = 0,
                 timeout_s: Optional[float] = None,
                 _router: Optional[list] = None):
        self._controller = controller
        self._app_name = app_name
        self._deployment = deployment
        self._method = method
        self._multiplexed_model_id = multiplexed_model_id
        self._stream = stream
        # > 0: hash this many leading prompt tokens of each request and
        # bias replica picking toward the hash's replica (prefix-cache
        # affinity); 0 disables
        self._prefix_affinity_tokens = prefix_affinity_tokens
        # per-request deadline; None defers to the deployment's
        # RequestRouterConfig.default_timeout_s
        self._timeout_s = timeout_s
        # the router depends only on (controller, app_name), both immutable
        # across options()/method handles — a shared mutable holder means
        # whichever handle first routes a request creates the Router and all
        # derived handles reuse its cached routing table
        self._router_holder: list = _router if _router is not None else [None]

    def options(self, *, method_name: Optional[str] = None,
                multiplexed_model_id: Optional[str] = None,
                stream: Optional[bool] = None,
                prefix_affinity_tokens: Optional[int] = None,
                timeout_s: Optional[float] = None) -> "DeploymentHandle":
        return DeploymentHandle(
            self._controller,
            self._app_name,
            self._deployment,
            method_name if method_name is not None else self._method,
            multiplexed_model_id
            if multiplexed_model_id is not None
            else self._multiplexed_model_id,
            stream if stream is not None else self._stream,
            prefix_affinity_tokens
            if prefix_affinity_tokens is not None
            else self._prefix_affinity_tokens,
            timeout_s if timeout_s is not None else self._timeout_s,
            _router=self._router_holder,
        )

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        # handle.other_method.remote(...) sugar
        return DeploymentHandle(
            self._controller, self._app_name, self._deployment, name,
            self._multiplexed_model_id, self._stream,
            self._prefix_affinity_tokens, self._timeout_s,
            _router=self._router_holder,
        )

    def remote(self, *args, **kwargs):
        if self._router_holder[0] is None:
            self._router_holder[0] = Router(self._controller, self._app_name)
        router: Router = self._router_holder[0]
        router_cfg = router.router_config(self._deployment)
        # handle-level options() wins; otherwise the deployment's
        # RequestRouterConfig.prefix_affinity_tokens (distributed through
        # the routing table) turns affinity on for every router — proxies
        # included — with no per-call-site configuration
        tokens = self._prefix_affinity_tokens or int(
            router_cfg.get("prefix_affinity_tokens", 0) or 0
        )
        affinity = None
        if self._multiplexed_model_id:
            # adapter-id affinity WINS over prefix affinity: a multiplexed
            # deployment (multi-tenant LoRA serving) keeps each tenant hot
            # on few replicas — the adapter stays resident in their slot
            # banks and that tenant's prefixes concentrate in their radix,
            # so both the adapter hit rate AND the prefix hit rate ride
            # the same rendezvous bias
            affinity = zlib.crc32(
                ("adapter:" + self._multiplexed_model_id).encode()
            )
        elif tokens > 0:
            affinity = _prefix_affinity_key(args, kwargs, tokens)
        timeout_s = self._timeout_s
        if timeout_s is None:
            timeout_s = router_cfg.get("default_timeout_s", 60.0)
        deadline_ts = time.time() + timeout_s if timeout_s else None
        trace_ctx = _tracing.inject_context()  # None on the untraced path
        route_wall = time.time()
        route_t0 = time.perf_counter()
        rid, replica = router.pick(
            self._deployment, affinity, deadline_ts=deadline_ts
        )
        if trace_ctx is not None:
            _tracing.emit_span(
                "serve.route", trace_ctx, route_wall,
                time.perf_counter() - route_t0,
                deployment=self._deployment, replica=rid,
                affinity=affinity is not None,
            )
        metadata: Dict[str, Any] = {}
        if trace_ctx is not None:
            # the trace rides the request: the replica adopts it so its
            # admission/engine/kvcache spans join this caller's trace
            metadata["trace_ctx"] = trace_ctx
        if self._multiplexed_model_id:
            metadata["multiplexed_model_id"] = self._multiplexed_model_id
        if affinity is not None:
            # the key rides with the request so the replica can count the
            # distinct prefixes recently routed to it — the controller's
            # scale-down victim signal (drain the fewest-prefixes replica)
            metadata["affinity_key"] = affinity
        if deadline_ts is not None:
            # the deadline rides WITH the request so the replica can reject
            # dead-on-arrival work; retries inherit the same absolute
            # deadline (remaining budget, not a fresh timeout)
            metadata["deadline_ts"] = deadline_ts
            metadata["timeout_s"] = timeout_s
        # response chaining (reference: passing DeploymentResponse into a
        # downstream .remote — serve/handle.py): a response argument becomes
        # its ObjectRef, which the task-arg machinery resolves to the VALUE
        # before the replica method runs — no blocking .result() in between
        def chain(x):
            return x._to_object_ref() if isinstance(x, DeploymentResponse) else x

        args = tuple(chain(a) for a in args)
        kwargs = {k: chain(v) for k, v in kwargs.items()}
        ctx = _RequestContext(
            router, self._deployment, self._method, args, kwargs,
            metadata or None, affinity, self._stream, deadline_ts,
            router_cfg, rid,
        )
        if self._stream:
            # replica-side async generator shipped item-by-item through the
            # runtime's streaming-generator path (ObjectRefGenerator)
            ref_gen = _submit(replica, ctx)
            return DeploymentResponseGenerator(
                ref_gen, timeout_s=timeout_s or 60.0, ctx=ctx
            )
        ref = _submit(replica, ctx)
        return DeploymentResponse(ref, ctx=ctx)

    def __reduce__(self):
        return (
            DeploymentHandle,
            (self._controller, self._app_name, self._deployment, self._method,
             self._multiplexed_model_id, self._stream,
             self._prefix_affinity_tokens, self._timeout_s),
        )
