"""ray_tpu.serve: scalable model serving (reference: python/ray/serve).

Controller actor reconciles deployments into replica actors; handles route
requests with power-of-two-choices; an aiohttp proxy terminates HTTP; the
queue-length autoscaler resizes replica sets — including TPU replicas that
reserve chips via ``ray_actor_options={"num_tpus": N}``.
"""

from .api import (
    Application,
    Deployment,
    delete,
    deployment,
    get_app_handle,
    get_deployment_handle,
    grpc_proxy_address,
    ingress,
    run,
    shutdown,
    start,
    status,
)
from .autoscale import AutoscalePolicy
from .batching import batch
from .grpc_proxy import grpc_call
from .config import AutoscalingConfig, DeploymentConfig, RequestRouterConfig
from .handle import (
    DeploymentHandle,
    DeploymentResponse,
    DeploymentResponseGenerator,
)
from .multiplex import get_multiplexed_model_id, multiplexed

__all__ = [
    "batch",
    "grpc_call",
    "grpc_proxy_address",
    "multiplexed",
    "get_multiplexed_model_id",
    "deployment",
    "Deployment",
    "Application",
    "run",
    "start",
    "delete",
    "shutdown",
    "status",
    "get_app_handle",
    "get_deployment_handle",
    "DeploymentHandle",
    "DeploymentResponse",
    "DeploymentResponseGenerator",
    "ingress",
    "AutoscalePolicy",
    "AutoscalingConfig",
    "DeploymentConfig",
    "RequestRouterConfig",
]
