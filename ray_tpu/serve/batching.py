"""Dynamic request batching: @serve.batch.

Role-equivalent of the reference's serve.batch (python/ray/serve/batching.py):
individual async calls accumulate into a list; the wrapped callable runs once
per batch (``async def fn(self, items: List)`` -> list of results, one per
caller) when the batch fills or the wait timeout fires. On TPU replicas this
is the lever that turns single requests into MXU-sized batches.
"""

from __future__ import annotations

import asyncio
import functools
import inspect
from typing import Any, Callable, List, Optional


class _BatchQueue:
    def __init__(self, fn: Callable, max_batch_size: int, wait_s: float):
        self._fn = fn
        self._max = max_batch_size
        self._wait_s = wait_s
        self._pending: List[tuple] = []  # (item, future)
        self._timer: Optional[asyncio.TimerHandle] = None
        # strong refs: the loop only weakly references tasks, and a collected
        # batch task would strand every caller future in it
        self._tasks: set = set()

    async def submit(self, item: Any):
        loop = asyncio.get_running_loop()
        fut: asyncio.Future = loop.create_future()
        self._pending.append((item, fut))
        if len(self._pending) >= self._max:
            self._flush()
        elif self._timer is None:
            self._timer = loop.call_later(self._wait_s, self._flush)
        return await fut

    def _flush(self):
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None
        if not self._pending:
            return
        batch, self._pending = self._pending, []
        task = asyncio.ensure_future(self._run(batch))
        self._tasks.add(task)
        task.add_done_callback(self._tasks.discard)

    async def _run(self, batch: List[tuple]):
        items = [item for item, _f in batch]
        try:
            results = await self._fn(items)
            if results is None or len(results) != len(items):
                raise ValueError(
                    "@serve.batch function must return one result per input "
                    f"(got {None if results is None else len(results)} for "
                    f"{len(items)} inputs)"
                )
            for (_item, fut), res in zip(batch, results):
                if not fut.done():
                    fut.set_result(res)
        except Exception as e:  # noqa: BLE001 — error fans out to all callers
            for _item, fut in batch:
                if not fut.done():
                    fut.set_exception(e)


def batch(_fn=None, *, max_batch_size: int = 10,
          batch_wait_timeout_s: float = 0.01):
    """Decorator: ``@serve.batch`` / ``@serve.batch(max_batch_size=32,
    batch_wait_timeout_s=0.05)`` on an async method taking a list."""

    def deco(fn):
        if not inspect.iscoroutinefunction(fn):
            raise TypeError("@serve.batch requires an async def function")
        params = list(inspect.signature(fn).parameters)
        is_method = bool(params) and params[0] == "self"
        attr = f"__serve_batch_queue_{fn.__name__}"

        if is_method:
            async def wrapper(self, item):
                q = getattr(self, attr, None)
                if q is None:
                    async def bound(items):
                        return await fn(self, items)

                    q = _BatchQueue(bound, max_batch_size, batch_wait_timeout_s)
                    setattr(self, attr, q)
                return await q.submit(item)
        else:
            state = {}

            async def wrapper(item):
                q = state.get("q")
                if q is None:
                    q = state["q"] = _BatchQueue(
                        fn, max_batch_size, batch_wait_timeout_s
                    )
                return await q.submit(item)

        return functools.wraps(fn)(wrapper)

    if _fn is not None:
        return deco(_fn)
    return deco
