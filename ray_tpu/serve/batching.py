"""Dynamic request batching: @serve.batch.

Role-equivalent of the reference's serve.batch (python/ray/serve/batching.py):
individual async calls accumulate into a list; the wrapped callable runs once
per batch (``async def fn(self, items: List)`` -> list of results, one per
caller) when the batch fills or the wait timeout fires. On TPU replicas this
is the lever that turns single requests into MXU-sized batches.

Batching composes with @serve.multiplexed: the pending queue is PARTITIONED
by the caller's multiplexed model id, so one flush never mixes requests for
different models, and the batch task re-enters the model-id context before
running the handler — ``get_multiplexed_model_id()`` inside the batch
function returns the batch's model id, not "" (the handler runs in a fresh
task, outside every caller's contextvar scope, so it must be restored
explicitly).
"""

from __future__ import annotations

import asyncio
import functools
import inspect
from typing import Any, Callable, Dict, List, Optional


class _BatchQueue:
    def __init__(self, fn: Callable, max_batch_size: int, wait_s: float):
        self._fn = fn
        self._max = max_batch_size
        self._wait_s = wait_s
        # model id -> [(item, future)]: per-model queues so a flush is
        # always single-model (the "" partition is the unmultiplexed path)
        self._pending: Dict[str, List[tuple]] = {}
        self._timers: Dict[str, asyncio.TimerHandle] = {}
        # strong refs: the loop only weakly references tasks, and a collected
        # batch task would strand every caller future in it
        self._tasks: set = set()

    async def submit(self, item: Any):
        from .multiplex import get_multiplexed_model_id

        model_id = get_multiplexed_model_id()
        loop = asyncio.get_running_loop()
        fut: asyncio.Future = loop.create_future()
        pending = self._pending.setdefault(model_id, [])
        pending.append((item, fut))
        if len(pending) >= self._max:
            self._flush(model_id)
        elif model_id not in self._timers:
            self._timers[model_id] = loop.call_later(
                self._wait_s, self._flush, model_id
            )
        return await fut

    def _flush(self, model_id: str):
        timer = self._timers.pop(model_id, None)
        if timer is not None:
            timer.cancel()
        batch = self._pending.pop(model_id, None)
        if not batch:
            return
        task = asyncio.ensure_future(self._run(batch, model_id))
        self._tasks.add(task)
        task.add_done_callback(self._tasks.discard)

    async def _run(self, batch: List[tuple], model_id: str):
        from .multiplex import _set_multiplexed_model_id

        # this task copied whatever context ensure_future saw at flush time
        # (a timer callback or one arbitrary caller) — pin the batch's model
        # id so the handler's get_multiplexed_model_id()/get_model() work
        _set_multiplexed_model_id(model_id)
        items = [item for item, _f in batch]
        try:
            results = await self._fn(items)
            if results is None or len(results) != len(items):
                raise ValueError(
                    "@serve.batch function must return one result per input "
                    f"(got {None if results is None else len(results)} for "
                    f"{len(items)} inputs)"
                )
            for (_item, fut), res in zip(batch, results):
                if not fut.done():
                    fut.set_result(res)
        except Exception as e:  # noqa: BLE001 — error fans out to all callers
            for _item, fut in batch:
                if not fut.done():
                    fut.set_exception(e)


def batch(_fn=None, *, max_batch_size: int = 10,
          batch_wait_timeout_s: float = 0.01):
    """Decorator: ``@serve.batch`` / ``@serve.batch(max_batch_size=32,
    batch_wait_timeout_s=0.05)`` on an async method taking a list."""

    def deco(fn):
        if not inspect.iscoroutinefunction(fn):
            raise TypeError("@serve.batch requires an async def function")
        params = list(inspect.signature(fn).parameters)
        is_method = bool(params) and params[0] == "self"
        attr = f"__serve_batch_queue_{fn.__name__}"

        if is_method:
            async def wrapper(self, item):
                q = getattr(self, attr, None)
                if q is None:
                    async def bound(items):
                        return await fn(self, items)

                    q = _BatchQueue(bound, max_batch_size, batch_wait_timeout_s)
                    setattr(self, attr, q)
                return await q.submit(item)
        else:
            state = {}

            async def wrapper(item):
                q = state.get("q")
                if q is None:
                    q = state["q"] = _BatchQueue(
                        fn, max_batch_size, batch_wait_timeout_s
                    )
                return await q.submit(item)

        return functools.wraps(fn)(wrapper)

    if _fn is not None:
        return deco(_fn)
    return deco
