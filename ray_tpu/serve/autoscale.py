"""SLO-driven serve autoscaling: policy, pressure signals, decisions.

The queue-depth ``AutoscalingConfig`` (config.py) scales on a single
instantaneous signal. This module is the closed-loop successor: an
``AutoscalePolicy`` names SLO targets (TTFT p99, queue depth per replica,
shed rate) and the serve controller evaluates them every ``interval_s``
against live telemetry — instantaneous queue depth from its own replica
polls (sub-second), TTFT bucket *deltas* and shed-counter *deltas* from
the metrics push plane (the cumulative histograms never decay, so only
windowed deltas reflect current pressure).

``evaluate()`` is a pure function of (policy, mutable state, signals,
now) so the hysteresis/cooldown state machine is unit-testable without a
cluster. Applied decisions are recorded three ways: the ``autoscale_*``
metrics (util/metrics.py), the controller's in-memory event log (actor
method ``autoscale_log``), and a bounded JSON mirror in the GCS KV under
``serve:autoscale_log`` so the dashboard and CLI can read it without an
actor handle.
"""

from __future__ import annotations

from dataclasses import asdict, dataclass, field
from typing import Dict, List, Optional

from ..runtime.gcs import keys as gcs_keys
from ..util.metrics import merged_histogram, quantile_from_buckets

AUTOSCALE_LOG_KEY = gcs_keys.SERVE_AUTOSCALE_LOG
LOG_LIMIT = 200


@dataclass
class AutoscalePolicy:
    """SLO targets + damping for one deployment. A target of 0 disables
    that pressure signal; pressure on ANY enabled signal counts."""

    min_replicas: int = 1
    max_replicas: int = 4
    interval_s: float = 1.0
    # pressure signals
    target_ttft_p99_ms: float = 0.0
    target_queue_per_replica: float = 4.0
    max_shed_per_interval: float = 0.0
    # damping: consecutive pressured/idle evaluations required, floors on
    # time between decisions, and per-decision step bounds
    up_hysteresis: int = 1
    down_hysteresis: int = 3
    idle_queue_per_replica: float = 0.5
    cooldown_up_s: float = 3.0
    cooldown_down_s: float = 10.0
    scale_up_step: int = 1
    scale_down_step: int = 1

    def __post_init__(self):
        if self.min_replicas < 1:
            raise ValueError("min_replicas must be >= 1")
        if self.max_replicas < self.min_replicas:
            raise ValueError("max_replicas must be >= min_replicas")
        if self.interval_s <= 0:
            raise ValueError("interval_s must be > 0")

    def as_dict(self) -> dict:
        return asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "AutoscalePolicy":
        return cls(**d)


@dataclass
class AutoscaleSignals:
    """One evaluation's inputs, also embedded in the decision event log so
    every transition is explainable after the fact."""

    queue_depth: float = 0.0
    queue_per_replica: float = 0.0
    shed_delta: float = 0.0
    ttft_p99_ms: Optional[float] = None
    running: int = 0
    starting: int = 0
    target: int = 0

    def as_dict(self) -> dict:
        return asdict(self)


@dataclass
class AutoscaleState:
    """Mutable per-deployment evaluation state held by the controller."""

    last_eval_ts: float = 0.0
    pressured_evals: int = 0
    idle_evals: int = 0
    breach_started_ts: float = 0.0
    idle_started_ts: float = 0.0
    last_up_ts: float = 0.0
    last_down_ts: float = 0.0
    # delta baselines for the cumulative push-plane series
    last_shed_total: float = 0.0
    last_ttft_counts: Optional[List[float]] = None
    last_ttft_source: str = ""


@dataclass
class AutoscaleDecision:
    direction: str  # "up" | "down"
    from_replicas: int
    to_replicas: int
    reason: str
    breach_age_s: float = 0.0


def evaluate(
    policy: AutoscalePolicy,
    st: AutoscaleState,
    sig: AutoscaleSignals,
    now: float,
) -> Optional[AutoscaleDecision]:
    """One tick of the policy state machine; mutates ``st``, returns the
    decision to apply (already cooldown/step/bound-checked) or None."""
    reasons = []
    if (
        policy.target_queue_per_replica > 0
        and sig.queue_per_replica > policy.target_queue_per_replica
    ):
        reasons.append(
            f"queue/replica {sig.queue_per_replica:.1f} > "
            f"{policy.target_queue_per_replica:g}"
        )
    if sig.shed_delta > policy.max_shed_per_interval:
        reasons.append(
            f"sheds {sig.shed_delta:.0f} > {policy.max_shed_per_interval:g}"
        )
    if (
        policy.target_ttft_p99_ms > 0
        and sig.ttft_p99_ms is not None
        and sig.ttft_p99_ms > policy.target_ttft_p99_ms
    ):
        reasons.append(
            f"ttft_p99 {sig.ttft_p99_ms:.0f}ms > "
            f"{policy.target_ttft_p99_ms:g}ms"
        )

    pressured = bool(reasons)
    idle = (
        not pressured
        and sig.queue_per_replica <= policy.idle_queue_per_replica
        and sig.shed_delta == 0
    )
    if pressured:
        if st.pressured_evals == 0:
            st.breach_started_ts = now
        st.pressured_evals += 1
        st.idle_evals = 0
    elif idle:
        if st.idle_evals == 0:
            st.idle_started_ts = now
        st.idle_evals += 1
        st.pressured_evals = 0
    else:
        st.pressured_evals = 0
        st.idle_evals = 0

    if (
        pressured
        and st.pressured_evals >= policy.up_hysteresis
        and sig.target < policy.max_replicas
        and sig.starting == 0  # let in-flight scale-ups land first
        and now - st.last_up_ts >= policy.cooldown_up_s
    ):
        to = min(
            policy.max_replicas, sig.target + max(1, policy.scale_up_step)
        )
        st.pressured_evals = 0
        st.last_up_ts = now
        return AutoscaleDecision(
            "up", sig.target, to, "; ".join(reasons),
            now - st.breach_started_ts,
        )

    if (
        idle
        and st.idle_evals >= policy.down_hysteresis
        and sig.target > policy.min_replicas
        and now - max(st.last_up_ts, st.last_down_ts)
        >= policy.cooldown_down_s
    ):
        to = max(
            policy.min_replicas, sig.target - max(1, policy.scale_down_step)
        )
        st.idle_evals = 0
        st.last_down_ts = now
        return AutoscaleDecision(
            "down",
            sig.target,
            to,
            f"idle: queue/replica {sig.queue_per_replica:.2f} <= "
            f"{policy.idle_queue_per_replica:g}",
            now - st.idle_started_ts,
        )
    return None


# ---------------------------------------------------------------------------
# Push-plane signal extraction. Counters and histogram buckets are
# cumulative since process start, so the controller keeps per-deployment
# baselines in AutoscaleState and reads windowed deltas.
# ---------------------------------------------------------------------------


def shed_total(payloads: List[dict], deployment: str) -> float:
    """Cumulative serve_shed_total across the cluster for one deployment."""
    import json as _json

    total = 0.0
    for payload in payloads:
        for snap in payload.get("metrics", []):
            if snap.get("name") != "serve_shed_total":
                continue
            for tag_json, value in snap.get("values", {}).items():
                tags = dict(
                    zip(snap.get("tag_keys", ()), _json.loads(tag_json))
                )
                if tags.get("deployment") == deployment:
                    total += value
    return total


def ttft_p99_ms(
    payloads: List[dict], deployment: str, st: AutoscaleState
) -> Optional[float]:
    """TTFT p99 over the window since the last evaluation, from merged
    bucket deltas. Prefers the deployment-tagged serve_ttft_seconds
    histogram; falls back to the engine-side kvcache_ttft_ms buckets when
    the deployment has recorded nothing (e.g. pre-existing engines).
    Returns None when no new samples landed in the window."""
    source = "serve"
    scale = 1000.0
    m = merged_histogram(
        payloads, "serve_ttft_seconds", {"deployment": deployment}
    )
    if m is None or not m["count"]:
        source = "kvcache"
        scale = 1.0
        m = merged_histogram(payloads, "kvcache_ttft_ms")
    if m is None:
        st.last_ttft_counts = None
        st.last_ttft_source = ""
        return None
    counts = m["counts"]
    prev = st.last_ttft_counts
    if (
        st.last_ttft_source == source
        and prev is not None
        and len(prev) == len(counts)
    ):
        window = [max(0.0, a - b) for a, b in zip(counts, prev)]
    else:
        window = list(counts)
    st.last_ttft_counts = list(counts)
    st.last_ttft_source = source
    est = quantile_from_buckets(m["boundaries"], window, 0.99)
    return None if est is None else est * scale
