"""Replica actor: hosts one copy of a deployment's user callable.

Role-equivalent of the reference's ReplicaActor
(python/ray/serve/_private/replica.py:1210): runs user __init__ once,
serves requests while tracking ongoing-request count (the autoscaling
metric), supports reconfigure(user_config) and health checks.
"""

from __future__ import annotations

import asyncio
import inspect
import time
from typing import Any, Dict, Optional


class Replica:
    """The actor class; created by the controller via make_actor_class."""

    def __init__(
        self,
        deployment_name: str,
        replica_id: str,
        cls_or_fn_bytes: bytes,
        init_args: tuple,
        init_kwargs: dict,
        user_config: Any,
    ):
        from .._internal import serialization

        from concurrent.futures import ThreadPoolExecutor

        self._deployment_name = deployment_name
        self._replica_id = replica_id
        self._ongoing = 0
        self._total_served = 0
        self._pool = ThreadPoolExecutor(
            max_workers=8, thread_name_prefix=f"replica-{replica_id}"
        )
        target = serialization.loads(cls_or_fn_bytes)
        if inspect.isclass(target):
            self._callable = target(*init_args, **init_kwargs)
        else:
            self._callable = target
        self._is_function = not inspect.isclass(target)
        if user_config is not None:
            self._reconfigure_sync(user_config)

    # -- request path --------------------------------------------------------

    async def _prepare_call(self, method: str, args: tuple, kwargs: dict,
                            metadata: Optional[dict]):
        """Shared request setup: multiplex context, chained-response
        resolution, target-callable lookup."""
        if metadata and metadata.get("multiplexed_model_id"):
            from .multiplex import _set_multiplexed_model_id

            _set_multiplexed_model_id(metadata["multiplexed_model_id"])
        # response chaining (reference: DeploymentResponse args resolve to
        # their values before the method runs): the handle converted chained
        # responses to ObjectRefs; they arrive nested inside the args tuple
        # (only top-level task args auto-resolve), so resolve here
        from ..object_ref import ObjectRef

        if any(isinstance(a, ObjectRef) for a in args) or any(
            isinstance(v, ObjectRef) for v in kwargs.values()
        ):
            from .. import api as ray_api

            async def resolve(x):
                if isinstance(x, ObjectRef):
                    loop = asyncio.get_running_loop()
                    return await loop.run_in_executor(
                        None, lambda: ray_api.get(x, timeout=60)
                    )
                return x

            args = tuple([await resolve(a) for a in args])
            kwargs = {k: await resolve(v) for k, v in kwargs.items()}
        if self._is_function:
            fn = self._callable
        else:
            fn = getattr(self._callable, method or "__call__")
        return fn, args, kwargs

    async def handle_request(self, method: str, args: tuple, kwargs: dict,
                             metadata: Optional[dict] = None):
        self._ongoing += 1
        try:
            fn, args, kwargs = await self._prepare_call(
                method, args, kwargs, metadata
            )
            if inspect.iscoroutinefunction(fn):
                return await fn(*args, **kwargs)
            # sync user code must not block the worker's event loop (it
            # services RPC + heartbeats); run it on the request pool. The
            # context carries the multiplexed model id across the thread hop.
            import contextvars

            loop = asyncio.get_running_loop()
            ctx = contextvars.copy_context()
            return await loop.run_in_executor(
                self._pool, lambda: ctx.run(fn, *args, **kwargs)
            )
        finally:
            self._ongoing -= 1
            self._total_served += 1

    async def handle_request_stream(self, method: str, args: tuple,
                                    kwargs: dict,
                                    metadata: Optional[dict] = None):
        """Streaming request path (reference: replica.py generator handling
        behind DeploymentResponseGenerator, serve/handle.py:557): the user
        method must be a (sync or async) generator; every yielded item ships
        to the caller through the runtime's streaming-generator machinery as
        soon as it exists."""
        _SENTINEL = object()
        self._ongoing += 1
        try:
            fn, args, kwargs = await self._prepare_call(
                method, args, kwargs, metadata
            )
            if inspect.isasyncgenfunction(fn):
                async for item in fn(*args, **kwargs):
                    yield item
                return
            if inspect.iscoroutinefunction(fn):
                raise TypeError(
                    f"stream=True requires a generator method; "
                    f"{method!r} is a coroutine function"
                )
            import contextvars

            loop = asyncio.get_running_loop()
            ctx = contextvars.copy_context()
            gen = await loop.run_in_executor(
                self._pool, lambda: ctx.run(fn, *args, **kwargs)
            )
            if not inspect.isgenerator(gen):
                raise TypeError(
                    f"stream=True requires a generator method; {method!r} "
                    f"returned {type(gen).__name__}"
                )
            # drive the sync generator on the pool: each next() may block on
            # user compute and must stay off the worker's event loop. Every
            # step runs under the copied context — generator bodies see the
            # context active at each next(), not at creation, so a bare
            # next() would drop the multiplexed-model-id var.
            while True:
                item = await loop.run_in_executor(
                    self._pool, lambda: ctx.run(next, gen, _SENTINEL)
                )
                if item is _SENTINEL:
                    return
                yield item
        finally:
            self._ongoing -= 1
            self._total_served += 1

    # -- control plane -------------------------------------------------------

    def get_metrics(self) -> Dict[str, Any]:
        return {
            "replica_id": self._replica_id,
            "queue_len": self._ongoing,
            "total_served": self._total_served,
        }

    def check_health(self) -> bool:
        user_check = getattr(self._callable, "check_health", None)
        if user_check is not None:
            user_check()
        return True

    def _reconfigure_sync(self, user_config):
        rec = getattr(self._callable, "reconfigure", None)
        if rec is not None:
            rec(user_config)

    def reconfigure(self, user_config) -> bool:
        self._reconfigure_sync(user_config)
        return True

    async def prepare_for_shutdown(self, timeout_s: float = 5.0) -> bool:
        """Drain: wait for ongoing requests to finish (reference:
        graceful_shutdown_timeout_s semantics)."""
        deadline = time.time() + timeout_s
        while self._ongoing > 0 and time.time() < deadline:
            await asyncio.sleep(0.05)
        # run user cleanup before the controller hard-kills this actor;
        # an explicit shutdown() wins over __del__ (which GC may also run)
        for hook in ("shutdown", "__del__"):
            fn = getattr(type(self._callable), hook, None)
            if fn is not None:
                try:
                    result = fn(self._callable)
                    if inspect.iscoroutine(result):
                        await result
                except Exception:
                    pass
                break
        return self._ongoing == 0
