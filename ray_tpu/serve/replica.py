"""Replica actor: hosts one copy of a deployment's user callable.

Role-equivalent of the reference's ReplicaActor
(python/ray/serve/_private/replica.py:1210): runs user __init__ once,
serves requests while tracking ongoing-request count (the autoscaling
metric), supports reconfigure(user_config) and health checks.

Fault-tolerant data plane: every request passes admission control before
user code runs — dead-on-arrival requests (deadline already passed) are
rejected without computing, DRAINING replicas refuse new work with a
retryable typed error, and once ``max_ongoing_requests`` are executing
further requests wait in a bounded queue (``max_queued_requests``) past
which the replica sheds fast with ``BackPressureError`` instead of letting
the caller's 60 s timeout pile up.
"""

from __future__ import annotations

import asyncio
import inspect
import os
import time
from typing import Any, Dict, Optional


class Replica:
    """The actor class; created by the controller via make_actor_class."""

    def __init__(
        self,
        deployment_name: str,
        replica_id: str,
        cls_or_fn_bytes: bytes,
        init_args: tuple,
        init_kwargs: dict,
        user_config: Any,
        max_ongoing_requests: int = 100,
        max_queued_requests: int = 64,
    ):
        from collections import OrderedDict

        from .._internal import serialization

        from concurrent.futures import ThreadPoolExecutor

        warmup_start = time.perf_counter()
        self._deployment_name = deployment_name
        self._replica_id = replica_id
        self._ongoing = 0
        self._queued = 0
        self._total_served = 0
        self._shed_total = 0
        self._doa_total = 0
        self._draining = False
        self._max_ongoing = max(1, int(max_ongoing_requests))
        self._max_queued = max(0, int(max_queued_requests))
        # set on every request completion so queued waiters re-check for a
        # free slot (created lazily: __init__ may run before a loop exists)
        self._slot_free: Optional[asyncio.Event] = None
        self._pool = ThreadPoolExecutor(
            max_workers=8, thread_name_prefix=f"replica-{replica_id}"
        )
        # recently-routed distinct prefix-affinity keys (bounded recency
        # map key -> last-seen ts); the controller reads the live count as
        # its scale-down victim signal
        self._affinity_keys: "OrderedDict[int, float]" = OrderedDict()
        target = serialization.loads(cls_or_fn_bytes)
        if inspect.isclass(target):
            self._callable = target(*init_args, **init_kwargs)
        else:
            self._callable = target
        self._is_function = not inspect.isclass(target)
        if user_config is not None:
            self._reconfigure_sync(user_config)
        # cold-start accounting: everything between actor start and
        # ready-to-serve counts — deserialize, user __init__ (weight-plane
        # resolution for LLM replicas happens there), reconfigure, and an
        # optional synchronous warmup() hook. check_health (and therefore
        # the STARTING -> RUNNING transition) cannot run before this
        # completes, so RUNNING always implies warmed-up.
        warmup_hook = getattr(self._callable, "warmup", None)
        if warmup_hook is not None and not inspect.iscoroutinefunction(
            warmup_hook
        ):
            warmup_hook()
        self._warmup_s = time.perf_counter() - warmup_start
        from ..util.metrics import record_serve_replica_warmup

        record_serve_replica_warmup(deployment_name, self._warmup_s)
        # per-replica telemetry series (util/timeseries.py): TTFT recorded
        # inline per request, queue depth pulled by a sampler on the push
        # cadence so the request hot path never pays for it
        self._ttft_series = None
        try:
            from ..util import timeseries as _ts

            _ts.register_series(
                _ts.SERVE_QUEUE_DEPTH,
                labels={
                    "deployment": deployment_name,
                    "replica": replica_id,
                },
                sampler=lambda: float(self._queued),
            )
        except Exception:
            pass  # telemetry is best-effort; replicas start regardless

    def _ttft_telemetry(self, ttft_s: float, trace_id: Optional[str]):
        """Per-replica TTFT history; the point carries the request's
        trace_id as an exemplar so a firing TTFT alert names a concrete
        slow request. Never raises."""
        try:
            if self._ttft_series is None:
                from ..util import timeseries as _ts

                self._ttft_series = _ts.register_series(
                    _ts.SERVE_TTFT_S,
                    labels={
                        "deployment": self._deployment_name,
                        "replica": self._replica_id,
                    },
                )
            self._ttft_series.record(ttft_s, exemplar=trace_id)
        except Exception:
            pass

    _AFFINITY_KEY_WINDOW_S = 60.0
    _AFFINITY_KEY_CAP = 4096

    def _note_affinity(self, metadata: Optional[dict]):
        key = (metadata or {}).get("affinity_key")
        if key is None:
            return
        self._affinity_keys.pop(key, None)
        self._affinity_keys[key] = time.time()
        while len(self._affinity_keys) > self._AFFINITY_KEY_CAP:
            self._affinity_keys.popitem(last=False)

    def _live_affinity_keys(self) -> int:
        cutoff = time.time() - self._AFFINITY_KEY_WINDOW_S
        while self._affinity_keys:
            key, ts = next(iter(self._affinity_keys.items()))
            if ts >= cutoff:
                break
            self._affinity_keys.popitem(last=False)
        return len(self._affinity_keys)

    # -- admission control ----------------------------------------------------

    def _deadline_of(self, metadata: Optional[dict]) -> Optional[float]:
        if not metadata:
            return None
        d = metadata.get("deadline_ts")
        return float(d) if d is not None else None

    def _check_doa(self, metadata: Optional[dict]):
        """Reject dead-on-arrival work: if the caller's deadline already
        passed, nobody is waiting for the result — don't compute it."""
        deadline = self._deadline_of(metadata)
        if deadline is not None and time.time() >= deadline:
            from ..exceptions import DeadlineExceededError
            from ..util.metrics import record_serve_doa

            self._doa_total += 1
            record_serve_doa(self._deployment_name)
            timeout_s = float((metadata or {}).get("timeout_s") or 0.0)
            raise DeadlineExceededError(
                deployment=self._deployment_name,
                elapsed_s=time.time() - (deadline - timeout_s)
                if timeout_s
                else 0.0,
                timeout_s=timeout_s,
                where=f"replica {self._replica_id} admission",
            )

    async def _admit(self, metadata: Optional[dict]):
        """Admission control, runs BEFORE user code and before the request
        counts as accepted. Order matters: drain check first (stale routers
        get a retryable error), then DOA, then capacity. Raises fast —
        shedding must cost milliseconds, not a timeout."""
        self._check_fenced()
        if self._draining:
            from ..exceptions import ReplicaDrainingError
            from ..util import events as _events

            _events.record_event(
                _events.DRAIN_REJECTED, deployment=self._deployment_name,
                replica=self._replica_id,
            )
            raise ReplicaDrainingError(self._replica_id)
        self._check_doa(metadata)
        if self._ongoing < self._max_ongoing:
            self._ongoing += 1
            return
        if self._queued >= self._max_queued:
            from ..exceptions import BackPressureError
            from ..util import events as _events
            from ..util.metrics import record_serve_shed

            self._shed_total += 1
            record_serve_shed(self._deployment_name)
            _events.record_event(
                _events.REQUEST_SHED, deployment=self._deployment_name,
                replica=self._replica_id, ongoing=self._ongoing,
                queued=self._queued,
            )
            raise BackPressureError(
                replica_id=self._replica_id,
                ongoing=self._ongoing,
                queued=self._queued,
                retry_after_s=0.1,
            )
        # wait for a slot; bounded by the request deadline (if any) so a
        # queued request never outlives its caller
        if self._slot_free is None:
            self._slot_free = asyncio.Event()
        deadline = self._deadline_of(metadata)
        self._queued += 1
        try:
            while True:
                self._check_fenced()
                if self._draining:
                    from ..exceptions import ReplicaDrainingError
                    from ..util import events as _events

                    _events.record_event(
                        _events.DRAIN_REJECTED,
                        deployment=self._deployment_name,
                        replica=self._replica_id, queued=True,
                    )
                    raise ReplicaDrainingError(self._replica_id)
                self._check_doa(metadata)
                if self._ongoing < self._max_ongoing:
                    self._ongoing += 1
                    return
                self._slot_free.clear()
                wait_s = 0.25
                if deadline is not None:
                    wait_s = min(wait_s, max(0.0, deadline - time.time()))
                try:
                    await asyncio.wait_for(
                        self._slot_free.wait(), timeout=wait_s + 0.001
                    )
                except asyncio.TimeoutError:
                    pass
        finally:
            self._queued -= 1

    def _check_fenced(self):
        """Split-brain guard: this replica's node lost GCS contact, so the
        controller may already be starting a replacement elsewhere. Reject
        with a retryable typed error so routers fail over instead of
        double-serving (or hanging on a partitioned node)."""
        from ..util import fencing

        if fencing.is_fenced():
            from ..exceptions import NodeFencedError

            _fenced, node_id, reason = fencing.fence_info()
            raise NodeFencedError(node_id, reason or "gcs unreachable")

    def _release(self):
        self._ongoing -= 1
        self._total_served += 1
        if self._slot_free is not None:
            self._slot_free.set()

    def _dequeue(self):
        self._queued -= 1

    # -- request path --------------------------------------------------------

    async def _prepare_call(self, method: str, args: tuple, kwargs: dict,
                            metadata: Optional[dict]):
        """Shared request setup: multiplex context, chained-response
        resolution, target-callable lookup."""
        if metadata and metadata.get("multiplexed_model_id"):
            from .multiplex import _set_multiplexed_model_id

            _set_multiplexed_model_id(metadata["multiplexed_model_id"])
        # response chaining (reference: DeploymentResponse args resolve to
        # their values before the method runs): the handle converted chained
        # responses to ObjectRefs; they arrive nested inside the args tuple
        # (only top-level task args auto-resolve), so resolve here
        from ..object_ref import ObjectRef

        if any(isinstance(a, ObjectRef) for a in args) or any(
            isinstance(v, ObjectRef) for v in kwargs.values()
        ):
            from .. import api as ray_api

            async def resolve(x):
                if isinstance(x, ObjectRef):
                    loop = asyncio.get_running_loop()
                    return await loop.run_in_executor(
                        None, lambda: ray_api.get(x, timeout=60)
                    )
                return x

            args = tuple([await resolve(a) for a in args])
            kwargs = {k: await resolve(v) for k, v in kwargs.items()}
        if self._is_function:
            fn = self._callable
        else:
            fn = getattr(self._callable, method or "__call__")
        return fn, args, kwargs

    async def handle_request(self, method: str, args: tuple, kwargs: dict,
                             metadata: Optional[dict] = None):
        from ..util import tracing as _tracing
        from ..util import watchdog as _watchdog
        from ..util.metrics import record_serve_ttft

        tctx = (metadata or {}).get("trace_ctx")
        t0 = time.perf_counter()
        wd_token = _watchdog.watch(
            "serve.request", timeout_s=(metadata or {}).get("timeout_s"),
            deployment=self._deployment_name, replica=self._replica_id,
        )
        try:
            if tctx is None and not _tracing.is_tracing_enabled():
                # untraced fast path: skip the span contextmanager entirely
                # — at ingress saturation even a no-op span's generator +
                # frame allocation shows up (the perf-smoke 5% guard)
                return await self._run_request(
                    method, args, kwargs, metadata, t0, None
                )
            # adopt the caller's trace: every span below (and anything user
            # code opens — the engine, kvcache) joins the request's trace
            with _tracing.request_span(
                "serve.replica", tctx, deployment=self._deployment_name,
                replica=self._replica_id, method=method or "__call__",
            ) as span_ctx:
                return await self._run_request(
                    method, args, kwargs, metadata, t0, span_ctx
                )
        finally:
            _watchdog.unwatch(wd_token)

    async def _run_request(self, method: str, args: tuple, kwargs: dict,
                           metadata: Optional[dict], t0: float,
                           span_ctx: Optional[dict]):
        from ..util import tracing as _tracing
        from ..util.metrics import record_serve_ttft

        admit_wall = time.time()
        try:
            await self._admit(metadata)
        except BaseException as exc:
            if span_ctx is not None:
                _tracing.emit_span(
                    "serve.admission", span_ctx, admit_wall,
                    time.perf_counter() - t0,
                    rejected=type(exc).__name__,
                )
            raise
        # admission span covers the bounded queue wait on purpose:
        # that wait IS the stage a slow request spent here
        if span_ctx is not None:
            _tracing.emit_span(
                "serve.admission", span_ctx, admit_wall,
                time.perf_counter() - t0,
                ongoing=self._ongoing, queued=self._queued,
            )
        self._note_affinity(metadata)
        try:
            fn, args, kwargs = await self._prepare_call(
                method, args, kwargs, metadata
            )
            if inspect.iscoroutinefunction(fn):
                result = await fn(*args, **kwargs)
            else:
                # sync user code must not block the worker's event
                # loop (it services RPC + heartbeats); run it on the
                # request pool. The context carries the multiplexed
                # model id AND the active trace context across the
                # thread hop.
                import contextvars

                loop = asyncio.get_running_loop()
                ctx = contextvars.copy_context()
                result = await loop.run_in_executor(
                    self._pool, lambda: ctx.run(fn, *args, **kwargs)
                )
            # unary TTFT = first (and only) output; queue wait is
            # included on purpose — that is the latency the caller
            # experiences and the signal the autoscaler scales on
            ttft = time.perf_counter() - t0
            record_serve_ttft(
                self._deployment_name, ttft,
                trace_id=span_ctx["trace_id"] if span_ctx else None,
            )
            self._ttft_telemetry(
                ttft, span_ctx["trace_id"] if span_ctx else None
            )
            return result
        finally:
            self._release()

    async def handle_request_stream(self, method: str, args: tuple,
                                    kwargs: dict,
                                    metadata: Optional[dict] = None):
        """Streaming request path (reference: replica.py generator handling
        behind DeploymentResponseGenerator, serve/handle.py:557): the user
        method must be a (sync or async) generator; every yielded item ships
        to the caller through the runtime's streaming-generator machinery as
        soon as it exists."""
        from ..util import tracing as _tracing
        from ..util import watchdog as _watchdog
        from ..util.metrics import record_serve_ttft

        _SENTINEL = object()
        tctx = (metadata or {}).get("trace_ctx")
        # async generator: a request_span set/reset token cannot bracket
        # the yields (each step may run under a different caller context),
        # so the stream span's identity is minted up front and recorded
        # explicitly when the stream ends
        span_ctx = _tracing.child_context(tctx)
        t0 = time.perf_counter()
        wall0 = time.time()
        first_emitted = False

        def _note_first():
            nonlocal first_emitted
            if not first_emitted:
                first_emitted = True
                ttft = time.perf_counter() - t0
                record_serve_ttft(
                    self._deployment_name, ttft,
                    trace_id=span_ctx["trace_id"] if span_ctx else None,
                )
                self._ttft_telemetry(
                    ttft, span_ctx["trace_id"] if span_ctx else None
                )
                if span_ctx is not None:
                    # streaming first-token stage: admission to first item
                    _tracing.emit_span(
                        "serve.first_token", span_ctx, wall0, ttft,
                        deployment=self._deployment_name,
                        replica=self._replica_id,
                    )

        wd_token = _watchdog.watch(
            "serve.request_stream",
            timeout_s=(metadata or {}).get("timeout_s"),
            deployment=self._deployment_name, replica=self._replica_id,
        )
        try:
            admit_wall = time.time()
            try:
                await self._admit(metadata)
            except BaseException as exc:
                if span_ctx is not None:
                    _tracing.emit_span(
                        "serve.admission", span_ctx, admit_wall,
                        time.perf_counter() - t0, rejected=type(exc).__name__,
                    )
                raise
            if span_ctx is not None:
                _tracing.emit_span(
                    "serve.admission", span_ctx, admit_wall,
                    time.perf_counter() - t0,
                    ongoing=self._ongoing, queued=self._queued,
                )
            self._note_affinity(metadata)
            try:
                fn, args, kwargs = await self._prepare_call(
                    method, args, kwargs, metadata
                )
                if inspect.isasyncgenfunction(fn):
                    async for item in fn(*args, **kwargs):
                        _note_first()
                        yield item
                    return
                if inspect.iscoroutinefunction(fn):
                    raise TypeError(
                        f"stream=True requires a generator method; "
                        f"{method!r} is a coroutine function"
                    )
                import contextvars

                loop = asyncio.get_running_loop()
                ctx = contextvars.copy_context()
                if span_ctx is not None:
                    # install the stream's span as the copied context's
                    # task context: generator steps below run under ctx, so
                    # engine/kvcache spans parent to this stream
                    ctx.run(_tracing._task_context.set, span_ctx)
                gen = await loop.run_in_executor(
                    self._pool, lambda: ctx.run(fn, *args, **kwargs)
                )
                if not inspect.isgenerator(gen):
                    raise TypeError(
                        f"stream=True requires a generator method; {method!r} "
                        f"returned {type(gen).__name__}"
                    )
                # drive the sync generator on the pool: each next() may block
                # on user compute and must stay off the worker's event loop.
                # Every step runs under the copied context — generator bodies
                # see the context active at each next(), not at creation, so
                # a bare next() would drop the multiplexed-model-id var.
                while True:
                    item = await loop.run_in_executor(
                        self._pool, lambda: ctx.run(next, gen, _SENTINEL)
                    )
                    if item is _SENTINEL:
                        return
                    _note_first()
                    yield item
            finally:
                self._release()
        finally:
            _watchdog.unwatch(wd_token)
            if span_ctx is not None:
                _tracing.emit_closed_span(
                    "serve.replica_stream", span_ctx, tctx, wall0,
                    time.perf_counter() - t0,
                    deployment=self._deployment_name,
                    replica=self._replica_id, method=method or "__call__",
                )

    # -- control plane -------------------------------------------------------

    def get_metrics(self) -> Dict[str, Any]:
        from .. import _worker_api

        try:
            worker = _worker_api.get_core_worker()
            node_id = worker.node_id.hex() if worker.node_id else ""
        except Exception:
            node_id = ""
        return {
            "replica_id": self._replica_id,
            "node_id": node_id,
            "queue_len": self._ongoing + self._queued,
            "ongoing": self._ongoing,
            "queued": self._queued,
            "shed_total": self._shed_total,
            "doa_total": self._doa_total,
            "total_served": self._total_served,
            "draining": self._draining,
            "pid": os.getpid(),
            "affinity_keys": self._live_affinity_keys(),
            "warmup_s": round(self._warmup_s, 6),
            "mesh": self._mesh_info(),
        }

    def _mesh_info(self):
        """Mesh ownership card from the user callable (LLM replicas expose
        mesh_info(): mesh shape, per-device HBM, KV pool footprint). None
        for callables without a mesh — the controller then reports the
        replica as single-device."""
        fn = getattr(self._callable, "mesh_info", None)
        if fn is None:
            return None
        try:
            return fn()
        except Exception:
            return None

    def check_health(self) -> bool:
        user_check = getattr(self._callable, "check_health", None)
        if user_check is not None:
            user_check()
        return True

    def _reconfigure_sync(self, user_config):
        rec = getattr(self._callable, "reconfigure", None)
        if rec is not None:
            rec(user_config)

    def reconfigure(self, user_config) -> bool:
        self._reconfigure_sync(user_config)
        return True

    async def _run_shutdown_hook(self):
        """Run user cleanup before the controller hard-kills this actor;
        an explicit shutdown() wins over __del__ (which GC may also run)."""
        for hook in ("shutdown", "__del__"):
            fn = getattr(type(self._callable), hook, None)
            if fn is not None:
                try:
                    result = fn(self._callable)
                    if inspect.iscoroutine(result):
                        await result
                except Exception:
                    pass
                break

    async def _wait_idle(self, timeout_s: float) -> bool:
        deadline = time.time() + timeout_s
        while (self._ongoing > 0 or self._queued > 0) and time.time() < deadline:
            await asyncio.sleep(0.05)
        return self._ongoing == 0 and self._queued == 0

    async def drain(self, timeout_s: float = 5.0) -> bool:
        """Graceful drain: stop admitting new requests, finish everything
        in-flight AND queued (bounded by timeout_s), then ack. The
        controller only kills this actor after the ack or the deadline
        (reference: replica.py perform_graceful_shutdown). Returns True if
        the replica drained clean (zero dropped accepted requests)."""
        from ..util.metrics import record_serve_drain

        start = time.time()
        self._draining = True
        clean = await self._wait_idle(timeout_s)
        await self._run_shutdown_hook()
        record_serve_drain(self._deployment_name, time.time() - start)
        return clean

    async def prepare_for_shutdown(self, timeout_s: float = 5.0) -> bool:
        """Drain: wait for ongoing requests to finish (reference:
        graceful_shutdown_timeout_s semantics). Kept as the synchronous
        stop path; sets _draining so no new work is admitted while the
        controller blocks on us."""
        self._draining = True
        clean = await self._wait_idle(timeout_s)
        await self._run_shutdown_hook()
        return clean
