"""Rendezvous (highest-random-weight) hashing over a replica set.

The prefix-affinity pick used to be ``sorted_ids[affinity % n]`` inside
each Router — correct for agreement, but any membership change remaps
almost every key (a scale-up from 3 to 4 replicas moves ~75% of prefixes,
cold-starting their KV blocks). Rendezvous hashing fixes both properties
at once: every process that sees the same replica-id set maps a key to
the same winner with **no coordination**, and adding/removing one replica
only moves the keys whose winner was that replica (~1/n of them).

For key ``k`` and replica ``r`` the weight is ``crc32(key_bytes,
seed=crc32(r))``; the replica with the highest weight wins. crc32, NOT
``hash()``: PYTHONHASHSEED randomizes str/bytes hashing per process, and
cross-process agreement is the entire point — every proxy, every handle,
every Router must pick the same warm replica for a prefix without asking
the controller.

The ring is immutable and rebuilt only when the routing table's version
(replica membership) changes; ``lookup_index`` is the per-request hot
path and allocates no dicts.
"""

from __future__ import annotations

import zlib
from typing import Iterable, Tuple


class ReplicaRing:
    """Immutable rendezvous ring over a replica-id set.

    Built once per routing-table generation; ``lookup_index(key)`` is
    O(n) crc32s with no allocation beyond the 8-byte key encoding —
    cheap for realistic replica counts, and the O(1)-update properties
    of a virtual-node ring buy nothing for n < a few hundred.
    """

    __slots__ = ("ids", "_salts")

    def __init__(self, replica_ids: Iterable[str]):
        # sorted for deterministic iteration order; agreement itself only
        # needs the same *set* (HRW is order-independent)
        self.ids: Tuple[str, ...] = tuple(sorted(str(r) for r in replica_ids))
        self._salts: Tuple[int, ...] = tuple(
            zlib.crc32(rid.encode()) for rid in self.ids
        )

    def __len__(self) -> int:
        return len(self.ids)

    def lookup_index(self, key: int) -> int:
        """Index (into ``ids``) of the key's preferred replica."""
        kb = (key & 0xFFFFFFFFFFFFFFFF).to_bytes(8, "little")
        salts = self._salts
        best_i = 0
        best_w = -1
        for i in range(len(salts)):
            w = zlib.crc32(kb, salts[i])
            if w > best_w:
                best_w = w
                best_i = i
        return best_i

    def lookup(self, key: int) -> str:
        """Replica id preferred for ``key`` (empty ring raises IndexError)."""
        return self.ids[self.lookup_index(key)]

    def lookup_excluding(self, key: int, exclude) -> int:
        """Preferred index skipping replicas in ``exclude`` (a set of ids);
        falls back to the unfiltered winner when exclusion would leave
        nothing (a 1-replica deployment's restart is still worth a try)."""
        kb = (key & 0xFFFFFFFFFFFFFFFF).to_bytes(8, "little")
        ids = self.ids
        salts = self._salts
        best_i = -1
        best_w = -1
        for i in range(len(salts)):
            if ids[i] in exclude:
                continue
            w = zlib.crc32(kb, salts[i])
            if w > best_w:
                best_w = w
                best_i = i
        if best_i < 0:
            return self.lookup_index(key)
        return best_i
