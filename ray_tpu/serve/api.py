"""Serve public API.

Role-equivalent of the reference's serve API (python/ray/serve/api.py —
serve.deployment, serve.run :681, serve.delete, serve.status,
serve.get_app_handle). ``@serve.deployment`` wraps a class/function into a
Deployment; ``.bind()`` builds the app graph; ``serve.run`` ships it to the
ServeController actor and returns a handle.
"""

from __future__ import annotations

import inspect
from typing import Any, Dict, List, Optional

from .. import api as ray_api
from .._internal import serialization
from .autoscale import AutoscalePolicy
from .config import (
    ApplicationStatus,
    AutoscalingConfig,
    DeploymentConfig,
    RequestRouterConfig,
)
from .controller import CONTROLLER_NAME, ServeController
from .handle import DeploymentHandle, DeploymentResponse

_state: Dict[str, Any] = {
    "controller": None, "proxy": None, "proxies": [], "grpc_proxies": [],
    "ingress": {},
}


class Application:
    """A bound deployment graph rooted at the ingress deployment."""

    def __init__(self, root: "_BoundDeployment"):
        self.root = root

    def _collect(self) -> List["_BoundDeployment"]:
        seen: Dict[str, _BoundDeployment] = {}

        def walk(node):
            if isinstance(node, Application):
                node = node.root
            if isinstance(node, _BoundDeployment):
                if node.deployment.name not in seen:
                    seen[node.deployment.name] = node
                    for a in list(node.init_args) + list(
                        node.init_kwargs.values()
                    ):
                        walk(a)
            elif isinstance(node, (list, tuple)):
                for x in node:
                    walk(x)
            elif isinstance(node, dict):
                for x in node.values():
                    walk(x)

        walk(self.root)
        return list(seen.values())


class _BoundDeployment:
    def __init__(self, deployment: "Deployment", args: tuple, kwargs: dict):
        self.deployment = deployment
        self.init_args = args
        self.init_kwargs = kwargs


class Deployment:
    def __init__(self, target, config: DeploymentConfig):
        self._target = target
        self._config = config

    @property
    def name(self) -> str:
        return self._config.name

    def options(self, **overrides) -> "Deployment":
        import dataclasses

        cfg = dataclasses.replace(self._config)
        for k, v in overrides.items():
            if not hasattr(cfg, k):
                raise ValueError(f"unknown deployment option {k!r}")
            setattr(cfg, k, v)
        return Deployment(self._target, cfg)

    def bind(self, *args, **kwargs) -> Application:
        return Application(_BoundDeployment(self, args, kwargs))


def deployment(_target=None, **options):
    """@serve.deployment / @serve.deployment(num_replicas=2, ...)"""

    def wrap(target):
        if isinstance(options.get("autoscaling_config"), dict):
            options["autoscaling_config"] = AutoscalingConfig(
                **options["autoscaling_config"]
            )
        if isinstance(options.get("request_router_config"), dict):
            options["request_router_config"] = RequestRouterConfig(
                **options["request_router_config"]
            )
        if isinstance(options.get("autoscale_policy"), dict):
            options["autoscale_policy"] = AutoscalePolicy(
                **options["autoscale_policy"]
            )
        cfg = DeploymentConfig(
            name=options.pop("name", None) or target.__name__, **options
        )
        return Deployment(target, cfg)

    if _target is not None:
        return wrap(_target)
    return wrap


def ingress(asgi_app):
    """Mount an ASGI app as a deployment's HTTP interface (reference:
    @serve.ingress, serve/api.py:181 — FastAPI apps become deployments).

    ``asgi_app`` is any ASGI-3 callable ``async app(scope, receive, send)``
    (FastAPI/Starlette instances qualify). The decorated class gains an
    ``__asgi__`` streaming method: the HTTP proxy forwards (scope, body) to
    it and relays the ASGI send-events back as they are produced, so
    streaming responses reach the client incrementally. The deployment
    instance is exposed to the app at ``scope["ray_tpu.replica"]``."""

    def decorator(cls):
        if not inspect.isclass(cls):
            raise TypeError("@serve.ingress decorates a deployment class")
        cls.__ray_tpu_asgi_app__ = staticmethod(asgi_app)

        async def __asgi__(self, scope: dict, body: bytes):
            import asyncio

            app = self.__ray_tpu_asgi_app__
            queue: asyncio.Queue = asyncio.Queue()
            _DONE = object()
            scope = dict(scope)
            scope["ray_tpu.replica"] = self
            body_sent = False

            async def receive():
                nonlocal body_sent
                if not body_sent:
                    body_sent = True
                    return {
                        "type": "http.request",
                        "body": body or b"",
                        "more_body": False,
                    }
                # block forever: an eager http.disconnect makes Starlette's
                # listen_for_disconnect cancel StreamingResponses mid-stream.
                # Disconnect propagation is the proxy's job; if the app
                # parks a task here it is cancelled in the finally below.
                await asyncio.Event().wait()

            async def send(event):
                await queue.put(event)

            async def run_app():
                try:
                    await app(scope, receive, send)
                except Exception as e:  # noqa: BLE001 — relayed to the proxy
                    await queue.put({"type": "asgi.error", "error": repr(e)})
                finally:
                    await queue.put(_DONE)

            task = asyncio.ensure_future(run_app())
            try:
                while True:
                    event = await queue.get()
                    if event is _DONE:
                        break
                    yield event
            finally:
                task.cancel()
                try:
                    await task
                except (asyncio.CancelledError, Exception):  # noqa: BLE001
                    pass

        cls.__asgi__ = __asgi__
        return cls

    return decorator


# -- controller / proxy management -------------------------------------------


def _default_num_proxies() -> int:
    """One proxy per alive node (the reference's proxy placement); at
    least one. Falls back to 1 when node state is unavailable."""
    try:
        return max(
            1, sum(1 for n in ray_api.nodes() if n.get("Alive", True))
        )
    except Exception:
        return 1


def _register_proxy(controller, p, proxy_id: str):
    """Fetch the proxy's identity and enter it into the controller's
    inventory (GCS ``proxy:`` registry) so drains/chaos/CLI see it."""
    info = ray_api.get(p.describe.remote())
    ray_api.get(controller.register_proxy.remote(proxy_id, info, p))


def start(
    *,
    http_host: str = "127.0.0.1",
    http_port: int = 8000,
    proxy: bool = True,
    grpc_port: Optional[int] = None,
    num_proxies: Optional[int] = None,
    num_grpc_proxies: int = 1,
):
    """Start (or connect to) the Serve control plane (reference:
    serve.start): a detached-ish named controller actor plus the ingress
    data plane — ``num_proxies`` HTTP proxy actors (default: one per alive
    node) sharing ``http_port`` via SO_REUSEPORT, and — with ``grpc_port``
    — ``num_grpc_proxies`` gRPC proxies the same way (0 picks a free port,
    see serve.grpc_proxy_address)."""
    if _state["controller"] is None:
        try:
            controller = ray_api.get_actor(CONTROLLER_NAME)
        except ValueError:
            # restartable: on crash the GCS re-creates it and __init__
            # recovers goal state from the KV checkpoint, re-adopting live
            # replicas (reference: controller.py:98-148)
            Controller = ray_api.remote(
                num_cpus=0, name=CONTROLLER_NAME, max_restarts=-1
            )(ServeController)
            controller = Controller.remote()
            ray_api.get(controller.ping.remote())
        _state["controller"] = controller
    if proxy and not _state["proxies"]:
        from .proxy import HTTPProxy

        n = num_proxies if num_proxies else _default_num_proxies()
        reuse = n > 1
        Proxy = ray_api.remote(num_cpus=0)(HTTPProxy)
        started = []
        for i in range(n):
            proxy_id = f"http#{i}"
            p = Proxy.remote(
                _state["controller"], http_host, http_port, proxy_id, reuse
            )
            ray_api.get(p.ping.remote())
            started.append((proxy_id, p))
        for proxy_id, p in started:
            _register_proxy(_state["controller"], p, proxy_id)
        _state["proxies"] = [p for _, p in started]
        _state["proxy"] = _state["proxies"][0]
    if grpc_port is not None and not _state["grpc_proxies"]:
        from .grpc_proxy import GRPCProxy

        n = max(1, int(num_grpc_proxies))
        # port 0 means "pick free": listener sharing needs the REAL port,
        # so the first proxy binds and the rest join its bound port
        reuse = n > 1
        GProxy = ray_api.remote(num_cpus=0)(GRPCProxy)
        started = []
        bound_port = grpc_port
        for i in range(n):
            proxy_id = f"grpc#{i}"
            gp = GProxy.remote(
                _state["controller"], http_host, bound_port, proxy_id, reuse
            )
            ray_api.get(gp.ping.remote())
            if i == 0 and n > 1:
                bound_port = ray_api.get(gp.address.remote())[1]
            started.append((proxy_id, gp))
        for proxy_id, gp in started:
            _register_proxy(_state["controller"], gp, proxy_id)
        _state["grpc_proxies"] = [gp for _, gp in started]
        _state["grpc_proxy"] = _state["grpc_proxies"][0]
    return _state["controller"]


def grpc_proxy_address():
    """(host, port) of the running gRPC ingress, or None."""
    gp = _state.get("grpc_proxy")
    if gp is None:
        return None
    return ray_api.get(gp.address.remote())


def run(
    app: Application,
    *,
    name: str = "default",
    route_prefix: Optional[str] = None,
    _blocking: bool = True,
    _proxy: bool = True,
    _local_testing_mode: bool = False,
) -> DeploymentHandle:
    """Deploy an application and wait until it is RUNNING (reference:
    serve.run serve/api.py:681). ``_local_testing_mode=True`` runs every
    deployment in-process with no cluster (reference:
    serve/_private/local_testing_mode.py)."""
    if _local_testing_mode:
        from .local_mode import run_local

        return run_local(app, name)
    controller = start(proxy=_proxy)
    nodes = app._collect()
    ingress_name = app.root.deployment.name
    payload = []
    for node in nodes:
        cfg = node.deployment._config
        import dataclasses

        cfg = dataclasses.replace(cfg)
        if route_prefix is not None and node is app.root:
            cfg.route_prefix = route_prefix
        # ingress/streaming/ASGI detection: the proxy needs to know how to
        # talk to the app root (reference: the proxy always speaks ASGI to
        # ingress replicas, proxy.py:805; here plain JSON deployments keep
        # the request/response path and generator/ASGI roots stream)
        target = node.deployment._target
        cfg.asgi = cfg.asgi or getattr(
            target, "__ray_tpu_asgi_app__", None
        ) is not None
        call = target if not inspect.isclass(target) else getattr(
            target, "__call__", None
        )
        cfg.stream = cfg.stream or (
            call is not None
            and (
                inspect.isgeneratorfunction(call)
                or inspect.isasyncgenfunction(call)
            )
        )
        if node is app.root:
            cfg.ingress = True
        # nested bound deployments become handles at replica init time
        init_args = _replace_bound(node.init_args, controller, name)
        init_kwargs = _replace_bound(node.init_kwargs, controller, name)
        payload.append(
            dict(
                config=cfg,
                cls_bytes=serialization.dumps(node.deployment._target),
                init_args=init_args,
                init_kwargs=init_kwargs,
            )
        )
    ray_api.get(controller.deploy_application.remote(name, payload))
    _state["ingress"][name] = ingress_name
    handle = DeploymentHandle(controller, name, ingress_name)
    if _blocking:
        _wait_healthy(name)
    return handle


def _replace_bound(obj, controller, app_name):
    if isinstance(obj, Application):
        obj = obj.root
    if isinstance(obj, _BoundDeployment):
        return DeploymentHandle(controller, app_name, obj.deployment.name)
    if isinstance(obj, tuple):
        return tuple(_replace_bound(x, controller, app_name) for x in obj)
    if isinstance(obj, list):
        return [_replace_bound(x, controller, app_name) for x in obj]
    if isinstance(obj, dict):
        return {k: _replace_bound(v, controller, app_name) for k, v in obj.items()}
    return obj


def _wait_healthy(app_name: str, timeout_s: float = 60.0):
    import time

    controller = _state["controller"]
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        st = ray_api.get(controller.status.remote())
        app = st.get(app_name)
        if app is not None and app.status == "RUNNING":
            return
        time.sleep(0.2)
    raise TimeoutError(f"application {app_name!r} not healthy in {timeout_s}s")


def status() -> Dict[str, ApplicationStatus]:
    controller = _require_controller()
    return ray_api.get(controller.status.remote())


def get_app_handle(name: str = "default", _controller=None) -> DeploymentHandle:
    controller = _controller or _require_controller()
    ingress = _state["ingress"].get(name)
    if ingress is None:
        table = ray_api.get(controller.get_routing_table.remote(name))
        if not table:
            raise ValueError(f"no application named {name!r}")
        ingress = next(iter(table.keys()))
    return DeploymentHandle(controller, name, ingress)


def get_deployment_handle(
    deployment_name: str, app_name: str = "default"
) -> DeploymentHandle:
    return DeploymentHandle(_require_controller(), app_name, deployment_name)


def delete(name: str = "default"):
    controller = _require_controller()
    ray_api.get(controller.delete_application.remote(name))
    _state["ingress"].pop(name, None)


def shutdown():
    controller = _state["controller"]
    if controller is not None:
        try:
            ray_api.get(controller.shutdown.remote(), timeout=30)
            ray_api.kill(controller)
        except Exception:
            pass
    for p in (
        list(_state.get("proxies") or [])
        + list(_state.get("grpc_proxies") or [])
    ):
        try:
            ray_api.kill(p)
        except Exception:
            pass
    for key in ("proxy", "grpc_proxy"):
        p = _state.get(key)
        if p is not None and p not in (_state.get("proxies") or []) \
                and p not in (_state.get("grpc_proxies") or []):
            try:
                ray_api.kill(p)
            except Exception:
                pass
    _state.update(controller=None, proxy=None, grpc_proxy=None,
                  proxies=[], grpc_proxies=[], ingress={})


def _require_controller():
    if _state["controller"] is None:
        try:
            _state["controller"] = ray_api.get_actor(CONTROLLER_NAME)
        except ValueError:
            raise RuntimeError("serve is not running; call serve.run first")
    return _state["controller"]
