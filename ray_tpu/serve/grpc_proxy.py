"""gRPC proxy actor: the cluster's second ingress.

Role-equivalent of the reference's gRPC proxy path (serve/_private/proxy.py
gRPC handling :533 + serve.proto's user-defined services): a grpc.aio
server routes RPCs to deployments through DeploymentHandles. The reference
compiles user .proto services; this environment has no protoc plugin for
Python, so the service is a generic bytes-in/bytes-out surface
(``/ray_tpu.serve.ServeAPI/Call``) carrying a JSON envelope
{"application", "method", "payload"} — any gRPC client in any language can
speak it without generated stubs.
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import threading
import time
from typing import Dict, Optional

logger = logging.getLogger(__name__)

SERVICE_NAME = "ray_tpu.serve.ServeAPI"


class GRPCProxy:
    """Actor: runs a grpc.aio server in a dedicated thread+loop.

    Same multi-proxy treatment as HTTPProxy: ``reuse_port=True`` sets
    grpc.so_reuseport so N gRPC proxies share one port, and each instance
    registers with the controller under its ``proxy_id``."""

    def __init__(self, controller, host: str = "127.0.0.1", port: int = 9000,
                 proxy_id: str = "grpc#0", reuse_port: bool = False):
        self._controller = controller
        self._host = host
        self._port = port
        self._proxy_id = proxy_id
        self._reuse_port = reuse_port
        self._bound_port: Optional[int] = None
        self._handles: Dict[str, object] = {}
        self._ready = threading.Event()
        self._error: Optional[str] = None
        self._started_at = time.time()
        self._draining = False
        self._inflight = 0
        from ..util.metrics import ingress_handles

        self._m = ingress_handles(proxy_id)
        self._thread = threading.Thread(
            target=self._serve_forever, daemon=True, name="grpc-proxy"
        )
        self._thread.start()
        if not self._ready.wait(timeout=15):
            raise RuntimeError(f"gRPC proxy failed to start: {self._error}")
        if self._error is not None:
            raise RuntimeError(f"gRPC proxy failed to start: {self._error}")

    def _serve_forever(self):
        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        try:
            loop.run_until_complete(self._start_server())
            loop.run_forever()
        except Exception as e:  # noqa: BLE001
            self._error = repr(e)
            self._ready.set()

    async def _start_server(self):
        import grpc

        options = []
        if self._reuse_port:
            # kernel-level listener sharing: every proxy binds the SAME
            # port and accepted connections spread across them
            options.append(("grpc.so_reuseport", 1))
        server = grpc.aio.server(options=options or None)
        rpc_handlers = {
            "Call": grpc.unary_unary_rpc_method_handler(
                self._handle_call,
                request_deserializer=None,  # raw bytes through
                response_serializer=None,
            ),
            "Healthz": grpc.unary_unary_rpc_method_handler(
                self._handle_health,
            ),
        }
        server.add_generic_rpc_handlers(
            (grpc.method_handlers_generic_handler(SERVICE_NAME, rpc_handlers),)
        )
        self._bound_port = server.add_insecure_port(
            f"{self._host}:{self._port}"
        )
        await server.start()
        self._server = server
        self._ready.set()

    async def _handle_health(self, request: bytes, context) -> bytes:
        return b'{"status": "ok"}'

    async def _handle_call(self, request: bytes, context) -> bytes:
        if self._draining:
            self._m["drain"].inc()
            return json.dumps(
                {"ok": False, "error": "proxy draining", "retry_after_s": 1.0}
            ).encode()
        t0 = time.perf_counter()
        self._inflight += 1
        self._m["inflight"].set(self._inflight)
        try:
            reply = await self._call_body(request, context)
        finally:
            self._inflight -= 1
            self._m["inflight"].set(self._inflight)
            self._m["latency"].observe((time.perf_counter() - t0) * 1000.0)
        return reply

    async def _call_body(self, request: bytes, context) -> bytes:
        try:
            envelope = json.loads(request or b"{}")
            app_name = envelope.get("application", "default")
            method = envelope.get("method", "__call__")
            payload = envelope.get("payload")
            # trace mint/honor, the gRPC twin of HTTP's X-Trace-Id header:
            # an envelope-supplied id joins the caller's trace; otherwise a
            # fresh trace starts when this process traces
            from ..util import tracing

            trace_id = envelope.get("trace_id")
            if trace_id:
                trace_ctx = tracing.new_trace_context(str(trace_id)[:64])
            elif tracing.is_tracing_enabled():
                trace_ctx = tracing.new_trace_context()
            else:
                trace_ctx = None
            # per-request deadline: an explicit envelope field wins, else
            # the client's gRPC deadline (context.time_remaining()), else
            # the deployment's default (60 s out of the box)
            timeout_s = envelope.get("timeout_s")
            if timeout_s is None:
                try:
                    remaining = context.time_remaining()
                except Exception:  # noqa: BLE001
                    remaining = None
                if remaining is not None and remaining > 0:
                    timeout_s = remaining
            result = await asyncio.get_event_loop().run_in_executor(
                None, self._call_ingress, app_name, method, payload,
                timeout_s, trace_ctx,
            )
            if isinstance(result, Exception):
                from ..exceptions import (
                    BackPressureError,
                    DeadlineExceededError,
                    GetTimeoutError,
                )

                cause = getattr(result, "cause", None) or result
                if isinstance(cause, BackPressureError):
                    self._m["shed"].inc()
                elif isinstance(cause, (DeadlineExceededError,
                                        GetTimeoutError)):
                    self._m["timeout"].inc()
                else:
                    self._m["error"].inc()
                return self._error_reply(result, context)
            reply = {"ok": True, "result": result}
            if trace_ctx is not None:
                reply["trace_id"] = trace_ctx["trace_id"]
            self._m["ok"].inc()
            return json.dumps(reply).encode()
        except Exception as e:  # noqa: BLE001
            self._m["error"].inc()
            return json.dumps({"ok": False, "error": repr(e)}).encode()

    @staticmethod
    def _error_reply(exc: Exception, context) -> bytes:
        """Map typed serve errors onto gRPC semantics: sheds become
        RESOURCE_EXHAUSTED with a retry_after_s hint, deadline expiry
        becomes DEADLINE_EXCEEDED (reference: the proxy's status-code
        mapping, serve/_private/proxy.py gRPC path)."""
        import grpc

        from ..exceptions import (
            BackPressureError,
            DeadlineExceededError,
            GetTimeoutError,
        )

        cause = getattr(exc, "cause", None) or exc
        body = {"ok": False, "error": repr(cause)}
        try:
            if isinstance(cause, BackPressureError):
                context.set_code(grpc.StatusCode.RESOURCE_EXHAUSTED)
                body["retry_after_s"] = cause.retry_after_s
            elif isinstance(cause, (DeadlineExceededError, GetTimeoutError)):
                context.set_code(grpc.StatusCode.DEADLINE_EXCEEDED)
        except Exception:  # noqa: BLE001 — status is advisory; reply wins
            pass
        return json.dumps(body).encode()

    def _call_ingress(self, app_name: str, method: str, payload,
                      timeout_s: Optional[float] = None,
                      trace_ctx: Optional[dict] = None):
        from ..util import tracing
        from .api import get_app_handle

        try:
            handle = self._handles.get(app_name)
            if handle is None:
                handle = get_app_handle(app_name, _controller=self._controller)
                self._handles[app_name] = handle
            if method != "__call__":
                handle = handle.options(method_name=method)
            if timeout_s is not None:
                handle = handle.options(timeout_s=float(timeout_s))
            # the handle's deadline (explicit or the deployment default)
            # bounds the wait — no hardcoded proxy-side 60 s
            if trace_ctx is None and not tracing.is_tracing_enabled():
                # untraced fast path: no span contextmanager allocation
                return handle.remote(payload).result()
            with tracing.request_span(
                "serve.grpc_proxy", trace_ctx, app=app_name, method=method
            ):
                return handle.remote(payload).result()
        except Exception as e:  # noqa: BLE001
            return e

    # -- control -------------------------------------------------------------

    def address(self):
        return (self._host, self._bound_port or self._port)

    def ping(self):
        return True

    def describe(self) -> dict:
        """Identity record for the controller's proxy inventory (GCS
        ``proxy:`` prefix)."""
        from ..util.metrics import _node_hex

        return {
            "kind": "grpc",
            "proxy_id": self._proxy_id,
            "host": self._host,
            "port": self._bound_port or self._port,
            "pid": os.getpid(),
            "node": _node_hex(),
            "started_at": self._started_at,
        }

    def stats(self) -> dict:
        return {"proxy_id": self._proxy_id, "inflight": self._inflight,
                "draining": self._draining}

    def drain(self, timeout_s: float = 5.0) -> bool:
        """See HTTPProxy.drain: refuse new calls, bounded wait on in-flight."""
        from ..util import events as _events

        self._draining = True
        deadline = time.time() + timeout_s
        while self._inflight > 0 and time.time() < deadline:
            time.sleep(0.02)
        _events.record_event(
            _events.PROXY_DRAIN, proxy_id=self._proxy_id, kind="grpc",
            inflight=self._inflight,
        )
        return self._inflight == 0


def grpc_call(address, payload, *, application="default", method="__call__",
              timeout_s: float = 60.0, trace_id: Optional[str] = None):
    """Client helper: one RPC against a GRPCProxy from any process
    (reference: generated stubs; here a generic bytes channel).
    ``trace_id`` joins the call to a caller-chosen trace (the envelope
    twin of the HTTP X-Trace-Id header)."""
    import grpc

    host, port = address
    envelope_dict = {
        "application": application, "method": method, "payload": payload,
    }
    if trace_id:
        envelope_dict["trace_id"] = trace_id
    envelope = json.dumps(envelope_dict).encode()
    with grpc.insecure_channel(f"{host}:{port}") as channel:
        fn = channel.unary_unary(f"/{SERVICE_NAME}/Call")
        reply = json.loads(fn(envelope, timeout=timeout_s))
    if not reply.get("ok"):
        raise RuntimeError(f"serve gRPC error: {reply.get('error')}")
    return reply.get("result")
