"""ServeController: the control-plane actor reconciling deployments.

Role-equivalent of the reference's ServeController
(python/ray/serve/_private/controller.py:102; reconcile loop :395) +
DeploymentState manager (deployment_state.py) + the queue-length autoscaler
(autoscaling_policy.py:85, autoscaling_state.py). A reconcile thread
compares target replica counts (static or autoscaler-driven) with live
replicas, starts/stops replica actors, polls queue metrics, and exposes the
replica directory to routers, which poll ``get_routing_table`` keyed by a
membership version (reference: LongPollClient snapshot ids).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Dict, List

from ..runtime.gcs import keys as gcs_keys
from ..util import events as _events
from .config import (
    ApplicationStatus,
    AutoscalingConfig,
    DeploymentConfig,
    DeploymentStatus,
    ReplicaStatus,
)

logger = logging.getLogger(__name__)

CONTROLLER_NAME = "SERVE_CONTROLLER"


class _ReplicaState:
    def __init__(self, replica_id: str, handle):
        self.replica_id = replica_id
        self.handle = handle
        self.state = "STARTING"
        self.queue_len = 0
        self.consecutive_health_failures = 0
        self.started_at = time.time()
        self.pid = 0  # captured from get_metrics; chaos CLI targets it
        # hex node id captured from get_metrics: reconcile replaces replicas
        # whose node the GCS marks SUSPECT/DEAD (partition failover)
        self.node_id = ""
        # captured from get_metrics: distinct prefix-affinity keys recently
        # routed here (scale-down victim signal) and cold-start wall time
        self.affinity_keys = 0
        self.warmup_s = 0.0
        # mesh ownership card from get_metrics (None = single device):
        # {"mesh": {"tp": 2}, "tag": "tp=2", "num_devices": 2,
        #  "per_device_hbm_bytes": [...], ...}
        self.mesh = None
        # drain bookkeeping (state == "DRAINING"): the in-flight drain()
        # call and the hard deadline after which the replica is killed
        # whether or not it acked
        self.drain_ref = None
        self.drain_deadline = 0.0


class _DeploymentState:
    def __init__(self, config: DeploymentConfig, cls_bytes, init_args, init_kwargs):
        self.config = config
        self.cls_bytes = cls_bytes
        self.init_args = init_args
        self.init_kwargs = init_kwargs
        self.replicas: Dict[str, _ReplicaState] = {}
        self.next_replica_idx = 0
        self.target_replicas = config.num_replicas
        if config.autoscaling_config:
            self.target_replicas = config.autoscaling_config.min_replicas
        policy = getattr(config, "autoscale_policy", None)
        if policy is not None:
            self.target_replicas = max(
                policy.min_replicas,
                min(policy.max_replicas, config.num_replicas),
            )
        # per-deployment SLO-autoscaler evaluation state (lazily created
        # for deployments recovered from pre-policy checkpoints)
        self.autoscale_state = None
        self.last_scale_up = 0.0
        self.last_scale_down = 0.0
        # bumped whenever replica membership changes, so routers cheap-poll
        self.version = 0


CHECKPOINT_KEY = gcs_keys.SERVE_CONTROLLER_CKPT


class ServeController:
    def __init__(self):
        self._apps: Dict[str, Dict[str, str]] = {}  # app -> short -> full name
        self._deployments: Dict[str, _DeploymentState] = {}
        self._lock = threading.RLock()
        self._running = True
        self._reconcile_interval_s = 0.25
        # goal state persists to GCS KV; a restarted controller re-adopts
        # live replicas instead of abandoning them (reference:
        # controller.py:98-148 checkpoint/recover)
        self._dirty = False
        # serializes snapshot+write so concurrent checkpoints (reconcile
        # thread vs deploy RPC thread) cannot land out of order and regress
        # the durable state to an older snapshot
        self._ckpt_lock = threading.Lock()
        # SLO-autoscaler decision event log (bounded). Mirrored to the GCS
        # KV under AUTOSCALE_LOG_KEY so dashboard/CLI read it without an
        # actor handle; actor method autoscale_log serves it directly.
        self._autoscale_events: List[dict] = []
        # ingress proxy inventory: proxy_id -> {info, handle, state,
        # failures}. Mirrored to GCS under the proxy: prefix so CLI/
        # dashboard/chaos see live proxies without an actor handle; health
        # is polled from the reconcile loop like replicas.
        self._proxies: Dict[str, dict] = {}
        self._last_proxy_poll = 0.0
        # replica-inventory KV mirror throttle: the snapshot only feeds
        # read-side surfaces (CLI/dashboard), so a 2 s cadence is plenty
        # and keeps the 0.25 s reconcile tick free of a per-tick kv_put
        self._last_replica_mirror = 0.0
        try:
            self._recover_from_checkpoint()
        except Exception:
            # never let recovery crash __init__: with max_restarts=-1 that
            # would restart-loop the controller forever on a bad checkpoint
            logger.exception("serve checkpoint recovery failed; starting fresh")
        self._thread = threading.Thread(
            target=self._run_control_loop, daemon=True, name="serve-reconcile"
        )
        self._thread.start()

    # -- checkpoint / recovery ----------------------------------------------

    def _kv_call(self, method: str, *args):
        from .. import _worker_api

        worker = _worker_api.get_core_worker()
        return _worker_api.run_on_worker_loop(
            worker.client_pool.get(*worker.gcs_address).call(
                method, *args, timeout=10.0
            )
        )

    def _checkpoint(self):
        """Persist goal state + live replica handles to GCS KV. Called from
        the reconcile loop when membership/config changed, and synchronously
        on deploy/delete so the goal state is durable before the API
        returns."""
        import cloudpickle

        with self._ckpt_lock:
            with self._lock:
                data = {
                    "apps": {a: dict(n) for a, n in self._apps.items()},
                    "deployments": {
                        full: {
                            "config": dep.config,
                            "cls_bytes": dep.cls_bytes,
                            "init_args": dep.init_args,
                            "init_kwargs": dep.init_kwargs,
                            "target_replicas": dep.target_replicas,
                            "next_replica_idx": dep.next_replica_idx,
                            "replicas": [
                                (r.replica_id, r.handle, r.state)
                                for r in dep.replicas.values()
                            ],
                        }
                        for full, dep in self._deployments.items()
                    },
                }
                self._dirty = False
            try:
                self._kv_call(
                    "kv_put", CHECKPOINT_KEY, cloudpickle.dumps(data), True
                )
            except Exception:
                # a failed write must be retried: without re-marking dirty
                # the change would stay unpersisted until some unrelated
                # later change, and a crash in that window recovers stale
                # membership
                logger.exception("serve controller checkpoint failed")
                with self._lock:
                    self._dirty = True

    def _recover_from_checkpoint(self):
        import pickle

        from .. import api

        try:
            raw = self._kv_call("kv_get", CHECKPOINT_KEY)
        except Exception:
            logger.exception("serve checkpoint read failed; starting fresh")
            return
        if not raw:
            return
        try:
            data = pickle.loads(raw)
        except Exception:
            logger.exception("serve checkpoint unreadable; starting fresh")
            return
        # probe every saved replica CONCURRENTLY under one shared deadline:
        # live ones are re-adopted with no churn; unresponsive ones are
        # killed (not just dropped — an alive-but-slow replica left orphaned
        # would double-serve next to its replacement) and converge replaces
        # them
        probes = []  # (dep, rid, handle, probe_ref)
        deps: Dict[str, _DeploymentState] = {}
        for full, d in data.get("deployments", {}).items():
            try:
                dep = _DeploymentState(
                    d["config"], d["cls_bytes"], d["init_args"], d["init_kwargs"]
                )
                dep.target_replicas = d["target_replicas"]
                dep.next_replica_idx = d["next_replica_idx"]
                replicas = list(d["replicas"])
            except Exception:
                # schema drift (checkpoint from another controller version):
                # skip this record rather than crash — with max_restarts=-1
                # an exception here would restart-loop the controller forever
                logger.exception("skipping malformed checkpoint record %s", full)
                continue
            deps[full] = dep
            for rid, handle, _state in replicas:
                try:
                    probes.append((dep, rid, handle, handle.check_health.remote()))
                except Exception:
                    probes.append((dep, rid, handle, None))
        deadline = time.time() + 15.0
        adopted = dead = 0
        for dep, rid, handle, ref in probes:
            healthy = False
            if ref is not None:
                try:
                    healthy = bool(
                        api.get(ref, timeout=max(deadline - time.time(), 0.5))
                    )
                except Exception:
                    healthy = False
            if healthy:
                replica = _ReplicaState(rid, handle)
                replica.state = "RUNNING"
                dep.replicas[rid] = replica
                adopted += 1
            else:
                dead += 1
                try:
                    api.kill(handle)
                except Exception:
                    pass
        self._deployments.update(deps)
        self._apps = {a: dict(n) for a, n in data.get("apps", {}).items()}
        if self._deployments:
            logger.info(
                "serve controller recovered: %d app(s), %d deployment(s); "
                "%d replica(s) re-adopted, %d dead",
                len(self._apps), len(self._deployments), adopted, dead,
            )

    # -- lifecycle -----------------------------------------------------------

    def _run_control_loop(self):
        """reference: ServeController.run_control_loop (controller.py:395)."""
        while self._running:
            try:
                self._reconcile_once()
                if self._dirty:
                    self._checkpoint()
            except Exception:
                logger.exception("serve reconcile iteration failed")
            time.sleep(self._reconcile_interval_s)

    def shutdown(self):
        self._running = False
        with self._lock:
            deps = list(self._deployments.values())
            self._deployments.clear()
            self._apps.clear()
        for dep in deps:
            dep.target_replicas = 0
            for rid in list(dep.replicas):
                self._stop_replica(dep, rid)
        try:
            # intentional teardown: a later controller must start fresh
            self._kv_call("kv_del", CHECKPOINT_KEY)
            from .autoscale import AUTOSCALE_LOG_KEY

            self._kv_call("kv_del", AUTOSCALE_LOG_KEY)
        except Exception:
            pass
        # sweep the proxy registry (including keys from proxies this
        # controller never saw — a crashed predecessor's leftovers)
        with self._lock:
            self._proxies.clear()
        try:
            for key in self._kv_call(
                "kv_keys", gcs_keys.SERVE_PROXY.scan
            ) or []:
                self._kv_call("kv_del", key)
        except Exception:
            pass
        return True

    # -- deploy API ----------------------------------------------------------

    def deploy_application(self, app_name: str, deployments: List[dict]) -> bool:
        """deployments: [{config, cls_bytes, init_args, init_kwargs}];
        re-deploy updates in place (reference: serve.run upsert)."""
        with self._lock:
            names = {}
            for d in deployments:
                config: DeploymentConfig = d["config"]
                full = f"{app_name}#{config.name}"
                names[config.name] = full
                existing = self._deployments.get(full)
                if existing is None:
                    self._deployments[full] = _DeploymentState(
                        config, d["cls_bytes"], d["init_args"], d["init_kwargs"]
                    )
                else:
                    old_user_config = existing.config.user_config
                    existing.config = config
                    policy = getattr(config, "autoscale_policy", None)
                    if not config.autoscaling_config and policy is None:
                        existing.target_replicas = config.num_replicas
                    elif policy is not None:
                        # keep the autoscaler's target across re-deploys,
                        # clamped into the (possibly new) policy bounds
                        existing.target_replicas = max(
                            policy.min_replicas,
                            min(policy.max_replicas,
                                existing.target_replicas),
                        )
                    if config.user_config != old_user_config:
                        # push new user_config without replica restarts
                        # (reference: reconfigure path)
                        for r in existing.replicas.values():
                            try:
                                r.handle.reconfigure.remote(config.user_config)
                            except Exception:
                                pass
            # deployments dropped by the re-deploy must not keep replicas
            old_names = self._apps.get(app_name, {})
            removed = [
                self._deployments.pop(full)
                for short, full in old_names.items()
                if short not in names and full in self._deployments
            ]
            self._apps[app_name] = names
        for dep in removed:
            for rid in list(dep.replicas):
                self._stop_replica(dep, rid)
        self._checkpoint()
        return True

    def delete_application(self, app_name: str) -> bool:
        with self._lock:
            names = self._apps.pop(app_name, {})
            deps = [
                self._deployments.pop(full)
                for full in names.values()
                if full in self._deployments
            ]
        for dep in deps:
            for rid in list(dep.replicas):
                self._stop_replica(dep, rid)
        self._checkpoint()
        return True

    # -- reconcile -----------------------------------------------------------

    def _reconcile_once(self):
        with self._lock:
            items = list(self._deployments.items())
        # metric payloads are fetched at most once per tick, and only when
        # some SLO-policy deployment is actually due for an evaluation
        payload_cache: Dict[str, list] = {}

        def _payloads() -> list:
            if "p" not in payload_cache:
                try:
                    from ..util.metrics import fetch_metric_payloads

                    payload_cache["p"] = fetch_metric_payloads(self._kv_call)
                except Exception:
                    payload_cache["p"] = []
            return payload_cache["p"]

        node_states = self._fetch_node_states()
        self._poll_proxies()
        for full_name, dep in items:
            self._poll_replicas(dep)
            self._evict_partitioned(dep, node_states)
            self._reap_draining(dep)
            policy = getattr(dep.config, "autoscale_policy", None)
            if policy is not None:
                self._autoscale_slo(full_name, dep, policy, _payloads)
            elif dep.config.autoscaling_config:
                self._autoscale(dep)
            self._converge(full_name, dep)
        self._mirror_replica_inventory()

    def _mirror_replica_inventory(self):
        """Mirror the replica inventory (incl. mesh ownership cards) to the
        GCS KV each tick, the proxy-registry pattern: `ray_tpu list
        replicas` and the dashboard read the KV snapshot instead of a
        controller round-trip, so inventory stays visible even while the
        controller is busy converging."""
        import json as _json

        now = time.time()
        if now - self._last_replica_mirror < 2.0:
            return
        self._last_replica_mirror = now
        rows = []
        with self._lock:
            app_names = list(self._apps)
        for app in app_names:
            for row in self.list_replica_info(app):
                row["app"] = app
                rows.append(row)
        try:
            self._kv_call(
                "kv_put", gcs_keys.SERVE_REPLICAS,
                _json.dumps({"ts": time.time(), "replicas": rows}).encode(),
                True,
            )
        except Exception:
            logger.debug("replica inventory mirror failed", exc_info=True)

    def _poll_replicas(self, dep: _DeploymentState):
        from .. import api

        for rid, replica in list(dep.replicas.items()):
            if replica.state != "RUNNING":
                continue
            try:
                metrics = api.get(replica.handle.get_metrics.remote(), timeout=5)
                replica.queue_len = metrics["queue_len"]
                replica.pid = metrics.get("pid", replica.pid)
                replica.node_id = metrics.get("node_id", replica.node_id)
                replica.affinity_keys = int(metrics.get("affinity_keys", 0))
                replica.warmup_s = float(
                    metrics.get("warmup_s", replica.warmup_s)
                )
                replica.mesh = metrics.get("mesh", replica.mesh)
                replica.consecutive_health_failures = 0
            except Exception:
                replica.consecutive_health_failures += 1
                if replica.consecutive_health_failures >= 3:
                    logger.warning("replica %s unhealthy; replacing", rid)
                    _events.record_event(
                        _events.REPLICA_STATE,
                        deployment=dep.config.name, replica=rid,
                        state="UNHEALTHY", reason="health_probe_failures",
                    )
                    with self._lock:
                        dep.replicas.pop(rid, None)
                        dep.version += 1
                        self._dirty = True
                    try:
                        api.kill(replica.handle)
                    except Exception:
                        pass

    def _fetch_node_states(self) -> Dict[str, str]:
        """node-hex -> ALIVE|SUSPECT|DEAD from the GCS, once per reconcile
        tick. An unreachable GCS returns {} — reconcile must keep running on
        health-probe evidence alone during a controller-side partition."""
        try:
            return self._kv_call("get_node_states") or {}
        except Exception:
            return {}

    def _evict_partitioned(self, dep: _DeploymentState, node_states):
        """Replace replicas on SUSPECT/DEAD nodes without waiting for three
        health-probe failures: the GCS's liveness verdict is the faster,
        cluster-wide signal during a partition. The partitioned node
        self-fences, so the old replica rejects work instead of
        double-serving next to its replacement."""
        from .. import api

        if not node_states:
            return
        for rid, replica in list(dep.replicas.items()):
            if replica.state != "RUNNING" or not replica.node_id:
                continue
            state = node_states.get(replica.node_id, "ALIVE")
            if state == "ALIVE":
                continue
            logger.warning(
                "replica %s on %s node %s; replacing",
                rid, state, replica.node_id,
            )
            _events.record_event(
                _events.REPLICA_STATE,
                deployment=dep.config.name, replica=rid,
                state="UNHEALTHY", reason=f"node_{state.lower()}",
                node=replica.node_id,
            )
            with self._lock:
                dep.replicas.pop(rid, None)
                dep.version += 1
                self._dirty = True
            try:
                api.kill(replica.handle)
            except Exception:
                pass

    def _begin_drain(self, dep: _DeploymentState, rid: str):
        """Transition a RUNNING replica to DRAINING: routers stop picking it
        (routing table filters to RUNNING), the replica finishes in-flight
        and queued work bounded by graceful_shutdown_timeout_s, then acks;
        _reap_draining kills it after the ack or the deadline. Asynchronous —
        reconcile keeps running while the replica drains (reference:
        deployment_state.py graceful-stop via STOPPING states)."""
        with self._lock:
            replica = dep.replicas.get(rid)
            if replica is None or replica.state != "RUNNING":
                return
            replica.state = "DRAINING"
            dep.version += 1
            self._dirty = True
        _events.record_event(
            _events.REPLICA_STATE,
            deployment=dep.config.name, replica=rid, state="DRAINING",
        )
        timeout_s = dep.config.graceful_shutdown_timeout_s
        try:
            replica.drain_ref = replica.handle.drain.remote(timeout_s)
        except Exception:
            replica.drain_ref = None
        # small slack over the replica-side bound so a clean ack wins the race
        replica.drain_deadline = time.time() + timeout_s + 2.0

    def _reap_draining(self, dep: _DeploymentState):
        from .. import api

        for rid, replica in list(dep.replicas.items()):
            if replica.state != "DRAINING":
                continue
            done = replica.drain_ref is None
            if not done:
                try:
                    api.get(replica.drain_ref, timeout=0.05)
                    done = True
                except TimeoutError:
                    done = False
                except Exception:
                    # replica died or drain errored; nothing left to wait for
                    done = True
            if done or time.time() >= replica.drain_deadline:
                with self._lock:
                    dep.replicas.pop(rid, None)
                    dep.version += 1
                    self._dirty = True
                _events.record_event(
                    _events.REPLICA_STOP,
                    deployment=dep.config.name, replica=rid,
                    reason="drained" if done else "drain_deadline",
                )
                try:
                    api.kill(replica.handle)
                except Exception:
                    pass

    def _autoscale(self, dep: _DeploymentState):
        cfg: AutoscalingConfig = dep.config.autoscaling_config
        running = [r for r in dep.replicas.values() if r.state == "RUNNING"]
        if not running:
            return
        total_ongoing = sum(r.queue_len for r in running)
        desired = cfg.desired_replicas(total_ongoing, len(running))
        now = time.time()
        if desired > dep.target_replicas:
            if now - dep.last_scale_up >= cfg.upscale_delay_s:
                logger.info(
                    "autoscale %s: %d -> %d (ongoing=%.1f)",
                    dep.config.name, dep.target_replicas, desired, total_ongoing,
                )
                _events.record_event(
                    _events.AUTOSCALE_DECISION,
                    deployment=dep.config.name, direction="up",
                    from_replicas=dep.target_replicas, to_replicas=desired,
                    ongoing=total_ongoing,
                )
                dep.target_replicas = desired
                dep.last_scale_up = now
        elif desired < dep.target_replicas:
            if now - dep.last_scale_down >= cfg.downscale_delay_s:
                _events.record_event(
                    _events.AUTOSCALE_DECISION,
                    deployment=dep.config.name, direction="down",
                    from_replicas=dep.target_replicas, to_replicas=desired,
                    ongoing=total_ongoing,
                )
                dep.target_replicas = desired
                dep.last_scale_down = now
        else:
            dep.last_scale_up = now
            dep.last_scale_down = now

    def _autoscale_slo(self, full_name, dep, policy, payloads_fn):
        """Closed-loop SLO autoscaler (serve/autoscale.py): every
        ``policy.interval_s`` build the pressure signals — queue depth from
        this tick's replica polls (instantaneous, so sustained pressure
        turns into a scale-up within one evaluation interval), TTFT p99 and
        shed counts as windowed deltas from the metrics push plane — run
        the pure ``evaluate`` state machine, and apply the decision by
        moving ``target_replicas`` (converge does the actual start/drain).
        Every applied decision lands in the autoscale_* metrics and the
        event log."""
        import json as _json

        from . import autoscale as _as

        st = dep.autoscale_state
        if st is None:
            st = dep.autoscale_state = _as.AutoscaleState()
        now = time.time()
        if now - st.last_eval_ts < policy.interval_s:
            return
        st.last_eval_ts = now
        running = [r for r in dep.replicas.values() if r.state == "RUNNING"]
        starting = [r for r in dep.replicas.values() if r.state == "STARTING"]
        if not running:
            return
        payloads = payloads_fn()
        shed_now = _as.shed_total(payloads, dep.config.name)
        queue_depth = float(sum(r.queue_len for r in running))
        sig = _as.AutoscaleSignals(
            queue_depth=queue_depth,
            queue_per_replica=queue_depth / len(running),
            shed_delta=max(0.0, shed_now - st.last_shed_total),
            ttft_p99_ms=_as.ttft_p99_ms(payloads, dep.config.name, st),
            running=len(running),
            starting=len(starting),
            target=dep.target_replicas,
        )
        st.last_shed_total = shed_now
        decision = _as.evaluate(policy, st, sig, now)
        if decision is None:
            return
        with self._lock:
            dep.target_replicas = decision.to_replicas
            self._dirty = True
        from ..util.metrics import record_autoscale_decision

        record_autoscale_decision(
            dep.config.name, decision.direction, decision.breach_age_s
        )
        _events.record_event(
            _events.AUTOSCALE_DECISION,
            deployment=full_name, direction=decision.direction,
            from_replicas=decision.from_replicas,
            to_replicas=decision.to_replicas, reason=decision.reason,
        )
        logger.info(
            "autoscale %s: %s %d -> %d (%s)",
            full_name, decision.direction, decision.from_replicas,
            decision.to_replicas, decision.reason,
        )
        event = {
            "ts": now,
            "deployment": full_name,
            "direction": decision.direction,
            "from": decision.from_replicas,
            "to": decision.to_replicas,
            "reason": decision.reason,
            "breach_age_s": round(decision.breach_age_s, 3),
            "signals": sig.as_dict(),
        }
        self._autoscale_events.append(event)
        del self._autoscale_events[:-_as.LOG_LIMIT]
        try:
            self._kv_call(
                "kv_put",
                _as.AUTOSCALE_LOG_KEY,
                _json.dumps(self._autoscale_events).encode(),
                True,
            )
        except Exception:
            logger.exception("autoscale event-log push failed")

    def _converge(self, full_name: str, dep: _DeploymentState):
        from .. import api

        # DRAINING replicas are lame ducks: they still exist (finishing
        # accepted work) but don't count toward the target, so a drained
        # replica's replacement starts immediately and rolling
        # replacement/scale-down never dips serving capacity to zero
        active = [
            r for r in dep.replicas.values()
            if r.state in ("STARTING", "RUNNING")
        ]
        if len(active) < dep.target_replicas:
            for _ in range(dep.target_replicas - len(active)):
                self._start_replica(full_name, dep)
        elif len(active) > dep.target_replicas:
            excess = len(active) - dep.target_replicas
            # STARTING victims first (nothing accepted yet — cheap kill),
            # then RUNNING ones with the fewest recently-routed prefix-
            # affinity keys (draining a cold replica preserves more of the
            # cluster's reusable KV prefix state), queue length as the tie
            # break
            victims = sorted(
                active,
                key=lambda r: (
                    r.state != "STARTING", r.affinity_keys, r.queue_len,
                ),
            )[:excess]
            for v in victims:
                if v.state == "STARTING":
                    self._stop_replica(dep, v.replica_id)
                else:
                    self._begin_drain(dep, v.replica_id)
        for replica in list(dep.replicas.values()):
            if replica.state == "STARTING":
                # short probe per iteration: a slow-loading replica stays
                # STARTING without stalling reconcile for other deployments
                try:
                    if api.get(replica.handle.check_health.remote(), timeout=2):
                        with self._lock:
                            replica.state = "RUNNING"
                            dep.version += 1
                            self._dirty = True
                        _events.record_event(
                            _events.REPLICA_STATE,
                            deployment=dep.config.name,
                            replica=replica.replica_id, state="RUNNING",
                        )
                except TimeoutError:
                    if (
                        time.time() - replica.started_at
                        > dep.config.startup_timeout_s
                    ):
                        logger.warning(
                            "replica %s startup timed out", replica.replica_id
                        )
                        self._stop_replica(dep, replica.replica_id)
                except Exception:
                    logger.exception(
                        "replica %s failed to start", replica.replica_id
                    )
                    with self._lock:
                        dep.replicas.pop(replica.replica_id, None)
                    try:
                        api.kill(replica.handle)
                    except Exception:
                        pass

    def _start_replica(self, full_name: str, dep: _DeploymentState):
        from .. import api
        from .replica import Replica

        rid = f"{full_name}#{dep.next_replica_idx}"
        dep.next_replica_idx += 1
        opts = dict(dep.config.ray_actor_options or {})
        opts.setdefault("num_cpus", 1)
        # getattr: configs unpickled from a pre-admission-control checkpoint
        # lack the queue knob
        max_queued = getattr(dep.config, "max_queued_requests", 64)
        # headroom above the admission caps so control-plane calls
        # (get_metrics/check_health/drain) are not starved behind a
        # saturated data plane and falsely mark the replica unhealthy —
        # queued requests each hold an actor-concurrency slot while waiting
        opts.setdefault(
            "max_concurrency",
            dep.config.max_ongoing_requests + max(0, max_queued) + 8,
        )
        ReplicaActor = api.remote(**opts)(Replica)
        handle = ReplicaActor.remote(
            dep.config.name,
            rid,
            dep.cls_bytes,
            dep.init_args,
            dep.init_kwargs,
            dep.config.user_config,
            max_ongoing_requests=dep.config.max_ongoing_requests,
            max_queued_requests=max_queued,
        )
        with self._lock:
            dep.replicas[rid] = _ReplicaState(rid, handle)
            self._dirty = True
        _events.record_event(
            _events.REPLICA_START, deployment=dep.config.name, replica=rid,
        )

    def _stop_replica(self, dep: _DeploymentState, rid: str):
        from .. import api

        with self._lock:
            replica = dep.replicas.pop(rid, None)
            if replica is None:
                return
            dep.version += 1
            self._dirty = True
        _events.record_event(
            _events.REPLICA_STOP,
            deployment=dep.config.name, replica=rid, reason="stopped",
        )
        try:
            api.get(
                replica.handle.prepare_for_shutdown.remote(
                    dep.config.graceful_shutdown_timeout_s
                ),
                timeout=dep.config.graceful_shutdown_timeout_s + 2,
            )
        except Exception:
            pass
        try:
            api.kill(replica.handle)
        except Exception:
            pass

    # -- router / status API -------------------------------------------------

    def get_routing_table(self, app_name: str) -> Dict[str, Any]:
        """deployment short-name -> {version, replicas: [(rid, handle,
        queue_len)], router_config}. DRAINING/UNHEALTHY replicas are
        filtered out here, so routers never pick a lame duck; the
        router_config dict distributes the deployment's failover policy to
        every handle (reference: LongPollClient pushing DeploymentConfig)."""
        from .config import RequestRouterConfig

        with self._lock:
            out = {}
            for short, full in self._apps.get(app_name, {}).items():
                dep = self._deployments.get(full)
                if dep is None:
                    continue
                rc = getattr(dep.config, "request_router_config", None) \
                    or RequestRouterConfig()
                out[short] = {
                    "version": dep.version,
                    "replicas": [
                        (r.replica_id, r.handle, r.queue_len)
                        for r in dep.replicas.values()
                        if r.state == "RUNNING"
                    ],
                    "router_config": rc.as_dict(),
                }
            return out

    def drain_replica(self, app_name: str, replica_id: str) -> bool:
        """Chaos/ops entry point: gracefully drain one replica. Converge
        starts its replacement on the next reconcile tick (the drained
        replica stops counting toward the target)."""
        with self._lock:
            candidates = [
                self._deployments[full]
                for full in self._apps.get(app_name, {}).values()
                if full in self._deployments
            ]
        for dep in candidates:
            if replica_id in dep.replicas:
                self._begin_drain(dep, replica_id)
                return True
        return False

    def list_replica_info(self, app_name: str) -> List[Dict[str, Any]]:
        """Replica inventory for the chaos CLI and tests: deployment,
        replica_id, state, pid (SIGKILL/SIGSTOP target), queue_len."""
        with self._lock:
            out = []
            for short, full in self._apps.get(app_name, {}).items():
                dep = self._deployments.get(full)
                if dep is None:
                    continue
                for r in dep.replicas.values():
                    out.append({
                        "deployment": short,
                        "replica_id": r.replica_id,
                        "state": r.state,
                        "pid": r.pid,
                        "node_id": r.node_id,
                        "queue_len": r.queue_len,
                        "affinity_keys": r.affinity_keys,
                        "warmup_s": r.warmup_s,
                        "mesh": r.mesh,
                    })
            return out

    # -- proxy inventory ------------------------------------------------------

    _PROXY_POLL_S = 2.0
    _PROXY_MAX_FAILURES = 3

    def register_proxy(self, proxy_id: str, info: dict, handle) -> bool:
        """Add an ingress proxy to the inventory and mirror its identity to
        the GCS ``proxy:`` prefix (what `ray_tpu proxies`, the dashboard
        and chaos kill-proxy read)."""
        import json as _json

        info = dict(info)
        info.setdefault("proxy_id", proxy_id)
        with self._lock:
            self._proxies[proxy_id] = {
                "info": info, "handle": handle, "state": "RUNNING",
                "failures": 0,
            }
        try:
            self._kv_call(
                "kv_put", gcs_keys.SERVE_PROXY.key(proxy_id),
                _json.dumps(info).encode(), True,
            )
        except Exception:
            logger.exception("proxy registry write failed for %s", proxy_id)
        _events.record_event(
            _events.PROXY_START, proxy_id=proxy_id,
            kind=info.get("kind"), host=info.get("host"),
            port=info.get("port"), pid=info.get("pid"),
        )
        return True

    def deregister_proxy(self, proxy_id: str, reason: str = "stopped") -> bool:
        with self._lock:
            entry = self._proxies.pop(proxy_id, None)
        if entry is None:
            return False
        try:
            self._kv_call("kv_del", gcs_keys.SERVE_PROXY.key(proxy_id))
        except Exception:
            pass
        _events.record_event(
            _events.PROXY_STOP, proxy_id=proxy_id, reason=reason,
        )
        return True

    def list_proxies(self) -> List[Dict[str, Any]]:
        """Proxy inventory rows (identity + state, no actor handles) for
        the CLI / dashboard / chaos kill-proxy."""
        with self._lock:
            return [
                {**e["info"], "proxy_id": pid, "state": e["state"]}
                for pid, e in sorted(self._proxies.items())
            ]

    def drain_proxy(self, proxy_id: str, timeout_s: float = 5.0) -> bool:
        """Gracefully retire one proxy: it refuses new requests (503 +
        Retry-After pushes clients to the survivors), finishes in-flight
        work bounded by ``timeout_s``, then leaves the inventory."""
        from .. import api

        with self._lock:
            entry = self._proxies.get(proxy_id)
            if entry is None:
                return False
            entry["state"] = "DRAINING"
        try:
            ok = api.get(
                entry["handle"].drain.remote(timeout_s),
                timeout=timeout_s + 5,
            )
        except Exception:
            ok = False
        self.deregister_proxy(proxy_id, reason="drained")
        return bool(ok)

    def _poll_proxies(self):
        """Reconcile-loop health pass over the proxy inventory: a proxy
        whose actor died (SIGKILL chaos, node loss) is deregistered at
        once; transient ping failures tolerate _PROXY_MAX_FAILURES
        consecutive misses before eviction."""
        from .. import api
        from ..exceptions import ActorDiedError

        now = time.time()
        if now - self._last_proxy_poll < self._PROXY_POLL_S:
            return
        self._last_proxy_poll = now
        with self._lock:
            items = [
                (pid, e) for pid, e in self._proxies.items()
                if e["state"] == "RUNNING"
            ]
        probes = []
        for pid, entry in items:
            try:
                probes.append((pid, entry, entry["handle"].ping.remote()))
            except Exception:
                probes.append((pid, entry, None))
        for pid, entry, ref in probes:
            dead = False
            ok = False
            if ref is not None:
                try:
                    api.get(ref, timeout=5)
                    ok = True
                except ActorDiedError:
                    dead = True
                except Exception:
                    ok = False
            if ok:
                entry["failures"] = 0
            else:
                entry["failures"] += 1
                if dead or entry["failures"] >= self._PROXY_MAX_FAILURES:
                    logger.warning(
                        "serve proxy %s unresponsive (dead=%s); "
                        "deregistering", pid, dead,
                    )
                    self.deregister_proxy(pid, reason="dead")

    def get_ingress_info(self, app_name: str) -> Dict[str, Any]:
        """How the proxy should talk to the app root: plain request/response,
        item streaming, or ASGI (reference: the proxy's per-app ingress
        resolution, serve/_private/proxy.py:805)."""
        with self._lock:
            first = None
            for short, full in self._apps.get(app_name, {}).items():
                dep = self._deployments.get(full)
                if dep is None:
                    continue
                info = {
                    "deployment": short,
                    "stream": getattr(dep.config, "stream", False),
                    "asgi": getattr(dep.config, "asgi", False),
                }
                if first is None:
                    first = info
                if getattr(dep.config, "ingress", False):
                    return info
            return first or {}

    def autoscale_log(self, limit: int = 100) -> List[dict]:
        """Most recent SLO-autoscaler decisions, oldest first (`ray_tpu
        autoscale log`, tests)."""
        with self._lock:
            return list(self._autoscale_events)[-max(0, limit):]

    def list_applications(self) -> List[str]:
        with self._lock:
            return list(self._apps.keys())

    def get_app_route_prefixes(self) -> Dict[str, str]:
        """route prefix -> app name, for the HTTP proxy."""
        with self._lock:
            out = {}
            for app_name, names in self._apps.items():
                prefix = f"/{app_name}"
                for short, full in names.items():
                    dep = self._deployments.get(full)
                    if dep and dep.config.route_prefix:
                        prefix = dep.config.route_prefix
                out[prefix] = app_name
            return out

    def status(self) -> Dict[str, ApplicationStatus]:
        with self._lock:
            out = {}
            for app_name, names in self._apps.items():
                deps = {}
                app_healthy = True
                for short, full in names.items():
                    dep = self._deployments.get(full)
                    if dep is None:
                        continue
                    replicas = [
                        ReplicaStatus(r.replica_id, r.state, r.queue_len)
                        for r in dep.replicas.values()
                    ]
                    n_running = sum(1 for r in replicas if r.state == "RUNNING")
                    healthy = n_running >= max(1, dep.target_replicas)
                    app_healthy = app_healthy and healthy
                    deps[short] = DeploymentStatus(
                        name=short,
                        status="HEALTHY" if healthy else "UPDATING",
                        replicas=replicas,
                    )
                out[app_name] = ApplicationStatus(
                    name=app_name,
                    status="RUNNING" if app_healthy else "DEPLOYING",
                    deployments=deps,
                )
            return out

    def ping(self):
        return True
