"""Serve configuration dataclasses.

Role-equivalent of the reference's deployment/autoscaling configs
(python/ray/serve/config.py — AutoscalingConfig, DeploymentConfig;
serve/_private/autoscaling_policy.py:12 _calculate_desired_num_replicas).
TPU twist: replicas can reserve TPU chips (``num_tpus`` in
``ray_actor_options``) so a deployment's replica set maps onto chips the
same way the reference maps GPU replicas via NVIDIA visible devices.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Any, Dict, Optional


@dataclass
class AutoscalingConfig:
    min_replicas: int = 1
    max_replicas: int = 10
    target_ongoing_requests: float = 2.0
    # smoothing / stability knobs (reference: autoscaling_policy.py)
    upscale_delay_s: float = 3.0
    downscale_delay_s: float = 10.0
    metrics_interval_s: float = 0.5

    def desired_replicas(
        self, total_ongoing: float, current: int
    ) -> int:
        """reference: _calculate_desired_num_replicas
        (serve/_private/autoscaling_policy.py:12) — scale so each replica
        carries ~target_ongoing_requests."""
        if current <= 0:
            return self.min_replicas
        raw = total_ongoing / max(self.target_ongoing_requests, 1e-9)
        desired = int(math.ceil(raw))
        return max(self.min_replicas, min(self.max_replicas, desired))


@dataclass
class RequestRouterConfig:
    """Handle-side failover policy, distributed to every router via the
    routing table (reference: serve/config.py RequestRouterConfig — there
    it picks the router class; here it parameterizes the retry envelope
    around ``handle.remote()``).

    ``max_attempts`` counts total submissions (1 = no failover).
    ``retry_backpressure`` controls whether a BackPressureError shed is
    retried on another replica or surfaced to the caller immediately —
    proxies surface it (they own the 503/Retry-After contract), plain
    handles retry by default.

    ``prefix_affinity_tokens`` > 0 turns on prefix-affinity routing for
    EVERY router of this deployment — proxies included: each request's
    leading prompt tokens hash onto the shared rendezvous ring
    (serve/hash_ring.py), so all ingress processes send a given prefix to
    the same warm replica without a controller round-trip. A handle-level
    ``options(prefix_affinity_tokens=...)`` still overrides per call site.
    """

    max_attempts: int = 3
    backoff_s: float = 0.05
    default_timeout_s: float = 60.0
    retry_backpressure: bool = True
    prefix_affinity_tokens: int = 0

    def as_dict(self) -> Dict[str, Any]:
        return {
            "max_attempts": self.max_attempts,
            "backoff_s": self.backoff_s,
            "default_timeout_s": self.default_timeout_s,
            "retry_backpressure": self.retry_backpressure,
            "prefix_affinity_tokens": self.prefix_affinity_tokens,
        }


@dataclass
class DeploymentConfig:
    name: str = ""
    num_replicas: int = 1
    max_ongoing_requests: int = 100
    # admission control: requests beyond max_ongoing_requests wait on the
    # replica up to this queue depth; past it the replica sheds with a
    # typed BackPressureError instead of letting latency pile up
    # (reference: serve DeploymentConfig.max_queued_requests)
    max_queued_requests: int = 64
    request_router_config: Optional[RequestRouterConfig] = None
    user_config: Optional[Any] = None
    autoscaling_config: Optional[AutoscalingConfig] = None
    # SLO-driven closed-loop autoscaling (serve/autoscale.py). Takes
    # precedence over autoscaling_config when both are set: the policy
    # reads TTFT p99 / queue depth / shed deltas from live telemetry
    # instead of the single instantaneous ongoing-requests signal.
    autoscale_policy: Optional[Any] = None
    ray_actor_options: Dict[str, Any] = field(default_factory=dict)
    route_prefix: Optional[str] = None
    health_check_period_s: float = 2.0
    graceful_shutdown_timeout_s: float = 5.0
    # replicas still STARTING after this are replaced (raise for slow model
    # loads; reference: initial_health_check_timeout_s semantics)
    startup_timeout_s: float = 300.0
    # streaming/ASGI ingress flags; serve.run auto-detects stream (generator
    # __call__) and asgi (@serve.ingress) and marks the app root as ingress
    # so the HTTP proxy knows how to talk to it
    stream: bool = False
    asgi: bool = False
    ingress: bool = False


@dataclass
class ReplicaStatus:
    replica_id: str
    state: str  # STARTING | RUNNING | DRAINING | STOPPING | DEAD
    queue_len: int = 0


@dataclass
class DeploymentStatus:
    name: str
    status: str  # UPDATING | HEALTHY | UNHEALTHY
    replicas: list = field(default_factory=list)
    message: str = ""


@dataclass
class ApplicationStatus:
    name: str
    status: str  # DEPLOYING | RUNNING | DELETING | NOT_STARTED
    deployments: Dict[str, DeploymentStatus] = field(default_factory=dict)
