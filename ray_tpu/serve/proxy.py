"""HTTP proxy actor: the cluster's ingress.

Role-equivalent of the reference's ProxyActor (python/ray/serve/_private/
proxy.py:1153; HTTP handling :709): terminates HTTP, resolves the route
prefix to an application, forwards the request body to the app's ingress
deployment through a DeploymentHandle, and streams the response back.
aiohttp replaces uvicorn; JSON in/out is the default content type.
"""

from __future__ import annotations

import asyncio
import json
import logging
import threading
from typing import Dict, Optional

logger = logging.getLogger(__name__)


class HTTPProxy:
    """Actor: runs an aiohttp server in a dedicated thread+loop."""

    def __init__(self, controller, host: str = "127.0.0.1", port: int = 8000):
        self._controller = controller
        self._host = host
        self._port = port
        self._routes: Dict[str, str] = {}
        self._handles: Dict[str, object] = {}
        self._ready = threading.Event()
        self._error: Optional[str] = None
        self._thread = threading.Thread(
            target=self._serve_forever, daemon=True, name="http-proxy"
        )
        self._thread.start()
        if not self._ready.wait(timeout=15):
            raise RuntimeError(f"HTTP proxy failed to start: {self._error}")

    # -- server --------------------------------------------------------------

    def _serve_forever(self):
        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        try:
            loop.run_until_complete(self._start_server())
            loop.run_forever()
        except Exception as e:  # noqa: BLE001
            self._error = repr(e)
            self._ready.set()

    async def _start_server(self):
        from aiohttp import web

        app = web.Application()
        app.router.add_route("*", "/-/routes", self._handle_routes)
        app.router.add_route("*", "/-/healthz", self._handle_health)
        app.router.add_route("*", "/{tail:.*}", self._handle_request)
        runner = web.AppRunner(app)
        await runner.setup()
        site = web.TCPSite(runner, self._host, self._port)
        await site.start()
        self._ready.set()

    async def _handle_health(self, request):
        from aiohttp import web

        return web.json_response({"status": "ok"})

    async def _handle_routes(self, request):
        from aiohttp import web

        await self._refresh_routes_async()
        return web.json_response(self._routes)

    async def _refresh_routes_async(self):
        # the blocking handle API must stay off the aiohttp loop, or one
        # slow controller call freezes every in-flight HTTP request
        await asyncio.get_event_loop().run_in_executor(
            None, self._refresh_routes
        )

    def _refresh_routes(self):
        from .. import api

        try:
            self._routes = api.get(
                self._controller.get_app_route_prefixes.remote(), timeout=10
            )
        except Exception:
            logger.exception("route refresh failed")

    def _resolve(self, path: str):
        """Longest-prefix route match -> (app_name, remaining path)."""
        best = None
        for prefix, app_name in self._routes.items():
            if path == prefix or path.startswith(prefix.rstrip("/") + "/") or (
                prefix == "/" and best is None
            ):
                if best is None or len(prefix) > len(best[0]):
                    best = (prefix, app_name)
        return best

    async def _handle_request(self, request):
        from aiohttp import web

        path = "/" + request.match_info["tail"]
        match = self._resolve(path)
        if match is None:
            await self._refresh_routes_async()
            match = self._resolve(path)
        if match is None:
            return web.json_response(
                {"error": f"no app for path {path}"}, status=404
            )
        prefix, app_name = match
        body: object = None
        if request.body_exists:
            raw = await request.read()
            if raw:
                try:
                    body = json.loads(raw)
                except json.JSONDecodeError:
                    body = raw.decode("utf-8", "replace")
        # forward to the app's ingress deployment off-loop (the handle API
        # is blocking); one thread per in-flight request keeps the proxy
        # loop responsive
        result = await asyncio.get_event_loop().run_in_executor(
            None, self._call_ingress, app_name, path, prefix, body
        )
        if isinstance(result, Exception):
            return web.json_response({"error": repr(result)}, status=500)
        if isinstance(result, (dict, list, int, float, str, bool)) or result is None:
            return web.json_response({"result": result})
        return web.Response(body=bytes(result))

    def _call_ingress(self, app_name: str, path: str, prefix: str, body):
        from .api import get_app_handle

        try:
            handle = self._handles.get(app_name)
            if handle is None:
                handle = get_app_handle(app_name, _controller=self._controller)
                self._handles[app_name] = handle
            return handle.remote(body).result(timeout_s=60)
        except Exception as e:  # noqa: BLE001
            return e

    # -- control -------------------------------------------------------------

    def address(self):
        return (self._host, self._port)

    def ping(self):
        return True
