"""HTTP proxy actor: the cluster's ingress.

Role-equivalent of the reference's ProxyActor (python/ray/serve/_private/
proxy.py:1153; HTTP handling :709): terminates HTTP, resolves the route
prefix to an application, forwards the request body to the app's ingress
deployment through a DeploymentHandle, and streams the response back.
aiohttp replaces uvicorn; JSON in/out is the default content type.
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import threading
import time
from typing import Dict, Optional

from .._internal.rpc import RPC_OOB_THRESHOLD as _RPC_OOB_THRESHOLD

logger = logging.getLogger(__name__)


class HTTPProxy:
    """Actor: runs an aiohttp server in a dedicated thread+loop.

    Multi-proxy data plane: N HTTPProxy actors share ONE host:port via
    SO_REUSEPORT (``reuse_port=True``) — the kernel spreads accepted
    connections across the listeners, so ingress scales with proxy count
    with no front-end balancer. Each proxy registers with the controller
    under its ``proxy_id`` (GCS ``proxy:`` prefix) so drains, chaos kills
    and the dashboard address individual proxies."""

    def __init__(self, controller, host: str = "127.0.0.1", port: int = 8000,
                 proxy_id: str = "http#0", reuse_port: bool = False):
        self._controller = controller
        self._host = host
        self._port = port
        self._proxy_id = proxy_id
        self._reuse_port = reuse_port
        self._routes: Dict[str, str] = {}
        self._handles: Dict[str, object] = {}
        self._ingress: Dict[str, dict] = {}
        self._ready = threading.Event()
        self._error: Optional[str] = None
        self._started_at = time.time()
        self._draining = False
        self._inflight = 0
        # pre-bound metric handles + pre-built hot response headers: the
        # request loop must not build tag dicts or header dicts per request
        from ..util.metrics import ingress_handles

        self._m = ingress_handles(proxy_id)
        self._hot_headers = {"X-Proxy-Id": proxy_id}
        self._thread = threading.Thread(
            target=self._serve_forever, daemon=True, name="http-proxy"
        )
        self._thread.start()
        if not self._ready.wait(timeout=15):
            raise RuntimeError(f"HTTP proxy failed to start: {self._error}")

    # -- server --------------------------------------------------------------

    def _serve_forever(self):
        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        try:
            loop.run_until_complete(self._start_server())
            loop.run_forever()
        except Exception as e:  # noqa: BLE001
            self._error = repr(e)
            self._ready.set()

    async def _start_server(self):
        from aiohttp import web

        app = web.Application()
        app.router.add_route("*", "/-/routes", self._handle_routes)
        app.router.add_route("*", "/-/healthz", self._handle_health)
        app.router.add_route("*", "/{tail:.*}", self._handle_request)
        runner = web.AppRunner(app)
        await runner.setup()
        site = web.TCPSite(
            runner, self._host, self._port, reuse_port=self._reuse_port
        )
        await site.start()
        self._ready.set()

    async def _handle_health(self, request):
        from aiohttp import web

        return web.json_response({"status": "ok"})

    async def _handle_routes(self, request):
        from aiohttp import web

        await self._refresh_routes_async()
        return web.json_response(self._routes)

    async def _refresh_routes_async(self):
        # the blocking handle API must stay off the aiohttp loop, or one
        # slow controller call freezes every in-flight HTTP request
        await asyncio.get_event_loop().run_in_executor(
            None, self._refresh_routes
        )

    def _refresh_routes(self):
        from .. import api

        try:
            self._routes = api.get(
                self._controller.get_app_route_prefixes.remote(), timeout=10
            )
            # re-deploys may flip an app's ingress mode (stream/asgi)
            self._ingress.clear()
        except Exception:
            logger.exception("route refresh failed")

    def _resolve(self, path: str):
        """Longest-prefix route match -> (app_name, remaining path)."""
        best = None
        for prefix, app_name in self._routes.items():
            if path == prefix or path.startswith(prefix.rstrip("/") + "/") or (
                prefix == "/" and best is None
            ):
                if best is None or len(prefix) > len(best[0]):
                    best = (prefix, app_name)
        return best

    @staticmethod
    def _request_timeout_s(request) -> Optional[float]:
        """Per-request deadline from the ``X-Request-Timeout-S`` header
        (reference: serve's RAY_SERVE_REQUEST_PROCESSING_TIMEOUT_S header
        override); None defers to the deployment's
        RequestRouterConfig.default_timeout_s (60 s out of the box)."""
        raw = request.headers.get("X-Request-Timeout-S")
        if not raw:
            return None
        try:
            timeout_s = float(raw)
        except ValueError:
            return None
        return timeout_s if timeout_s > 0 else None

    @staticmethod
    def _trace_context(request) -> Optional[dict]:
        """Mint the request's trace at the ingress: honor an inbound
        ``X-Trace-Id`` (caller-chosen id — loadgen/bench join their
        records to server spans with it), else start a fresh trace when
        this process traces. None on the untraced path — requests with no
        header and tracing off cost nothing."""
        from ..util import tracing

        raw = request.headers.get("X-Trace-Id")
        if raw:
            return tracing.new_trace_context(raw.strip()[:64])
        if tracing.is_tracing_enabled():
            return tracing.new_trace_context()
        return None

    @staticmethod
    def _error_response(exc: Exception):
        """Map typed serve errors onto HTTP semantics: backpressure sheds
        are 503 + Retry-After (the client should back off and retry),
        deadline expiry is 504, everything else stays a 500."""
        from aiohttp import web

        from ..exceptions import (
            BackPressureError,
            DeadlineExceededError,
            GetTimeoutError,
        )

        cause = getattr(exc, "cause", None) or exc
        if isinstance(cause, BackPressureError):
            return web.json_response(
                {"error": repr(cause), "retry_after_s": cause.retry_after_s},
                status=503,
                headers={
                    "Retry-After": str(max(1, int(cause.retry_after_s + 0.5)))
                },
            )
        if isinstance(cause, (DeadlineExceededError, GetTimeoutError)):
            return web.json_response({"error": repr(cause)}, status=504)
        return web.json_response({"error": repr(exc)}, status=500)

    async def _handle_request(self, request):
        from aiohttp import web

        if self._draining:
            self._m["drain"].inc()
            return web.json_response(
                {"error": "proxy draining", "retry_after_s": 1.0},
                status=503,
                headers={"Retry-After": "1", "X-Proxy-Id": self._proxy_id},
            )
        t0 = time.perf_counter()
        self._inflight += 1
        self._m["inflight"].set(self._inflight)
        try:
            resp = await self._dispatch(request)
        except Exception as e:  # noqa: BLE001
            resp = self._error_response(e)
        finally:
            self._inflight -= 1
            self._m["inflight"].set(self._inflight)
            self._m["latency"].observe((time.perf_counter() - t0) * 1000.0)
        status = resp.status
        if status < 400:
            self._m["ok"].inc()
        elif status == 503:
            self._m["shed"].inc()
        elif status == 504:
            self._m["timeout"].inc()
        else:
            self._m["error"].inc()
        if not resp.prepared:
            # streaming/ASGI responses stamp the header pre-prepare
            resp.headers.setdefault("X-Proxy-Id", self._proxy_id)
        return resp

    async def _dispatch(self, request):
        from aiohttp import web

        path = "/" + request.match_info["tail"]
        match = self._resolve(path)
        if match is None:
            await self._refresh_routes_async()
            match = self._resolve(path)
        if match is None:
            return web.json_response(
                {"error": f"no app for path {path}"}, status=404
            )
        prefix, app_name = match
        info = await self._ingress_info(app_name)
        if info.get("asgi"):
            return await self._handle_asgi(request, app_name, path, prefix)
        body: object = None
        raw = b""
        if request.body_exists:
            raw = await request.read()
            if raw:
                if request.content_type == "application/octet-stream":
                    # binary fast path: no JSON decode, and large bodies are
                    # wrapped in bytearray so the proxy→replica hop ships
                    # them through the v2 framing's zero-copy out-of-band
                    # buffer path instead of re-pickling the payload inline
                    body = (
                        bytearray(raw)
                        if len(raw) >= _RPC_OOB_THRESHOLD else raw
                    )
                else:
                    try:
                        body = json.loads(raw)
                    except json.JSONDecodeError:
                        body = raw.decode("utf-8", "replace")
        timeout_s = self._request_timeout_s(request)
        trace_ctx = self._trace_context(request)
        if info.get("stream"):
            return await self._handle_stream(request, app_name, body,
                                             timeout_s, trace_ctx)
        # forward to the app's ingress deployment off-loop (the handle API
        # is blocking); one thread per in-flight request keeps the proxy
        # loop responsive
        result = await asyncio.get_event_loop().run_in_executor(
            None, self._call_ingress, app_name, path, prefix, body, timeout_s,
            trace_ctx,
        )
        # untraced hot path reuses ONE prebuilt header dict (aiohttp copies
        # it into the response's CIMultiDict); traced requests echo the
        # trace id so callers can join their latency record with the
        # server-side spans (`ray_tpu timeline`)
        if trace_ctx is None:
            headers = self._hot_headers
        else:
            headers = {"X-Proxy-Id": self._proxy_id,
                       "X-Trace-Id": trace_ctx["trace_id"]}
        if isinstance(result, Exception):
            resp = self._error_response(result)
            resp.headers.update(headers)
            return resp
        if isinstance(result, (dict, list, int, float, str, bool)) or result is None:
            return web.json_response({"result": result}, headers=headers)
        return web.Response(
            body=bytes(result), headers=headers,
            content_type="application/octet-stream",
        )

    _INGRESS_TTL_S = 5.0

    async def _ingress_info(self, app_name: str) -> dict:
        import time

        cached = self._ingress.get(app_name)
        if cached is not None and time.time() - cached[0] < self._INGRESS_TTL_S:
            return cached[1]
        from .. import api

        def fetch():
            try:
                return api.get(
                    self._controller.get_ingress_info.remote(app_name),
                    timeout=10,
                )
            except Exception:
                logger.exception("ingress info fetch failed")
                return {}

        info = await asyncio.get_event_loop().run_in_executor(None, fetch)
        self._ingress[app_name] = (time.time(), info)
        return info

    def _get_handle(self, app_name: str):
        from .api import get_app_handle

        handle = self._handles.get(app_name)
        if handle is None:
            handle = get_app_handle(app_name, _controller=self._controller)
            self._handles[app_name] = handle
        return handle

    def _call_ingress(self, app_name: str, path: str, prefix: str, body,
                      timeout_s: Optional[float] = None,
                      trace_ctx: Optional[dict] = None):
        # the deadline rides through the handle into the replica; the
        # result() wait is bounded by it (default 60 s — no more hardcoded
        # proxy timeout disagreeing with the request's actual budget). The
        # handle absorbs replica deaths/drains (and sheds, per the
        # deployment's RequestRouterConfig); what still escapes maps to
        # typed HTTP statuses in _error_response.
        from ..util import tracing

        try:
            handle = self._get_handle(app_name).options(
                timeout_s=timeout_s
            ) if timeout_s is not None else self._get_handle(app_name)
            if trace_ctx is None and not tracing.is_tracing_enabled():
                # untraced fast path: skip the span contextmanager entirely
                # (even a no-op span allocates the generator + frame; the
                # perf-smoke 5% guard fences this)
                return handle.remote(body).result()
            # the proxy span is the trace's top: route/attempt/replica
            # spans parent under it (this runs on an executor thread, so
            # the task-context install inside is thread-safe)
            with tracing.request_span(
                "serve.proxy", trace_ctx, app=app_name, path=path
            ):
                return handle.remote(body).result()
        except Exception as e:  # noqa: BLE001
            return e

    # -- streaming -----------------------------------------------------------

    async def _iter_stream(self, make_gen):
        """Drive a blocking DeploymentResponseGenerator on a pool thread,
        relaying items onto the aiohttp loop as they arrive — the proxy
        event loop never blocks on the next item. Closing this generator
        (client disconnect, early break) stops the pump so the pool thread
        is released instead of draining the rest of the replica's stream
        into the queue."""
        loop = asyncio.get_event_loop()
        queue: asyncio.Queue = asyncio.Queue()
        _DONE = object()
        stop = threading.Event()

        def pump():
            gen = None
            try:
                gen = make_gen()
                for item in gen:
                    if stop.is_set():
                        break
                    loop.call_soon_threadsafe(queue.put_nowait, item)
            except Exception as e:  # noqa: BLE001 — relayed to the consumer
                loop.call_soon_threadsafe(queue.put_nowait, e)
            finally:
                close = getattr(gen, "close", None)
                if close is not None:
                    try:
                        close()
                    except Exception:  # noqa: BLE001
                        pass
                loop.call_soon_threadsafe(queue.put_nowait, _DONE)

        loop.run_in_executor(None, pump)
        try:
            while True:
                item = await queue.get()
                if item is _DONE:
                    return
                if isinstance(item, Exception):
                    raise item
                yield item
        finally:
            stop.set()

    async def _handle_stream(self, request, app_name: str, body,
                             timeout_s: Optional[float] = None,
                             trace_ctx: Optional[dict] = None):
        """Generator ingress -> chunked HTTP: newline-delimited JSON, or SSE
        when the client asks for text/event-stream (reference: proxy
        streaming of DeploymentResponseGenerator outputs). Teardown (client
        disconnect, early close) closes the DeploymentResponseGenerator,
        which cancels the replica-side generator — the replica stops
        producing tokens nobody will read."""
        from aiohttp import web

        sse = "text/event-stream" in request.headers.get("Accept", "")
        resp = web.StreamResponse()
        resp.content_type = "text/event-stream" if sse else "application/x-ndjson"
        resp.headers["X-Proxy-Id"] = self._proxy_id
        if trace_ctx:
            resp.headers["X-Trace-Id"] = trace_ctx["trace_id"]
        await resp.prepare(request)

        def make_gen():
            from ..util import tracing

            opts = {"stream": True}
            if timeout_s is not None:
                opts["timeout_s"] = timeout_s
            handle = self._get_handle(app_name).options(**opts)
            if trace_ctx is None:
                return handle.remote(body)
            # covers submission only (items stream on after it closes);
            # the replica-side stream span covers the generation itself
            with tracing.request_span(
                "serve.proxy", trace_ctx, app=app_name, stream=True
            ):
                return handle.remote(body)

        from contextlib import aclosing

        try:
            async with aclosing(self._iter_stream(make_gen)) as stream:
                async for item in stream:
                    if isinstance(item, (bytes, bytearray)):
                        chunk = bytes(item)
                    elif sse:
                        chunk = f"data: {json.dumps(item)}\n\n".encode()
                    else:
                        chunk = (json.dumps(item) + "\n").encode()
                    await resp.write(chunk)
        except Exception as e:  # noqa: BLE001 — stream already started
            err = json.dumps({"error": repr(e)})
            # keep the error inside the negotiated framing or SSE parsers
            # silently drop it
            await resp.write(
                f"data: {err}\n\n".encode() if sse else (err + "\n").encode()
            )
        await resp.write_eof()
        return resp

    async def _handle_asgi(self, request, app_name: str, path: str,
                           prefix: str):
        """ASGI ingress: build an ASGI-3 HTTP scope from the aiohttp
        request, stream it through the replica's __asgi__ method, and relay
        response-start/body events back as they arrive (reference: the
        proxy's ASGI protocol with ingress replicas, proxy.py:805)."""
        from aiohttp import web

        root = prefix.rstrip("/")
        scope = {
            "type": "http",
            "asgi": {"version": "3.0", "spec_version": "2.3"},
            "http_version": "1.1",
            "method": request.method,
            "scheme": "http",
            "path": path[len(root):] or "/" if path.startswith(root) else path,
            "raw_path": path.encode(),
            "root_path": root,
            "query_string": request.query_string.encode(),
            "headers": [
                (k.lower().encode(), v.encode())
                for k, v in request.headers.items()
            ],
            "client": None,
            "server": (self._host, self._port),
        }
        body = await request.read() if request.body_exists else b""

        def make_gen():
            return (
                self._get_handle(app_name)
                .options(stream=True, method_name="__asgi__")
                .remote(scope, body)
            )

        from contextlib import aclosing

        resp = None

        async def relay():
            nonlocal resp
            async with aclosing(self._iter_stream(make_gen)) as stream:
                async for event in stream:
                    etype = event.get("type")
                    if etype == "http.response.start":
                        resp = web.StreamResponse(
                            status=event.get("status", 200)
                        )
                        for k, v in event.get("headers", []):
                            name = k.decode() if isinstance(k, bytes) else k
                            val = v.decode() if isinstance(v, bytes) else v
                            # aiohttp computes framing itself
                            if name.lower() not in ("content-length",
                                                    "transfer-encoding"):
                                resp.headers[name] = val
                        await resp.prepare(request)
                    elif etype == "http.response.body":
                        if resp is None:
                            raise RuntimeError(
                                "ASGI app sent body before response start"
                            )
                        await resp.write(event.get("body", b""))
                        if not event.get("more_body"):
                            return
                    elif etype == "asgi.error":
                        raise RuntimeError(
                            event.get("error", "ASGI app failed")
                        )

        try:
            await relay()
        except Exception as e:  # noqa: BLE001
            if resp is None:
                return web.json_response({"error": repr(e)}, status=500)
            await resp.write(json.dumps({"error": repr(e)}).encode())
        if resp is None:
            return web.json_response(
                {"error": "ASGI app sent no response"}, status=500
            )
        await resp.write_eof()
        return resp

    # -- control -------------------------------------------------------------

    def address(self):
        return (self._host, self._port)

    def ping(self):
        return True

    def describe(self) -> dict:
        """Identity record the controller writes under the GCS ``proxy:``
        prefix — what `ray_tpu proxies`, the dashboard and chaos kill-proxy
        see."""
        from ..util.metrics import _node_hex

        return {
            "kind": "http",
            "proxy_id": self._proxy_id,
            "host": self._host,
            "port": self._port,
            "pid": os.getpid(),
            "node": _node_hex(),
            "started_at": self._started_at,
        }

    def stats(self) -> dict:
        return {"proxy_id": self._proxy_id, "inflight": self._inflight,
                "draining": self._draining}

    def drain(self, timeout_s: float = 5.0) -> bool:
        """Stop accepting (new requests get 503 + Retry-After so clients
        move to a surviving proxy), then wait — bounded — for in-flight
        requests to finish. Returns True when the proxy drained clean."""
        from ..util import events as _events

        self._draining = True
        deadline = time.time() + timeout_s
        while self._inflight > 0 and time.time() < deadline:
            time.sleep(0.02)
        _events.record_event(
            _events.PROXY_DRAIN, proxy_id=self._proxy_id, kind="http",
            inflight=self._inflight,
        )
        return self._inflight == 0
