"""Model multiplexing: many models time-share one replica.

Role-equivalent of the reference's serve.multiplexed /
get_multiplexed_model_id (python/ray/serve/multiplex.py + api.py): the
caller tags a request with a model id
(``handle.options(multiplexed_model_id="m1").remote(...)``); the replica's
``@serve.multiplexed`` loader keeps an LRU cache of loaded models (on TPU:
param pytrees resident in HBM), loading on miss and evicting the least
recently used model beyond the cap.
"""

from __future__ import annotations

import asyncio
import contextvars
import functools
import inspect
from collections import OrderedDict
from typing import Any, Callable

_model_id_ctx: contextvars.ContextVar[str] = contextvars.ContextVar(
    "ray_tpu_serve_multiplexed_model_id", default=""
)


def get_multiplexed_model_id() -> str:
    """Model id of the current request (reference:
    serve.get_multiplexed_model_id)."""
    return _model_id_ctx.get()


def _set_multiplexed_model_id(model_id: str):
    """Bind the model id in the CURRENT task's context. asyncio tasks copy
    the context at creation, so a task spawned to run work on behalf of
    tagged callers (replica request handling, @serve.batch's per-model
    batch task) must re-bind explicitly — setting here never leaks into the
    callers' contexts."""
    _model_id_ctx.set(model_id)


class _ModelCache:
    def __init__(self, loader: Callable, max_models: int):
        self._loader = loader
        self._max = max_models
        self._cache: "OrderedDict[str, Any]" = OrderedDict()
        self._loading: dict = {}  # model_id -> future (dedup concurrent loads)

    async def get(self, self_obj, model_id: str):
        if model_id in self._cache:
            self._cache.move_to_end(model_id)
            return self._cache[model_id]
        fut = self._loading.get(model_id)
        if fut is not None:
            return await fut
        loop = asyncio.get_running_loop()
        fut = loop.create_future()
        self._loading[model_id] = fut
        try:
            if self_obj is not None:
                model = await self._loader(self_obj, model_id)
            else:
                model = await self._loader(model_id)
            while len(self._cache) >= self._max:
                # Evict = drop our reference. In-flight requests still hold
                # theirs, so device buffers (jax arrays free on GC) are
                # released only when the last user finishes — calling a
                # finalizer here would free HBM mid-use and CPython would
                # run __del__ a second time at GC.
                self._cache.popitem(last=False)
            self._cache[model_id] = model
            fut.set_result(model)
            return model
        except Exception as e:  # noqa: BLE001
            fut.set_exception(e)
            raise
        finally:
            self._loading.pop(model_id, None)
            # consume the exception if nobody else awaited the future
            if fut.done() and fut.exception() is not None:
                fut.exception()


def multiplexed(_fn=None, *, max_num_models_per_replica: int = 3):
    """Decorator for an async model loader: ``@serve.multiplexed`` /
    ``@serve.multiplexed(max_num_models_per_replica=8)``
    ``async def get_model(self, model_id): ...`` (reference: serve.multiplexed)."""

    def deco(fn):
        if not inspect.iscoroutinefunction(fn):
            raise TypeError("@serve.multiplexed requires an async def loader")
        params = list(inspect.signature(fn).parameters)
        is_method = bool(params) and params[0] == "self"
        attr = f"__serve_multiplex_cache_{fn.__name__}"

        if is_method:
            async def wrapper(self, model_id: str = ""):
                model_id = model_id or get_multiplexed_model_id()
                if not model_id:
                    raise ValueError(
                        "no model id: pass one or set multiplexed_model_id "
                        "on the handle"
                    )
                cache = getattr(self, attr, None)
                if cache is None:
                    cache = _ModelCache(fn, max_num_models_per_replica)
                    setattr(self, attr, cache)
                return await cache.get(self, model_id)
        else:
            state: dict = {}

            async def wrapper(model_id: str = ""):
                model_id = model_id or get_multiplexed_model_id()
                if not model_id:
                    raise ValueError(
                        "no model id: pass one or set multiplexed_model_id "
                        "on the handle"
                    )
                cache = state.get("c")
                if cache is None:
                    cache = state["c"] = _ModelCache(
                        fn, max_num_models_per_replica
                    )
                return await cache.get(None, model_id)

        return functools.wraps(fn)(wrapper)

    if _fn is not None:
        return deco(_fn)
    return deco
