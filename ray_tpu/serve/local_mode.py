"""Local testing mode: run a Serve app in-process, no cluster.

Role-equivalent of the reference's local testing mode
(serve/_private/local_testing_mode.py, ``serve.run(..,
_local_testing_mode=True)``): deployments are instantiated in the caller's
process, handles call them directly, and async def methods (including
@serve.batch / @serve.multiplexed machinery) run on a private event-loop
thread — so unit tests exercise the exact user code without paying for
controller/proxy/replica actors.
"""

from __future__ import annotations

import asyncio
import threading
from typing import Any, Dict, Optional


class _LocalLoop:
    """One shared event-loop thread for all local replicas' async methods."""

    _instance: Optional["_LocalLoop"] = None

    def __init__(self):
        self.loop = asyncio.new_event_loop()
        t = threading.Thread(
            target=self.loop.run_forever, name="serve-local", daemon=True
        )
        t.start()

    @classmethod
    def get(cls) -> "_LocalLoop":
        if cls._instance is None:
            cls._instance = _LocalLoop()
        return cls._instance

    def run(self, coro, timeout=None):
        return asyncio.run_coroutine_threadsafe(coro, self.loop).result(timeout)


class LocalDeploymentResponse:
    """Mirror of DeploymentResponse for local mode: the request is already
    in flight (dispatched eagerly, like the real handle) and ``result``
    just waits."""

    def __init__(self, future, default_timeout_s: Optional[float] = None):
        self._future = future
        self._default_timeout_s = default_timeout_s

    def result(self, timeout_s: Optional[float] = None):
        if timeout_s is None:
            timeout_s = self._default_timeout_s
        return self._future.result(timeout_s)


class LocalResponseGenerator:
    """Local-mode mirror of DeploymentResponseGenerator: drains a queue fed
    by the generator running on the local loop, so items arrive as produced."""

    _DONE = object()

    def __init__(self, queue):
        self._queue = queue

    def __iter__(self):
        return self

    def __next__(self):
        item = self._queue.get()
        if item is self._DONE:
            raise StopIteration
        if isinstance(item, Exception):
            raise item
        return item


class LocalDeploymentHandle:
    """Calls the in-process instance directly (reference: the local-mode
    handle in local_testing_mode.py)."""

    def __init__(self, instances: Dict[str, Any], deployment: str,
                 method: str = "__call__", multiplexed_model_id: str = "",
                 stream: bool = False, prefix_affinity_tokens: int = 0,
                 timeout_s: Optional[float] = None):
        self._instances = instances
        self._deployment = deployment
        self._method = method
        self._multiplexed_model_id = multiplexed_model_id
        self._stream = stream
        # accepted for parity with DeploymentHandle.options so code under
        # test can set them unconditionally; with one in-process instance
        # there is nothing to bias, and timeout_s bounds the result() wait
        self._prefix_affinity_tokens = prefix_affinity_tokens
        self._timeout_s = timeout_s

    def options(self, *, method_name: Optional[str] = None,
                multiplexed_model_id: Optional[str] = None,
                stream: Optional[bool] = None,
                prefix_affinity_tokens: Optional[int] = None,
                timeout_s: Optional[float] = None):
        return LocalDeploymentHandle(
            self._instances,
            self._deployment,
            method_name if method_name is not None else self._method,
            multiplexed_model_id
            if multiplexed_model_id is not None
            else self._multiplexed_model_id,
            stream if stream is not None else self._stream,
            prefix_affinity_tokens
            if prefix_affinity_tokens is not None
            else self._prefix_affinity_tokens,
            timeout_s if timeout_s is not None else self._timeout_s,
        )

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        return LocalDeploymentHandle(
            self._instances, self._deployment, name,
            self._multiplexed_model_id, self._stream,
            self._prefix_affinity_tokens, self._timeout_s,
        )

    def _remote_stream(self, *args, **kwargs) -> "LocalResponseGenerator":
        import inspect
        import queue as queue_mod

        instance = self._instances[self._deployment]
        method = (
            instance
            if self._method == "__call__" and not hasattr(instance, "__call__")
            else getattr(instance, self._method)
        )
        out: queue_mod.Queue = queue_mod.Queue()
        loop = _LocalLoop.get().loop
        model_id = self._multiplexed_model_id

        _SENTINEL = object()

        async def drive():
            import contextvars

            try:
                if model_id:
                    from .multiplex import _set_multiplexed_model_id

                    _set_multiplexed_model_id(model_id)
                gen = method(*args, **kwargs)
                if inspect.isasyncgen(gen):
                    async for item in gen:
                        out.put(item)
                elif inspect.isgenerator(gen):
                    # sync generators step on a thread under the copied
                    # context (generator bodies see the context of each
                    # next(), so the model-id var must ride along); a
                    # blocking next() must not freeze the shared local loop
                    ctx = contextvars.copy_context()
                    while True:
                        item = await loop.run_in_executor(
                            None, lambda: ctx.run(next, gen, _SENTINEL)
                        )
                        if item is _SENTINEL:
                            break
                        out.put(item)
                else:
                    raise TypeError(
                        "stream=True requires a generator method; "
                        f"{self._method!r} returned {type(gen).__name__}"
                    )
            except Exception as e:  # noqa: BLE001 — relayed to the consumer
                out.put(e)
            finally:
                out.put(LocalResponseGenerator._DONE)

        asyncio.run_coroutine_threadsafe(drive(), loop)
        return LocalResponseGenerator(out)

    def remote(self, *args, **kwargs) -> LocalDeploymentResponse:
        if self._stream:
            return self._remote_stream(*args, **kwargs)
        import contextvars

        instance = self._instances[self._deployment]
        method = (
            instance
            if self._method == "__call__" and not hasattr(instance, "__call__")
            else getattr(instance, self._method)
        )
        model_id = self._multiplexed_model_id
        loop = _LocalLoop.get().loop

        async def invoke():
            if asyncio.iscoroutinefunction(method):
                if model_id:
                    from .multiplex import _set_multiplexed_model_id

                    # this task's context only — no leak across requests
                    _set_multiplexed_model_id(model_id)
                return await method(*args, **kwargs)
            # sync method: run on a thread (the loop must keep serving
            # concurrent requests, e.g. @serve.batch coalescing), inside a
            # context copy so the model-id var never leaks to later calls
            def call():
                if model_id:
                    from .multiplex import _set_multiplexed_model_id

                    _set_multiplexed_model_id(model_id)
                return method(*args, **kwargs)

            ctx = contextvars.copy_context()
            return await loop.run_in_executor(None, lambda: ctx.run(call))

        # eager dispatch, matching the real handle: fire-and-forget calls
        # still execute and concurrent requests actually overlap
        future = asyncio.run_coroutine_threadsafe(invoke(), loop)
        return LocalDeploymentResponse(
            future, default_timeout_s=self._timeout_s
        )


def run_local(app, name: str = "default") -> LocalDeploymentHandle:
    """Instantiate every deployment in-process and return the root handle."""
    from .api import Application, _BoundDeployment

    nodes = app._collect()
    instances: Dict[str, Any] = {}

    def resolve(obj):
        if isinstance(obj, Application):
            obj = obj.root
        if isinstance(obj, _BoundDeployment):
            return LocalDeploymentHandle(instances, obj.deployment.name)
        if isinstance(obj, tuple):
            return tuple(resolve(x) for x in obj)
        if isinstance(obj, list):
            return [resolve(x) for x in obj]
        if isinstance(obj, dict):
            return {k: resolve(v) for k, v in obj.items()}
        return obj

    for node in nodes:
        target = node.deployment._target
        args = resolve(node.init_args)
        kwargs = resolve(node.init_kwargs)
        if isinstance(target, type):
            instances[node.deployment.name] = target(*args, **kwargs)
        else:
            # function deployment: the "instance" is the function itself
            instances[node.deployment.name] = target
    return LocalDeploymentHandle(instances, app.root.deployment.name)
