"""ObjectRef: a distributed future.

Role-equivalent of the reference's ObjectRef (includes/object_ref.pxi): wraps
an ObjectID plus the owner's address. The process that created the ref (via
``put`` or task submission) owns the object's metadata and lifetime; when the
last Python reference in the owning process drops, the owner releases the
object (reference: reference_counter.h local-ref accounting via __dealloc__).
"""

from __future__ import annotations

from typing import Optional, Tuple

from ._internal.ids import ObjectID


class ObjectRef:
    __slots__ = ("id", "owner_address", "_registered", "__weakref__")

    def __init__(
        self,
        object_id: ObjectID,
        owner_address: Optional[Tuple[str, int]] = None,
        *,
        _register: bool = True,
    ):
        self.id = object_id
        self.owner_address = owner_address
        self._registered = False
        if _register:
            from . import _worker_api

            worker = _worker_api.maybe_get_core_worker()
            if worker is not None:
                worker.register_ref(self)
                self._registered = True

    def hex(self) -> str:
        return self.id.hex()

    def binary(self) -> bytes:
        return self.id.binary()

    def task_id(self):
        return self.id.task_id()

    def __hash__(self):
        return hash(self.id)

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other.id == self.id

    def __repr__(self):
        return f"ObjectRef({self.id.hex()})"

    def __del__(self):
        if self._registered:
            try:
                from . import _worker_api
            except ImportError:
                return  # interpreter shutdown
            worker = _worker_api.maybe_get_core_worker()
            if worker is not None:
                try:
                    worker.unregister_ref(self)
                except Exception:
                    pass

    def __reduce__(self):
        # Serializing a ref (into task args or object values) makes the
        # receiver a borrower; the owner address travels with the ref. An
        # active arg-flattening collector records the ref so nested refs get
        # pinned for the task's flight (serialization.collect_refs).
        from ._internal import serialization

        serialization.record_serialized_ref(self)
        return (_deserialize_ref, (self.id, self.owner_address))

    def future(self):
        """Return a concurrent.futures.Future resolving to the value."""
        from . import _worker_api

        return _worker_api.get_core_worker().as_future(self)

    def __await__(self):
        import asyncio

        return asyncio.wrap_future(self.future()).__await__()


def _deserialize_ref(object_id, owner_address):
    return ObjectRef(object_id, owner_address)


class ObjectRefGenerator:
    """Iterator over a streaming-generator task's yielded objects.

    Role-equivalent of the reference's ObjectRefGenerator
    (_private/object_ref_generator.py:32 backed by TryReadObjectRefStream,
    core_worker.h:306): ``next()`` blocks until the executor reports the
    next yielded item (items stream while the task still runs) and returns
    its ObjectRef; StopIteration at end-of-stream; a mid-stream task error
    raises after the already-yielded items are consumed.
    """

    def __init__(self, task_id):
        self._task_id = task_id

    def __iter__(self):
        return self

    def __next__(self) -> "ObjectRef":
        from . import _worker_api

        worker = _worker_api.get_core_worker()
        ref = _worker_api.run_on_worker_loop(
            worker.next_stream_item(self._task_id)
        )
        if ref is None:
            raise StopIteration
        return ref

    def __repr__(self):
        return f"ObjectRefGenerator({self._task_id.hex()})"

    def close(self):
        """Eagerly release the owner's stream bookkeeping (don't wait for
        GC): the next item the executor reports finds no stream state and
        learns the consumer is gone, so the replica-side generator is
        closed instead of producing into the void."""
        from . import _worker_api

        worker = _worker_api.maybe_get_core_worker()
        if worker is None:
            return
        try:
            worker.loop.call_soon_threadsafe(
                worker.drop_stream, self._task_id
            )
        except RuntimeError:
            pass

    def __del__(self):
        # abandoning the generator releases the owner's stream bookkeeping
        # (a failed or half-consumed stream must not pin state forever)
        try:
            from . import _worker_api
        except ImportError:
            return  # interpreter shutdown
        worker = _worker_api.maybe_get_core_worker()
        if worker is None:
            return
        try:
            worker.loop.call_soon_threadsafe(
                worker.drop_stream, self._task_id
            )
        except RuntimeError:
            pass
