"""Project-invariant static analysis (``ray_tpu lint``).

Every review round of this codebase has caught the same *classes* of bug by
hand: process-global trace state cross-contaminating concurrent tasks,
blocking calls inside the worker's async RPC loop, lock-guarded attributes
mutated bare from another method, metric names colliding, and stray GCS key
f-strings nobody sweeps. This package encodes those invariants as
machine-checked rules over the repo's own AST, so they gate every future PR
instead of relying on reviewer memory.

Structure:

- :mod:`.core` — finding model, checker plugin registry, single-pass file
  walker (each file is parsed once; every registered checker sees the tree).
- :mod:`.checkers` — the project-specific rules RT001..RT006, distilled from
  this repo's actual bug history (see each module's docstring for the
  incident it encodes).
- :mod:`.baseline` — committed grandfather list for pre-existing findings.
  Policy: shrink-only. New code never adds baseline entries.

Run it: ``python -m ray_tpu.scripts.cli lint [--json]``. The tier-1 gate
test (``tests/test_analysis.py``) fails on any non-baselined finding.
"""

from .core import (  # noqa: F401
    Analyzer,
    AnalysisResult,
    Checker,
    Finding,
    checker_catalog,
    register,
)
from .baseline import (  # noqa: F401
    DEFAULT_BASELINE_PATH,
    apply_baseline,
    load_baseline,
    write_baseline,
)

# importing the subpackage registers every built-in checker
from . import checkers  # noqa: F401  isort: skip
