"""Baseline: committed grandfather list for pre-existing findings.

The baseline is a JSON file mapping finding fingerprints (rule + path +
message, line-number-free so unrelated edits don't churn it) to the finding
as last observed. ``ray_tpu lint`` subtracts it from the live findings;
anything left fails the gate.

Policy: **shrink-only, never grow.** A new PR fixes its findings instead of
baselining them; entries disappear when the underlying finding is fixed
(``lint --baseline-update`` rewrites the file from the current findings and
the gate test fails on *stale* entries too, so a fixed finding forces the
baseline to shrink in the same PR).
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Iterable, List, Optional, Tuple

from .core import Finding

#: the committed repo baseline, next to this module
DEFAULT_BASELINE_PATH = Path(__file__).resolve().parent / "baseline.json"

_VERSION = 1


def load_baseline(path: Optional[Path | str] = None) -> List[dict]:
    """Baseline entries (possibly empty). Raises on a malformed file —
    a silently-ignored baseline would un-gate the whole repo."""
    p = Path(path) if path is not None else DEFAULT_BASELINE_PATH
    if not p.exists():
        return []
    doc = json.loads(p.read_text(encoding="utf-8"))
    if not isinstance(doc, dict) or doc.get("version") != _VERSION:
        raise ValueError(f"unsupported baseline format in {p}")
    entries = doc.get("findings", [])
    if not isinstance(entries, list):
        raise ValueError(f"baseline {p} 'findings' must be a list")
    return entries


def write_baseline(
    findings: Iterable[Finding], path: Optional[Path | str] = None
) -> Path:
    """Rewrite the baseline from the given findings (sorted, stable)."""
    p = Path(path) if path is not None else DEFAULT_BASELINE_PATH
    entries = [f.to_dict() for f in findings]
    entries.sort(key=lambda e: (e["rule"], e["path"], e["message"]))
    doc = {
        "version": _VERSION,
        "policy": "shrink-only: fix new findings, never add entries",
        "findings": entries,
    }
    p.write_text(json.dumps(doc, indent=2) + "\n", encoding="utf-8")
    return p


def _entry_fingerprint(entry: dict) -> str:
    return f"{entry.get('rule')}::{entry.get('path')}::{entry.get('message')}"


def apply_baseline(
    findings: Iterable[Finding], entries: Iterable[dict]
) -> Tuple[List[Finding], List[Finding], List[dict]]:
    """Split live findings against the baseline.

    Returns ``(new, suppressed, stale)``: findings not in the baseline,
    findings matched by it, and baseline entries whose finding no longer
    exists (the shrink-only gate fails on those until the file is updated).
    """
    by_fp = {_entry_fingerprint(e): e for e in entries}
    new: List[Finding] = []
    suppressed: List[Finding] = []
    seen_fps = set()
    for f in findings:
        if f.fingerprint in by_fp:
            suppressed.append(f)
            seen_fps.add(f.fingerprint)
        else:
            new.append(f)
    stale = [e for fp, e in by_fp.items() if fp not in seen_fps]
    return new, suppressed, stale
