"""Analysis core: finding model, checker registry, single-pass walker.

Design goals, in order: zero dependencies beyond stdlib ``ast`` (the lint
gate must run wherever the tests run), one parse per file no matter how many
checkers are registered, and deterministic output (findings sorted, stable
fingerprints) so the committed baseline diffs cleanly.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Type

#: directories never scanned (relative path parts)
_SKIP_PARTS = {"__pycache__", ".git", "build", "dist"}


@dataclass(frozen=True)
class Finding:
    """One rule violation at one site."""

    rule: str  #: rule id, e.g. "RT003"
    path: str  #: repo-relative posix path
    line: int  #: 1-based line number
    message: str  #: human-readable description of the violation

    @property
    def fingerprint(self) -> str:
        """Stable identity for baseline matching. Excludes the line number
        on purpose: unrelated edits above a grandfathered finding must not
        un-baseline it."""
        return f"{self.rule}::{self.path}::{self.message}"

    def to_dict(self) -> dict:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "message": self.message,
        }


class Checker:
    """Base class for one rule.

    Subclasses set ``RULE_ID``/``DESCRIPTION``, optionally narrow
    ``applies_to``, and implement ``check_file``. Cross-file rules collect
    state in ``check_file`` and emit from ``finalize`` (called once after
    every file has been visited). A fresh checker instance is built per
    :class:`Analyzer` run, so instance state never leaks between runs.
    """

    RULE_ID: str = ""
    DESCRIPTION: str = ""

    def applies_to(self, path: str) -> bool:
        """``path`` is repo-relative posix; return False to skip the file."""
        return True

    def check_file(
        self, path: str, tree: ast.AST, source: str
    ) -> Iterable[Finding]:
        return ()

    def finalize(self) -> Iterable[Finding]:
        return ()

    def finding(self, path: str, node: ast.AST, message: str) -> Finding:
        return Finding(
            rule=self.RULE_ID,
            path=path,
            line=getattr(node, "lineno", 0),
            message=message,
        )


_CHECKERS: Dict[str, Type[Checker]] = {}


def register(cls: Type[Checker]) -> Type[Checker]:
    """Class decorator adding a checker to the global plugin registry."""
    if not cls.RULE_ID:
        raise ValueError(f"{cls.__name__} must set RULE_ID")
    existing = _CHECKERS.get(cls.RULE_ID)
    if existing is not None and existing is not cls:
        raise ValueError(f"duplicate checker rule id {cls.RULE_ID}")
    _CHECKERS[cls.RULE_ID] = cls
    return cls


def checker_catalog() -> Dict[str, Type[Checker]]:
    """rule id -> checker class, for ``lint --rules`` and the docs table."""
    # the subpackage import is what registers the built-ins; tolerate being
    # called before ray_tpu.analysis.__init__ finished (cyclic first import)
    from . import checkers  # noqa: F401

    return dict(sorted(_CHECKERS.items()))


@dataclass
class AnalysisResult:
    findings: List[Finding] = field(default_factory=list)
    files_scanned: int = 0
    parse_errors: List[str] = field(default_factory=list)

    def by_rule(self) -> Dict[str, int]:
        counts: Dict[str, int] = {}
        for f in self.findings:
            counts[f.rule] = counts.get(f.rule, 0) + 1
        return dict(sorted(counts.items()))


class Analyzer:
    """Single-pass AST walker over a directory (or one file).

    ``rel_to`` is the base findings are reported relative to; it defaults to
    the parent of ``root`` so scanning ``<repo>/ray_tpu`` yields paths like
    ``ray_tpu/serve/handle.py`` — the shape the committed baseline uses.
    """

    def __init__(
        self,
        root: Path | str,
        rules: Optional[Sequence[str]] = None,
        rel_to: Optional[Path | str] = None,
    ):
        self.root = Path(root).resolve()
        self.rel_to = (
            Path(rel_to).resolve() if rel_to is not None
            else (self.root.parent if self.root.is_dir() else self.root.parent)
        )
        catalog = checker_catalog()
        if rules is not None:
            unknown = set(rules) - set(catalog)
            if unknown:
                raise ValueError(f"unknown rule id(s): {sorted(unknown)}")
            catalog = {rid: catalog[rid] for rid in catalog if rid in set(rules)}
        self.checkers: List[Checker] = [cls() for cls in catalog.values()]

    def _iter_files(self) -> Iterable[Path]:
        if self.root.is_file():
            yield self.root
            return
        for path in sorted(self.root.rglob("*.py")):
            if any(part in _SKIP_PARTS for part in path.parts):
                continue
            yield path

    def run(self) -> AnalysisResult:
        result = AnalysisResult()
        for path in self._iter_files():
            rel = path.relative_to(self.rel_to).as_posix()
            try:
                source = path.read_text(encoding="utf-8", errors="replace")
                tree = ast.parse(source, filename=rel)
            except SyntaxError as e:
                result.parse_errors.append(f"{rel}:{e.lineno}: {e.msg}")
                continue
            result.files_scanned += 1
            for checker in self.checkers:
                if checker.applies_to(rel):
                    result.findings.extend(checker.check_file(rel, tree, source))
        for checker in self.checkers:
            result.findings.extend(checker.finalize())
        result.findings.sort(key=lambda f: (f.path, f.line, f.rule, f.message))
        return result
