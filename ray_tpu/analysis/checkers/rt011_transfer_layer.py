"""RT011: KV block bytes cross processes only via the transfer layer.

Incident class this encodes: the disaggregated serving work (PR 17).
KV shipments and peer prefix pulls move multi-megabyte block payloads
between replicas; the shared pinned-buffer transfer layer
(``ray_tpu/_internal/transfer.py``) is the one place that knows how to
chunk them, pin the source buffers for zero-copy pulls, probe a holder
before fetching (the 2s dead-peer probe), and account logical vs wire
bytes for the int8 codec. A direct ``worker.put_serialized(...)`` or a
raw GCS ``call("store_put", ...)`` in the serving plane bypasses all of
that: the bytes land unpinned (a peer pull then copies), unprobed (a
dead holder hangs the puller for the full RPC timeout), and invisible
to the ``kvtier_transfer_bytes_total`` split.

Flags, in ``ray_tpu/kvtier/``, ``ray_tpu/kvcache/`` and ``ray_tpu/llm/``:

- any ``X.put_serialized(...)`` attribute call — the object-plane raw
  put primitive;
- any ``X.call("store_put", ...)`` — the same primitive reached through
  a GCS/raylet RPC client.

``_internal/transfer.py`` itself is outside the scanned paths: that IS
the chokepoint. Route new KV byte movement through ``put_chunks`` /
``fetch_chunk`` there so pinning, probing and byte accounting stay in
one audited place.
"""

from __future__ import annotations

import ast

from ..core import Checker, register


@register
class TransferLayerChecker(Checker):
    RULE_ID = "RT011"
    DESCRIPTION = (
        "raw object-plane put in the serving KV path (kvtier/kvcache/llm); "
        "route KV bytes through _internal/transfer.py"
    )

    def applies_to(self, path: str) -> bool:
        parts = path.split("/")
        return any(p in ("kvtier", "kvcache", "llm") for p in parts[:-1])

    def check_file(self, path, tree, source):
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if not isinstance(func, ast.Attribute):
                continue
            if func.attr == "put_serialized":
                yield self.finding(
                    path, node,
                    "direct put_serialized() in the serving KV path "
                    "bypasses pinning, dead-peer probing and wire-byte "
                    "accounting; route KV bytes through "
                    "_internal/transfer.py (put_chunks/fetch_chunk)",
                )
                continue
            if (
                func.attr == "call"
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and node.args[0].value == "store_put"
            ):
                yield self.finding(
                    path, node,
                    'raw call("store_put", ...) in the serving KV path '
                    "bypasses the transfer layer; route KV bytes through "
                    "_internal/transfer.py (put_chunks/fetch_chunk)",
                )
