"""RT004: metrics registry consistency.

Incident this encodes: the metrics plane keys the process-wide registry by
metric *name* (``util/metrics._registry[name]``) — two constructions with
the same name silently alias one ``Metric`` object, and a tag-set mismatch
between them makes ``prometheus_text`` emit series whose label tuples don't
line up (the PR 3 review's last-worker-wins summary bug was the read-side
twin of this). The invariants:

- every ``Counter``/``Gauge``/``Histogram`` name is a **literal**
  snake_case string (a computed name defeats grep, the baseline, and the
  dashboard's metric tables);
- each name is declared exactly **once**, and only in ``util/metrics.py``
  (the single place ``_ensure_*`` lazy-init guards already live — a
  declaration elsewhere races the pusher's registry snapshot);
- when the same name *is* seen more than once (the fixture case), their
  ``tag_keys`` must agree — a cross-file check, emitted from finalize().

Import-aware: a file that does ``from collections import Counter`` is
ignored; only names bound from ``util.metrics`` (or used inside
``util/metrics.py`` itself) count as metric constructors.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Tuple

from ..astutil import str_const
from ..core import Checker, Finding, register

_METRIC_CLASSES = {"Counter", "Gauge", "Histogram"}
_SNAKE_RE = re.compile(r"^[a-z][a-z0-9_]*$")
_HOME_FILE = "util/metrics.py"


def _metric_bindings(tree: ast.AST, path: str) -> Dict[str, str]:
    """local name -> metric class, honoring imports. In util/metrics.py the
    classes are defined locally so the bare names always bind."""
    bound: Dict[str, str] = {}
    if path.endswith(_HOME_FILE):
        for cls in _METRIC_CLASSES:
            bound[cls] = cls
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module and (
            node.module.endswith("util.metrics") or node.module == "metrics"
        ):
            for alias in node.names:
                if alias.name in _METRIC_CLASSES:
                    bound[alias.asname or alias.name] = alias.name
        elif isinstance(node, ast.ImportFrom) and node.module == "collections":
            for alias in node.names:
                # shadows a metric-class name with collections.Counter
                bound.pop(alias.asname or alias.name, None)
    return bound


@register
class MetricsRegistryChecker(Checker):
    RULE_ID = "RT004"
    DESCRIPTION = (
        "metric names: literal snake_case, declared once in util/metrics.py,"
        " consistent tag sets"
    )

    def __init__(self):
        # name -> list of (path, line, tag_keys or None)
        self._declarations: Dict[str, List[Tuple[str, int, Optional[tuple]]]] = {}

    def check_file(self, path, tree, source):
        bound = _metric_bindings(tree, path)
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            cls = self._metric_class(node, bound)
            if cls is None:
                continue
            name_node = node.args[0] if node.args else None
            name = str_const(name_node) if name_node is not None else None
            if name is None:
                yield self.finding(
                    path, node,
                    f"{cls} name must be a literal string (computed names "
                    f"defeat the registry audit)",
                )
                continue
            if not _SNAKE_RE.match(name):
                yield self.finding(
                    path, node,
                    f"metric name {name!r} is not snake_case",
                )
            if not path.endswith(_HOME_FILE):
                yield self.finding(
                    path, node,
                    f"metric {name!r} declared outside util/metrics.py — "
                    f"all declarations live there so names can't collide",
                )
            self._declarations.setdefault(name, []).append(
                (path, node.lineno, self._tag_keys(node))
            )

    def finalize(self):
        for name, decls in sorted(self._declarations.items()):
            if len(decls) > 1:
                sites = ", ".join(f"{p}:{ln}" for p, ln, _ in decls)
                yield Finding(
                    rule=self.RULE_ID, path=decls[0][0], line=decls[0][1],
                    message=f"metric {name!r} declared {len(decls)} times "
                            f"({sites}) — the registry keys by name, later "
                            f"declarations alias the first",
                )
            tag_sets = {t for _, _, t in decls if t is not None}
            if len(tag_sets) > 1:
                p, ln, _ = decls[0]
                yield Finding(
                    rule=self.RULE_ID, path=p, line=ln,
                    message=f"metric {name!r} declared with conflicting "
                            f"tag_keys {sorted(tag_sets)}",
                )

    @staticmethod
    def _metric_class(node: ast.Call, bound: Dict[str, str]) -> Optional[str]:
        func = node.func
        if isinstance(func, ast.Name):
            return bound.get(func.id)
        if (
            isinstance(func, ast.Attribute)
            and func.attr in _METRIC_CLASSES
            and isinstance(func.value, ast.Name)
            and func.value.id in ("metrics", "ray_metrics")
        ):
            return func.attr
        return None

    @staticmethod
    def _tag_keys(node: ast.Call) -> Optional[tuple]:
        for kw in node.keywords:
            if kw.arg == "tag_keys" and isinstance(
                kw.value, (ast.Tuple, ast.List)
            ):
                keys = [str_const(e) for e in kw.value.elts]
                if all(k is not None for k in keys):
                    return tuple(keys)
        return ()
