"""RT007: flight-recorder event-name registry consistency.

The RT004 twin for the flight recorder (``util/events.py``): the event
taxonomy is the process-wide ``_registry`` keyed by event *name*, and the
``ray_tpu events --name X`` query plane plus the docs' event table are only
trustworthy if every name is greppable and declared in one place. The
invariants:

- every ``EventName(...)`` construction takes a **literal** snake_case
  string (a computed name defeats grep and the post-mortem query filter);
- each name is constructed exactly **once**, and only in
  ``util/events.py`` — the single home of the taxonomy, so an emitter
  can't mint a private name that the docs and CLI never learn about.

Import-aware like RT004: only ``EventName`` bound from ``util.events``
(or used inside ``util/events.py`` itself) counts; an unrelated local
class of the same name in some other module is ignored.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Tuple

from ..astutil import str_const
from ..core import Checker, Finding, register

_EVENT_CLASS = "EventName"
_SNAKE_RE = re.compile(r"^[a-z][a-z0-9_]*$")
_HOME_FILE = "util/events.py"


def _event_bindings(tree: ast.AST, path: str) -> Dict[str, str]:
    """local name -> 'EventName', honoring imports. In util/events.py the
    class is defined locally so the bare name always binds."""
    bound: Dict[str, str] = {}
    if path.endswith(_HOME_FILE):
        bound[_EVENT_CLASS] = _EVENT_CLASS
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module and (
            node.module.endswith("util.events") or node.module == "events"
        ):
            for alias in node.names:
                if alias.name == _EVENT_CLASS:
                    bound[alias.asname or alias.name] = _EVENT_CLASS
    return bound


@register
class EventRegistryChecker(Checker):
    RULE_ID = "RT007"
    DESCRIPTION = (
        "flight-recorder event names: literal snake_case, declared once in"
        " util/events.py"
    )

    def __init__(self):
        # name -> list of (path, line)
        self._declarations: Dict[str, List[Tuple[str, int]]] = {}

    def check_file(self, path, tree, source):
        bound = _event_bindings(tree, path)
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            if self._event_class(node, bound) is None:
                continue
            name_node = node.args[0] if node.args else None
            name = str_const(name_node) if name_node is not None else None
            if name is None:
                yield self.finding(
                    path, node,
                    "EventName must be constructed from a literal string "
                    "(computed names defeat the taxonomy audit and "
                    "`ray_tpu events --name`)",
                )
                continue
            if not _SNAKE_RE.match(name):
                yield self.finding(
                    path, node,
                    f"event name {name!r} is not snake_case",
                )
            if not path.endswith(_HOME_FILE):
                yield self.finding(
                    path, node,
                    f"event {name!r} declared outside util/events.py — the "
                    f"taxonomy lives there so the docs/CLI can't drift",
                )
            self._declarations.setdefault(name, []).append(
                (path, node.lineno)
            )

    def finalize(self):
        for name, decls in sorted(self._declarations.items()):
            if len(decls) > 1:
                sites = ", ".join(f"{p}:{ln}" for p, ln in decls)
                yield Finding(
                    rule=self.RULE_ID, path=decls[0][0], line=decls[0][1],
                    message=f"event {name!r} declared {len(decls)} times "
                            f"({sites}) — the registry keys by name, later "
                            f"declarations alias the first",
                )

    @staticmethod
    def _event_class(node: ast.Call, bound: Dict[str, str]):
        func = node.func
        if isinstance(func, ast.Name):
            return bound.get(func.id)
        if (
            isinstance(func, ast.Attribute)
            and func.attr == _EVENT_CLASS
            and isinstance(func.value, ast.Name)
            and func.value.id in ("events", "_events")
        ):
            return func.attr
        return None
