"""RT001: no blocking calls inside ``async def`` bodies.

Incident this encodes: the core worker's RPC server runs task executions
concurrently on one event loop — a single ``time.sleep`` or blocking
``Future.result()`` inside a coroutine stalls every in-flight task, lease
renewal, and health heartbeat on that worker ("Exploring the limits of
Concurrency on TPUs" dies on exactly this class of host-side stall). The
sanctioned escapes are ``asyncio.sleep`` and handing the blocking closure to
an executor (``_run_traced`` in the worker; ``run_in_executor`` elsewhere).

Flagged inside any ``async def`` (nested sync ``def`` s are exempt — they
are the executor-thunk idiom and run on a thread):

- ``time.sleep(...)`` (any alias of the module or the function)
- ``<fut>.result()`` / ``<fut>.result(timeout=None)`` — a blocking
  concurrent-futures wait; await the future instead

Scope: the asyncio planes of the codebase — ``runtime/``, ``serve/``,
``dag/``, ``client/``, and the dashboard. Synchronous leaf libraries
(collective rendezvous loops, loadgen dispatch threads) legitimately sleep.
"""

from __future__ import annotations

import ast

from ..astutil import call_name, time_aliases, walk_shallow
from ..core import Checker, register

_SCOPE_DIRS = {"runtime", "serve", "dag", "client", "dashboard"}


@register
class BlockingInAsyncChecker(Checker):
    RULE_ID = "RT001"
    DESCRIPTION = (
        "blocking call (time.sleep / Future.result) inside an async def"
    )

    def applies_to(self, path: str) -> bool:
        return bool(_SCOPE_DIRS.intersection(path.split("/")))

    def check_file(self, path, tree, source):
        time_mods, sleep_names = time_aliases(tree)
        for node in ast.walk(tree):
            if not isinstance(node, ast.AsyncFunctionDef):
                continue
            for child in walk_shallow(node):
                if not isinstance(child, ast.Call):
                    continue
                name = call_name(child)
                if name is not None:
                    mod, _, attr = name.rpartition(".")
                    if (mod in time_mods and attr == "sleep") or (
                        not mod and attr in sleep_names
                    ):
                        yield self.finding(
                            path, child,
                            f"time.sleep inside async def "
                            f"{node.name!r}: use asyncio.sleep or an "
                            f"executor hand-off",
                        )
                        continue
                if (
                    isinstance(child.func, ast.Attribute)
                    and child.func.attr == "result"
                    and self._is_blocking_result(child)
                ):
                    yield self.finding(
                        path, child,
                        f"blocking .result() inside async def "
                        f"{node.name!r}: await the future (or wrap it "
                        f"with asyncio.wrap_future)",
                    )

    @staticmethod
    def _is_blocking_result(call: ast.Call) -> bool:
        """.result() with no bound, or an explicit timeout=None — an
        unbounded blocking wait. A finite timeout is still a stall but is
        at least bounded; keep the rule sharp (zero false positives on
        deliberate short waits) rather than broad."""
        if not call.args and not call.keywords:
            return True
        if len(call.args) == 1 and not call.keywords:
            a = call.args[0]
            return isinstance(a, ast.Constant) and a.value is None
        if (
            not call.args
            and len(call.keywords) == 1
            and call.keywords[0].arg == "timeout"
        ):
            v = call.keywords[0].value
            return isinstance(v, ast.Constant) and v.value is None
        return False
