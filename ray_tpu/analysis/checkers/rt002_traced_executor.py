"""RT002: traced-executor discipline in the core worker.

Incident this encodes: PR 3's review found the worker's task trace context
living in a process global — the RPC server executes tasks concurrently via
``ensure_future``, so the global cross-contaminated concurrent tasks'
parentage and non-LIFO exits left workers permanently "tracing on". The fix
was two-part and both halves are load-bearing:

1. trace context is a coroutine-local ``contextvars.ContextVar``;
2. every hop onto an executor thread goes through
   ``CoreWorker._run_traced``, which ``copy_context()``-s the dispatching
   coroutine's context across so user code on the thread sees the right
   parent span.

This rule keeps both from regressing:

- in ``core_worker.py``, any ``*.run_in_executor(...)`` call outside the
  ``_run_traced`` definition is flagged (a raw hop silently drops the trace
  context *and* whatever future ContextVars ride along);
- in ``core_worker.py`` and ``tracing.py``, a module-level assignment that
  names trace/span/context state but is not a ``ContextVar(...)`` is
  flagged (the original PR 3 bug shape).
"""

from __future__ import annotations

import ast
import re

from ..astutil import call_name
from ..core import Checker, register

_TRACE_STATE_RE = re.compile(
    r"^_?(current|active|task)_?(trace|span|context|ctx)\w*$"
)


@register
class TracedExecutorChecker(Checker):
    RULE_ID = "RT002"
    DESCRIPTION = (
        "run_in_executor outside _run_traced / non-ContextVar trace state"
    )

    def applies_to(self, path: str) -> bool:
        base = path.rsplit("/", 1)[-1]
        return base in ("core_worker.py", "tracing.py")

    def check_file(self, path, tree, source):
        base = path.rsplit("/", 1)[-1]
        if base == "core_worker.py":
            yield from self._check_executor_sites(path, tree)
        yield from self._check_module_trace_state(path, tree)

    def _check_executor_sites(self, path, tree):
        # line spans of every `_run_traced` definition: calls inside are the
        # one sanctioned raw run_in_executor site
        sanctioned = [
            (n.lineno, n.end_lineno)
            for n in ast.walk(tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
            and n.name == "_run_traced"
        ]
        for node in ast.walk(tree):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "run_in_executor"
            ):
                if any(lo <= node.lineno <= hi for lo, hi in sanctioned):
                    continue
                yield self.finding(
                    path, node,
                    "run_in_executor must route through _run_traced so the "
                    "dispatching coroutine's contextvars (trace context) "
                    "reach the executor thread",
                )

    def _check_module_trace_state(self, path, tree):
        for node in tree.body:
            targets = []
            value = None
            if isinstance(node, ast.Assign):
                targets, value = node.targets, node.value
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                targets, value = [node.target], node.value
            for target in targets:
                if not isinstance(target, ast.Name):
                    continue
                if not _TRACE_STATE_RE.match(target.id):
                    continue
                if self._is_contextvar(value):
                    continue
                yield self.finding(
                    path, node,
                    f"module-global trace state {target.id!r} must be a "
                    f"contextvars.ContextVar (a process global "
                    f"cross-contaminates concurrent tasks)",
                )

    @staticmethod
    def _is_contextvar(value: ast.AST) -> bool:
        if not isinstance(value, ast.Call):
            return False
        name = call_name(value) or ""
        return name.split(".")[-1] == "ContextVar"
