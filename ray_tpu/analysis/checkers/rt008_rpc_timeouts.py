"""RT008: control-plane RPCs must carry a bounded timeout.

Incident this encodes: the PR 11 partition work. Under a directional
partition (or a chaos-mesh blackhole) an un-deadlined ``client.call(...)``
never returns — the awaiting coroutine parks forever, the caller's state
machine wedges, and the hang watchdog is the first thing to notice. Every
control-plane RPC on the GCS/raylet/serve planes must therefore bound its
wait: either a ``timeout=`` kwarg on ``.call(...)``, a ``timeout=`` /
``total_timeout=`` budget on ``retry_call(...)``, or an enclosing
``asyncio.wait_for``. Data-plane fire-and-forget sends (``call_oneway``)
never block on a reply, so they are exempt.

Flags ``<expr>.call(...)`` and ``retry_call(...)`` sites on the control
planes that carry none of ``timeout=`` / ``total_timeout=`` / ``deadline=``,
are not wrapped in ``asyncio.wait_for``, and do not splat ``**kwargs``
(a splat may forward a caller-supplied budget; static analysis can't see
through it, so it gets the benefit of the doubt).
"""

from __future__ import annotations

import ast
from typing import Set

from ..core import Checker, register

_PLANE_PREFIXES = (
    "ray_tpu/runtime/gcs/",
    "ray_tpu/runtime/raylet/",
    "ray_tpu/serve/",
)
_PLANE_FILES = ("ray_tpu/runtime/node.py",)

_BOUND_KWARGS = {"timeout", "total_timeout", "deadline"}


def _is_rpc_call(node: ast.Call) -> bool:
    func = node.func
    if isinstance(func, ast.Attribute):
        return func.attr in ("call", "retry_call")
    if isinstance(func, ast.Name):
        return func.id == "retry_call"
    return False


def _is_bounded(node: ast.Call) -> bool:
    for kw in node.keywords:
        if kw.arg is None:  # **kwargs splat may forward a budget
            return True
        if kw.arg in _BOUND_KWARGS:
            return True
    return False


def _wait_for_wrapped(tree: ast.AST) -> Set[int]:
    """ids of Call nodes appearing as arguments to asyncio.wait_for."""
    wrapped: Set[int] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        name = func.attr if isinstance(func, ast.Attribute) else getattr(
            func, "id", ""
        )
        if name != "wait_for":
            continue
        for arg in node.args:
            if isinstance(arg, ast.Call):
                wrapped.add(id(arg))
    return wrapped


@register
class RpcTimeoutChecker(Checker):
    RULE_ID = "RT008"
    DESCRIPTION = (
        "control-plane .call()/retry_call() without a bounded "
        "timeout/deadline (hangs forever under partition)"
    )

    def applies_to(self, path: str) -> bool:
        return path.startswith(_PLANE_PREFIXES) or path in _PLANE_FILES

    def check_file(self, path, tree, source):
        wrapped = _wait_for_wrapped(tree)
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call) or not _is_rpc_call(node):
                continue
            if id(node) in wrapped or _is_bounded(node):
                continue
            func = node.func
            name = (
                func.attr if isinstance(func, ast.Attribute) else func.id
            )
            yield self.finding(
                path, node,
                f"control-plane {name}() without timeout=/total_timeout=/"
                f"deadline= blocks forever under a network partition; "
                f"bound it or wrap in asyncio.wait_for",
            )
