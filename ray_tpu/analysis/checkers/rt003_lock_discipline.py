"""RT003: lock discipline — attributes guarded in one method, bare in another.

Incident this encodes: PR 2's review found the weight subscriber's
``_current``/``_prefetched`` mutated under ``self._lock`` on the adoption
path but written bare from the prefetch thread — the lost-race branch
orphaned pins. PR 4's allocator had the same shape. The invariant: once a
class protects an attribute with ``with self.<lock>:`` anywhere, every
*mutation* of that attribute in every other method must hold the lock too.

Mechanics: per class, collect attributes assigned (or aug-assigned) on
``self`` inside a ``with self.<something matching 'lock'>:`` block; then
flag assignments to those attributes outside any lock block in *other*
methods. Deliberate limits to stay honest (low false-positive) rather than
complete:

- ``__init__``/``__del__``/``__enter__``/``__exit__`` are exempt — setup
  and teardown run before/after concurrency exists;
- bare *reads* are not flagged (too many benign monotonic-flag reads; the
  write side is where lost updates corrupt state);
- only ``self``-attribute locks are recognized, which is this codebase's
  only locking idiom.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set, Tuple

from ..core import Checker, register

_EXEMPT_METHODS = {"__init__", "__del__", "__enter__", "__exit__",
                   "__post_init__"}


def _lock_attr_name(item: ast.withitem) -> bool:
    """True if the with-item is ``self.<attr>`` where attr names a lock."""
    expr = item.context_expr
    # `with self._lock:` and `with self._lock.something():` both count? No:
    # only the bare acquire; a method call on the lock object is not an
    # acquisition we can reason about.
    return (
        isinstance(expr, ast.Attribute)
        and isinstance(expr.value, ast.Name)
        and expr.value.id == "self"
        and "lock" in expr.attr.lower()
    )


def _self_attr_writes(node: ast.AST) -> List[Tuple[str, int]]:
    """(attr, lineno) for every ``self.X = ...`` / ``self.X += ...`` in the
    subtree, not descending into nested functions/classes."""
    out = []
    stack = [node]
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda,
                          ast.ClassDef)) and n is not node:
            continue
        if isinstance(n, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = (
                n.targets if isinstance(n, ast.Assign) else [n.target]
            )
            for t in targets:
                if (
                    isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"
                ):
                    out.append((t.attr, n.lineno))
        stack.extend(ast.iter_child_nodes(n))
    return out


@register
class LockDisciplineChecker(Checker):
    RULE_ID = "RT003"
    DESCRIPTION = (
        "attribute assigned under `with self._lock:` in one method but "
        "mutated bare in another"
    )

    def check_file(self, path, tree, source):
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef):
                yield from self._check_class(path, node)

    def _check_class(self, path, cls: ast.ClassDef):
        methods = [
            n for n in cls.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]
        # pass 1: which attrs does any method write under a lock?
        guarded: Dict[str, str] = {}  # attr -> method that guards it
        for m in methods:
            for w in ast.walk(m):
                if isinstance(w, (ast.With, ast.AsyncWith)) and any(
                    _lock_attr_name(i) for i in w.items
                ):
                    for attr, _line in _self_attr_writes(w):
                        guarded.setdefault(attr, m.name)
        if not guarded:
            return
        # pass 2: bare writes to those attrs in *other* methods
        for m in methods:
            if m.name in _EXEMPT_METHODS:
                continue
            locked_spans = [
                (w.lineno, w.end_lineno)
                for w in ast.walk(m)
                if isinstance(w, (ast.With, ast.AsyncWith))
                and any(_lock_attr_name(i) for i in w.items)
            ]
            for attr, line in _self_attr_writes(m):
                if attr not in guarded or guarded[attr] == m.name:
                    continue
                if any(lo <= line <= hi for lo, hi in locked_spans):
                    continue
                yield self.finding(
                    path,
                    _LineNode(line),
                    f"{cls.name}.{m.name} assigns self.{attr} without the "
                    f"lock that guards it in {cls.name}.{guarded[attr]}",
                )


class _LineNode:
    """Minimal stand-in carrying a line number for Checker.finding()."""

    def __init__(self, lineno: int):
        self.lineno = lineno
