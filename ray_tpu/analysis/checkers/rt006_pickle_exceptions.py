"""RT006: typed exceptions must be pickle-safe.

Incident this encodes: framework exceptions travel as object values — a
failed task stores its exception, ``get`` re-raises it at the caller, and
the serve retry envelope switches on the *type*. An exception class with a
custom ``__init__`` but no ``__reduce__`` breaks that silently:
``pickle.dumps`` stores ``(cls, self.args)``, and since ``args`` holds the
*formatted message* (one string) instead of the constructor's parameters,
``pickle.loads`` either raises ``TypeError`` (arity mismatch) or rebuilds a
husk whose typed fields (``retry_after_s``, ``deadline``, ...) are gone —
exactly what the PR 7 retry policy reads on the caller side.

Rule, applied to ``exceptions.py``: every exception class whose
``__init__`` takes parameters beyond ``self`` must define ``__reduce__``
in its own body. (The dynamic twin — an actual ``pickle.loads(pickle.
dumps(e))`` structural round-trip of every class — lives in
``tests/test_analysis.py``.)
"""

from __future__ import annotations

import ast

from ..core import Checker, register


@register
class PickleSafeExceptionChecker(Checker):
    RULE_ID = "RT006"
    DESCRIPTION = (
        "exception with a custom __init__ but no __reduce__ (breaks "
        "pickle round-trip of typed fields)"
    )

    def applies_to(self, path: str) -> bool:
        return path.rsplit("/", 1)[-1] == "exceptions.py"

    def check_file(self, path, tree, source):
        for node in ast.walk(tree):
            if not isinstance(node, ast.ClassDef):
                continue
            init = None
            has_reduce = False
            for item in node.body:
                if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    if item.name == "__init__":
                        init = item
                    elif item.name in ("__reduce__", "__reduce_ex__",
                                       "__getnewargs__", "__getstate__"):
                        has_reduce = True
            if init is None or has_reduce:
                continue
            args = init.args
            extra = (
                len(args.args) - 1  # beyond self
                + len(args.posonlyargs)
                + len(args.kwonlyargs)
                + (1 if args.vararg else 0)
                + (1 if args.kwarg else 0)
            )
            if extra <= 0:
                continue
            yield self.finding(
                path, node,
                f"exception {node.name!r} has a custom __init__ but no "
                f"__reduce__: pickle will rebuild it from the formatted "
                f"message and drop/mangle its typed fields",
            )
