"""RT010: train-loop gradient reduction goes through the scheduler.

Incident class this encodes: the overlapped-collectives work (PR 16).
A bare blocking ``group.allreduce(grads)`` at the step boundary of a train
loop exposes the whole collective on the critical path — exactly the time
the bucketized async scheduler (collective/scheduler.py) exists to hide —
and silently bypasses the exposed-vs-overlapped StepBreakdown split, so the
regression doesn't even show up in the metrics. Inside ``ray_tpu/train/``
gradient reduction must route through ``GradientReduceScheduler`` (or its
session-level wrapper ``train.collective.reduce_gradients``): the scheduler
degrades to the blocking path when ``overlap=False``, so there is no
"simple case" that justifies calling the group directly.

Flags, in ``ray_tpu/train/`` modules:

- attribute calls ``X.allreduce(...)`` / ``X.reducescatter(...)`` — a
  direct blocking collective on a group object;
- bare ``allreduce(...)`` / ``reducescatter(...)`` name calls (the
  module-level ``ray_tpu.collective`` wrappers imported into a loop).

The body of a function literally named ``allreduce`` is exempt: that is
the sanctioned small-host-value control-plane wrapper
(``train/collective.py``) — scalar consensus (loss averaging, early-stop
votes), not gradient traffic. Scheduler internals are out of scope by
construction (they live in ``collective/``, not ``train/``).
"""

from __future__ import annotations

import ast
from typing import Set

from ..core import Checker, register

_REDUCE_OPS = {"allreduce", "reducescatter"}


def _wrapper_spans(tree: ast.AST) -> Set[int]:
    """ids of all nodes inside a FunctionDef named allreduce (the
    sanctioned control-plane wrapper)."""
    exempt: Set[int] = set()
    for node in ast.walk(tree):
        if (
            isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
            and node.name == "allreduce"
        ):
            for sub in ast.walk(node):
                exempt.add(id(sub))
    return exempt


@register
class SchedulerReduceChecker(Checker):
    RULE_ID = "RT010"
    DESCRIPTION = (
        "blocking gradient reduction in train/ hot paths; route it "
        "through GradientReduceScheduler / train.collective.reduce_gradients"
    )

    def applies_to(self, path: str) -> bool:
        parts = path.split("/")
        return "train" in parts[:-1]

    def check_file(self, path, tree, source):
        exempt = _wrapper_spans(tree)
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call) or id(node) in exempt:
                continue
            func = node.func
            if isinstance(func, ast.Attribute) and func.attr in _REDUCE_OPS:
                yield self.finding(
                    path, node,
                    f".{func.attr}() directly on a collective group in "
                    "train/ blocks the step on the full reduce; use "
                    "GradientReduceScheduler (train.collective."
                    "reduce_gradients) so it can overlap",
                )
            elif isinstance(func, ast.Name) and func.id in _REDUCE_OPS:
                yield self.finding(
                    path, node,
                    f"bare {func.id}() in train/ bypasses the overlapped "
                    "scheduler; use train.collective.reduce_gradients",
                )
