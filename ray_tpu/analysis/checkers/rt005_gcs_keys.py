"""RT005: GCS key-space hygiene — no stray key-prefix literals.

Incident this encodes: the PR 5 collective seq-key leak. Rendezvous keys
were minted by f-strings scattered across the collective layer; the epoch
sweep didn't know one of the formats existed, so every abnormal exit leaked
its in-flight keys forever. The fix (and this rule's invariant): every
reserved prefix of the GCS KV key space is declared once in
``runtime/gcs/keys.py`` and every key is minted through that registry, so
writers, scanners, and sweepers can never drift apart.

Flags any string literal — plain or the literal head of an f-string —
starting with ``<registered-prefix>:`` outside ``runtime/gcs/keys.py``.
Docstrings are exempt (prose may name keys); comments are invisible to the
AST anyway. The prefix list is imported from the registry itself, so adding
a prefix automatically extends enforcement.
"""

from __future__ import annotations

import ast
from typing import Set, Tuple

from ...runtime.gcs import keys as gcs_keys
from ..astutil import docstring_positions, fstring_literal_head, str_const
from ..core import Checker, register

_HOME_FILE = "runtime/gcs/keys.py"


def _scan_prefixes() -> Tuple[str, ...]:
    return tuple(f"{name}:" for name in gcs_keys.known_prefixes())


@register
class GcsKeyHygieneChecker(Checker):
    RULE_ID = "RT005"
    DESCRIPTION = (
        "GCS key literal bypassing the runtime/gcs/keys.py prefix registry"
    )

    def __init__(self):
        self._prefixes = _scan_prefixes()

    def applies_to(self, path: str) -> bool:
        return not path.endswith(_HOME_FILE)

    def check_file(self, path, tree, source):
        skip: Set[Tuple[int, int]] = docstring_positions(tree)
        # constants that are pieces of an f-string: judged via the
        # JoinedStr head, not independently (avoids double reports)
        fstring_parts = {
            id(v)
            for n in ast.walk(tree) if isinstance(n, ast.JoinedStr)
            for v in n.values
        }
        for node in ast.walk(tree):
            literal = None
            if isinstance(node, ast.JoinedStr):
                literal = fstring_literal_head(node)
            elif id(node) not in fstring_parts:
                literal = str_const(node)
            if not literal:
                continue
            if (node.lineno, node.col_offset) in skip:
                continue
            hit = next(
                (p for p in self._prefixes if literal.startswith(p)), None
            )
            if hit is None:
                continue
            yield self.finding(
                path, node,
                f"GCS key literal {literal.split(':')[0] + ':'!r} must be "
                f"minted via runtime/gcs/keys.py "
                f"(KeyPrefix {hit[:-1]!r}) so scans and sweeps can't drift",
            )
