"""RT012: telemetry series registry + label-cardinality discipline.

The timeseries twin of RT004 (metrics registry) and RT007 (event
taxonomy), for ``util/timeseries.py``. Two independent invariants, both
existing to keep the series namespace closed and its cardinality
bounded — an unbounded label value mints one GCS-resident series per
distinct runtime string and melts the store:

- every series *name* reaching ``TelemetryStream.register(...)`` /
  ``register_series(...)`` must be a reference to a ``SeriesName``
  constant, and those constants are literal snake_case strings declared
  exactly once, in ``util/timeseries.py`` — the registry's single home;
- every *labels* argument at a register site must be a dict literal
  with statically-known string keys, and no label value may be an
  f-string / string-concat / ``.format()`` / ``%`` expression.  A plain
  name or ``str(rank)`` call is fine — ranks and group names are
  bounded by the cluster — but string-building syntax is how unbounded
  ids (request ids, timestamps) sneak into label sets.

Import-aware like RT004/RT007: only names bound from
``util.timeseries`` (or used inside the home file itself) count.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Tuple

from ..astutil import str_const
from ..core import Checker, Finding, register

_SERIES_CLASS = "SeriesName"
_REGISTER_FN = "register_series"
_REGISTER_METHOD = "register"
_SNAKE_RE = re.compile(r"^[a-z][a-z0-9_]*$")
_HOME_FILE = "util/timeseries.py"


def _series_bindings(tree: ast.AST, path: str) -> Dict[str, str]:
    """local name -> canonical name, honoring imports. Tracks both the
    SeriesName class and register_series, plus declared constants
    (STEP_TIME_S etc.) imported from util.timeseries."""
    bound: Dict[str, str] = {}
    if path.endswith(_HOME_FILE):
        bound[_SERIES_CLASS] = _SERIES_CLASS
        bound[_REGISTER_FN] = _REGISTER_FN
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module and (
            node.module.endswith("util.timeseries")
            or node.module == "timeseries"
        ):
            for alias in node.names:
                bound[alias.asname or alias.name] = alias.name
    return bound


def _is_string_building(node: ast.AST) -> bool:
    """f-string / concat / %-format / .format() — the unbounded-label
    syntaxes the rule bans as label values."""
    if isinstance(node, ast.JoinedStr):
        return True
    if isinstance(node, ast.BinOp) and isinstance(
        node.op, (ast.Add, ast.Mod)
    ):
        return True
    return (
        isinstance(node, ast.Call)
        and isinstance(node.func, ast.Attribute)
        and node.func.attr == "format"
    )


@register
class SeriesRegistryChecker(Checker):
    RULE_ID = "RT012"
    DESCRIPTION = (
        "telemetry series: names are SeriesName constants declared once in"
        " util/timeseries.py; label sets statically bounded"
    )

    def __init__(self):
        # declared series name -> list of (path, line)
        self._declarations: Dict[str, List[Tuple[str, int]]] = {}

    def check_file(self, path, tree, source):
        bound = _series_bindings(tree, path)
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            if self._is_series_class(node, bound):
                yield from self._check_declaration(path, node)
            elif self._is_register(node, bound):
                yield from self._check_register(path, node, bound)

    # -- SeriesName("...") declarations --------------------------------------

    def _check_declaration(self, path, node: ast.Call):
        name_node = node.args[0] if node.args else None
        name = str_const(name_node) if name_node is not None else None
        if name is None:
            yield self.finding(
                path, node,
                "SeriesName must be constructed from a literal string "
                "(computed names defeat the registry audit and "
                "`/api/timeseries?name=`)",
            )
            return
        if not _SNAKE_RE.match(name):
            yield self.finding(
                path, node, f"series name {name!r} is not snake_case",
            )
        if not path.endswith(_HOME_FILE):
            yield self.finding(
                path, node,
                f"series {name!r} declared outside util/timeseries.py — "
                f"the registry lives there so readers/docs can't drift",
            )
        self._declarations.setdefault(name, []).append((path, node.lineno))

    # -- register_series(...) / stream.register(...) sites --------------------

    def _check_register(self, path, node: ast.Call, bound):
        if not node.args:
            return
        name_node = node.args[0]
        if str_const(name_node) is not None or isinstance(
            name_node, ast.JoinedStr
        ):
            # inside the home file the module-level default samplers pass
            # local constants; everywhere a literal is a registry bypass
            yield self.finding(
                path, node,
                "series name at a register site must be a SeriesName "
                "constant from util.timeseries, not a string literal",
            )
        labels_node = None
        if len(node.args) > 1:
            labels_node = node.args[1]
        for kw in node.keywords:
            if kw.arg == "labels":
                labels_node = kw.value
        if labels_node is None or isinstance(labels_node, ast.Constant):
            return
        if not isinstance(labels_node, ast.Dict):
            yield self.finding(
                path, node,
                "labels at a register site must be a dict literal so the "
                "label-set cardinality is statically auditable",
            )
            return
        for key in labels_node.keys:
            if key is None or str_const(key) is None:
                yield self.finding(
                    path, node,
                    "label keys must be literal strings (no ** / computed "
                    "keys) — the set of label names is part of the schema",
                )
        for value in labels_node.values:
            if _is_string_building(value):
                yield self.finding(
                    path, node,
                    "label value built with f-string/concat/format — "
                    "unbounded label values mint unbounded series; pass a "
                    "bounded variable (or str(rank)) instead",
                )

    def finalize(self):
        for name, decls in sorted(self._declarations.items()):
            if len(decls) > 1:
                sites = ", ".join(f"{p}:{ln}" for p, ln in decls)
                yield Finding(
                    rule=self.RULE_ID, path=decls[0][0], line=decls[0][1],
                    message=f"series {name!r} declared {len(decls)} times "
                            f"({sites}) — the registry keys by name, later "
                            f"declarations raise at import",
                )

    # -- call-shape recognizers ----------------------------------------------

    @staticmethod
    def _is_series_class(node: ast.Call, bound: Dict[str, str]) -> bool:
        func = node.func
        if isinstance(func, ast.Name):
            return bound.get(func.id) == _SERIES_CLASS
        return (
            isinstance(func, ast.Attribute)
            and func.attr == _SERIES_CLASS
            and isinstance(func.value, ast.Name)
            and func.value.id in ("timeseries", "_ts")
        )

    @staticmethod
    def _is_register(node: ast.Call, bound: Dict[str, str]) -> bool:
        func = node.func
        if isinstance(func, ast.Name):
            return bound.get(func.id) == _REGISTER_FN
        if not isinstance(func, ast.Attribute):
            return False
        if func.attr == _REGISTER_FN:
            # timeseries.register_series(...) / _ts.register_series(...)
            return True
        if func.attr == _REGISTER_METHOD and isinstance(
            func.value, ast.Name
        ) and func.value.id in ("stream", "_stream"):
            # TelemetryStream handles conventionally named stream/_stream;
            # other .register() attributes (rpc servers etc.) are unrelated
            return True
        return False
