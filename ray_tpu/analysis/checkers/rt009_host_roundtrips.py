"""RT009: no ad-hoc device->host round-trips on the serving hot path.

Incident class this encodes: the tensor-parallel serving work (PR 13).
Every ``jax.device_get``/``np.asarray(jnp...)``/``float(jnp...)`` sprinkled
through the engine or the KV-cache manager is a synchronous device->host
transfer that stalls the dispatch pipeline — and under a sharded mesh it is
worse, because materializing a replicated output gathers from every device.
The serving plane therefore funnels ALL materialization through the single
audited ``host_sync`` chokepoint in ``ray_tpu/llm/engine.py`` (one fused
sampling program, one transfer per decode step); everything else on the hot
path must stay on device.

Flags, in ``ray_tpu/llm/engine.py`` and ``ray_tpu/kvcache/``:

- ``jax.device_get(...)`` calls;
- ``.block_until_ready()`` calls (a barrier is a hidden round-trip);
- ``np.asarray(X)`` / ``np.array(X)`` / ``float(X)`` / ``int(X)`` where the
  argument expression is rooted at a ``jnp``/``jax`` name — i.e. the value
  being materialized is statically known to live on device. Host-side
  conversions (``np.asarray(py_list)``, ``int(host_row[i])``) are fine and
  not flagged; that asymmetry is what keeps the rule statically decidable.

The body of a function literally named ``host_sync`` is exempt: that IS the
chokepoint. Route new materializations through it so they stay auditable.
"""

from __future__ import annotations

import ast
from typing import Set

from ..core import Checker, register

_MATERIALIZERS_NP = {"asarray", "array"}
_MATERIALIZERS_BUILTIN = {"float", "int"}


def _root_name(node: ast.AST) -> str:
    """Leftmost Name of an attribute/call/subscript chain, '' otherwise."""
    while True:
        if isinstance(node, ast.Attribute):
            node = node.value
        elif isinstance(node, ast.Call):
            node = node.func
        elif isinstance(node, ast.Subscript):
            node = node.value
        elif isinstance(node, ast.Name):
            return node.id
        else:
            return ""


def _device_rooted(node: ast.AST) -> bool:
    return _root_name(node) in ("jnp", "jax")


def _host_sync_spans(tree: ast.AST) -> Set[int]:
    """ids of all nodes inside a FunctionDef named host_sync (the exempt
    chokepoint)."""
    exempt: Set[int] = set()
    for node in ast.walk(tree):
        if (
            isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
            and node.name == "host_sync"
        ):
            for sub in ast.walk(node):
                exempt.add(id(sub))
    return exempt


@register
class HostRoundTripChecker(Checker):
    RULE_ID = "RT009"
    DESCRIPTION = (
        "device->host round-trip on the serving hot path (engine/kvcache); "
        "route materialization through host_sync"
    )

    def applies_to(self, path: str) -> bool:
        parts = path.split("/")
        if "kvcache" in parts[:-1]:
            return True
        return parts[-1] == "engine.py" and len(parts) >= 2 and (
            parts[-2] == "llm"
        )

    def check_file(self, path, tree, source):
        exempt = _host_sync_spans(tree)
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call) or id(node) in exempt:
                continue
            func = node.func
            if isinstance(func, ast.Attribute):
                if func.attr == "device_get" and _root_name(func) == "jax":
                    yield self.finding(
                        path, node,
                        "jax.device_get() on the serving hot path is a "
                        "synchronous device->host transfer; route it "
                        "through host_sync",
                    )
                    continue
                if func.attr == "block_until_ready":
                    yield self.finding(
                        path, node,
                        ".block_until_ready() on the serving hot path is "
                        "a hidden dispatch barrier; drop it or move it "
                        "behind host_sync",
                    )
                    continue
                if (
                    func.attr in _MATERIALIZERS_NP
                    and _root_name(func) == "np"
                    and node.args
                    and _device_rooted(node.args[0])
                ):
                    yield self.finding(
                        path, node,
                        f"np.{func.attr}() of a device value materializes "
                        "it host-side mid-hot-path; route it through "
                        "host_sync",
                    )
                    continue
            elif isinstance(func, ast.Name):
                if (
                    func.id in _MATERIALIZERS_BUILTIN
                    and node.args
                    and _device_rooted(node.args[0])
                ):
                    yield self.finding(
                        path, node,
                        f"{func.id}() of a device value is a synchronous "
                        "device->host transfer; route it through host_sync",
                    )
