"""Built-in project checkers. Importing this package registers them all."""

from . import rt001_blocking_async  # noqa: F401
from . import rt002_traced_executor  # noqa: F401
from . import rt003_lock_discipline  # noqa: F401
from . import rt004_metrics_registry  # noqa: F401
from . import rt005_gcs_keys  # noqa: F401
from . import rt006_pickle_exceptions  # noqa: F401
from . import rt007_event_registry  # noqa: F401
from . import rt008_rpc_timeouts  # noqa: F401
from . import rt009_host_roundtrips  # noqa: F401
from . import rt010_scheduler_reduce  # noqa: F401
from . import rt011_transfer_layer  # noqa: F401
from . import rt012_series_registry  # noqa: F401
from . import rt013_adapter_slots  # noqa: F401
