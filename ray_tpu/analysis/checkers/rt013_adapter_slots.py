"""RT013: the LoRA slot bank is mutated only through AdapterStore.

Incident class this encodes: the multi-tenant LoRA plane (PR 20). The
adapter bank is a stacked ``(num_slots, ...)`` device buffer shared by
every in-flight request — decode programs gather rows out of it by slot
index every step. ``AdapterStore._write_slot`` is the one audited way to
change a row: a jitted copy-on-write ``dynamic_update_index_in_dim``
over the whole tree that keeps the bank's shardings, scales ``lora_b``
by alpha/rank at attach, and only runs while the slot holds zero leases
(the superseded bank stays valid for decode steps already in flight).
Writing a row any other way — rebuilding the bank pytree in the engine,
poking ``store._bank`` from serving code, or calling the private
``_write_slot`` from outside the store — silently corrupts whatever
request is decoding from that row, skips the refcount gate, and drops
the sharded-layout guarantee the engine's compiled programs rely on.

Flags, in ``ray_tpu/llm/``, ``ray_tpu/serve/`` and ``ray_tpu/kvcache/``:

- any assignment to an attribute named ``_bank`` or ``_adapter_bank`` —
  rebinding the slot pool outside the store;
- any ``X._write_slot(...)`` attribute call — reaching the private write
  primitive around its lease accounting.

``ray_tpu/lora/`` itself is outside the scanned paths: that IS the
chokepoint. Mutate slots via ``AdapterStore.acquire`` / ``release`` /
``prewarm`` so lease refcounts, LRU state and metrics stay coherent.
"""

from __future__ import annotations

import ast

from ..core import Checker, register

_BANK_NAMES = ("_bank", "_adapter_bank")


@register
class AdapterSlotsChecker(Checker):
    RULE_ID = "RT013"
    DESCRIPTION = (
        "LoRA slot-bank mutation outside AdapterStore (llm/serve/kvcache); "
        "go through acquire/release/prewarm in ray_tpu/lora"
    )

    def applies_to(self, path: str) -> bool:
        parts = path.split("/")
        return any(p in ("llm", "serve", "kvcache") for p in parts[:-1])

    def check_file(self, path, tree, source):
        for node in ast.walk(tree):
            if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                targets = (
                    node.targets if isinstance(node, ast.Assign)
                    else [node.target]
                )
                for tgt in targets:
                    if (
                        isinstance(tgt, ast.Attribute)
                        and tgt.attr in _BANK_NAMES
                    ):
                        yield self.finding(
                            path, node,
                            f"assignment to .{tgt.attr} rebinds the LoRA "
                            "slot bank outside AdapterStore, corrupting "
                            "rows in-flight requests are gathering from; "
                            "mutate slots via AdapterStore.acquire/"
                            "release/prewarm",
                        )
                continue
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "_write_slot"
            ):
                yield self.finding(
                    path, node,
                    "direct _write_slot() call bypasses AdapterStore's "
                    "lease refcounts and LRU accounting; attach adapters "
                    "via AdapterStore.acquire/prewarm",
                )
