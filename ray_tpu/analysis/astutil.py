"""Small AST helpers shared by the checkers."""

from __future__ import annotations

import ast
from typing import Iterator, Optional, Set, Tuple

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


def walk_shallow(node: ast.AST) -> Iterator[ast.AST]:
    """Walk a function body WITHOUT descending into nested function/lambda
    definitions. A nested ``def`` has its own execution context (this repo's
    idiom hands such closures to an executor), so blocking-call rules must
    judge it separately — nested ``async def`` s are found by the outer
    file walk anyway."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        child = stack.pop()
        yield child
        if not isinstance(child, _FUNC_NODES):
            stack.extend(ast.iter_child_nodes(child))


def dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for Name/Attribute chains, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_name(call: ast.Call) -> Optional[str]:
    return dotted_name(call.func)


def str_const(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def fstring_literal_head(node: ast.JoinedStr) -> str:
    """The leading literal chunk of an f-string ("colmember:" for
    ``f"colmember:{g}:{r}"``), or "" if it starts with an expression."""
    if node.values:
        head = str_const(node.values[0])
        if head is not None:
            return head
    return ""


def docstring_positions(tree: ast.AST) -> Set[Tuple[int, int]]:
    """(lineno, col) of every docstring constant, so literal-scanning rules
    can skip them."""
    out: Set[Tuple[int, int]] = set()
    for node in ast.walk(tree):
        if isinstance(
            node, (ast.Module, ast.ClassDef, ast.FunctionDef, ast.AsyncFunctionDef)
        ):
            body = node.body
            if (
                body
                and isinstance(body[0], ast.Expr)
                and str_const(body[0].value) is not None
            ):
                c = body[0].value
                out.add((c.lineno, c.col_offset))
    return out


def time_aliases(tree: ast.AST) -> Tuple[Set[str], Set[str]]:
    """(module aliases of ``time``, local names bound to ``time.sleep``)."""
    mods: Set[str] = set()
    sleeps: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name == "time":
                    mods.add(alias.asname or "time")
        elif isinstance(node, ast.ImportFrom) and node.module == "time":
            for alias in node.names:
                if alias.name == "sleep":
                    sleeps.add(alias.asname or "sleep")
    return mods, sleeps
