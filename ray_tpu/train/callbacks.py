"""Controller callbacks, including TPU slice reservation.

Role-equivalent of the reference's Train v2 callbacks
(train/v2/_internal/execution/callback.py) and in particular
TPUReservationCallback (v2/_internal/execution/callback/
tpu_reservation_callback.py:9): before the worker group starts, reserve a
whole ICI slice and hand the worker group the slice's label selector so the
ranked gang lands on it; release the slice on shutdown.
"""

from __future__ import annotations

import logging
from typing import Dict, Optional

logger = logging.getLogger(__name__)


class TrainCallback:
    """Hooks observed by the TrainController."""

    def before_worker_group_start(self, scaling_config) -> Optional[dict]:
        """May return overrides: {"bundle_label_selector": {...},
        "placement_group_override": PlacementGroup}."""
        return None

    def after_worker_group_start(self, worker_group) -> None:
        pass

    def on_report(self, report) -> None:
        pass

    def before_worker_group_shutdown(self, worker_group) -> None:
        pass

    def after_run(self, result) -> None:
        pass


class WeightPublishCallback(TrainCallback):
    """Publish every reported checkpoint's state to the weight plane
    (reference role: the learner-side weight broadcast RLlib/Serve consume).

    Each checkpoint the train loop reports becomes one version of the named
    model: downstream subscribers — serve replicas hot-reloading a
    fine-tune, RL env-runners, evaluation jobs — pull it over the broadcast
    tree instead of re-reading checkpoint storage per consumer.

    ``load_fn(checkpoint) -> pytree`` extracts the publishable state; the
    default understands ``state.pkl`` files (what the examples write) and
    falls back to the sharded-checkpoint reader.
    """

    def __init__(self, name: str, load_fn=None):
        self._name = name
        self._load_fn = load_fn or _default_checkpoint_load
        self._last_published_index = None

    def on_report(self, report) -> None:
        if report.checkpoint is None or report.world_rank != 0:
            return
        if report.index == self._last_published_index:
            return
        try:
            state = self._load_fn(report.checkpoint)
        except Exception:
            logger.exception(
                "weight publish: could not load state from checkpoint %s",
                report.checkpoint,
            )
            return
        if state is None:
            return
        from .. import weights

        handle = weights.publish(
            self._name, state, meta={"checkpoint_index": report.index}
        )
        self._last_published_index = report.index
        logger.info(
            "published checkpoint %d as weights %s v%s",
            report.index, self._name, handle.version,
        )

    def after_run(self, result) -> None:
        # reclaim superseded versions' chunks before the driver moves on
        from ..weights import _publisher

        try:
            _publisher(self._name).collect()
        except Exception:
            pass


def _default_checkpoint_load(checkpoint):
    """Best-effort state extraction: a ``state.pkl`` in the checkpoint dir,
    else an orbax sharded checkpoint, else None."""
    import os
    import pickle

    with checkpoint.as_directory() as path:
        pkl = os.path.join(path, "state.pkl")
        if os.path.exists(pkl):
            with open(pkl, "rb") as f:
                return pickle.load(f)
        try:
            from .sharded_checkpoint import restore_sharded

            return restore_sharded(path)
        except Exception:
            return None


class TPUReservationCallback(TrainCallback):
    """Reserve one slice per run (reference flow: reserve_tpu_slice →
    bundle_label_selector, tpu_reservation_callback.py:12)."""

    def __init__(self, timeout: float = 120.0):
        self._timeout = timeout
        self._reservation = None

    def before_worker_group_start(self, scaling_config) -> Optional[dict]:
        if not (scaling_config.use_tpu and scaling_config.topology):
            return None
        from ..util.tpu import reserve_tpu_slice

        self._reservation = reserve_tpu_slice(
            scaling_config.topology, timeout=self._timeout
        )
        logger.info(
            "train run reserved TPU slice %s", self._reservation.slice_name
        )
        return {
            "placement_group_override": self._reservation.workers_pg,
            "slice_name": self._reservation.slice_name,
        }

    def before_worker_group_shutdown(self, worker_group) -> None:
        if self._reservation is not None:
            try:
                self._reservation.release()
            except Exception:
                pass
            self._reservation = None
