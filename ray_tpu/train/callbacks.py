"""Controller callbacks, including TPU slice reservation.

Role-equivalent of the reference's Train v2 callbacks
(train/v2/_internal/execution/callback.py) and in particular
TPUReservationCallback (v2/_internal/execution/callback/
tpu_reservation_callback.py:9): before the worker group starts, reserve a
whole ICI slice and hand the worker group the slice's label selector so the
ranked gang lands on it; release the slice on shutdown.
"""

from __future__ import annotations

import logging
from typing import Dict, Optional

logger = logging.getLogger(__name__)


class TrainCallback:
    """Hooks observed by the TrainController."""

    def before_worker_group_start(self, scaling_config) -> Optional[dict]:
        """May return overrides: {"bundle_label_selector": {...},
        "placement_group_override": PlacementGroup}."""
        return None

    def after_worker_group_start(self, worker_group) -> None:
        pass

    def on_report(self, report) -> None:
        pass

    def before_worker_group_shutdown(self, worker_group) -> None:
        pass

    def after_run(self, result) -> None:
        pass


class TPUReservationCallback(TrainCallback):
    """Reserve one slice per run (reference flow: reserve_tpu_slice →
    bundle_label_selector, tpu_reservation_callback.py:12)."""

    def __init__(self, timeout: float = 120.0):
        self._timeout = timeout
        self._reservation = None

    def before_worker_group_start(self, scaling_config) -> Optional[dict]:
        if not (scaling_config.use_tpu and scaling_config.topology):
            return None
        from ..util.tpu import reserve_tpu_slice

        self._reservation = reserve_tpu_slice(
            scaling_config.topology, timeout=self._timeout
        )
        logger.info(
            "train run reserved TPU slice %s", self._reservation.slice_name
        )
        return {
            "placement_group_override": self._reservation.workers_pg,
            "slice_name": self._reservation.slice_name,
        }

    def before_worker_group_shutdown(self, worker_group) -> None:
        if self._reservation is not None:
            try:
                self._reservation.release()
            except Exception:
                pass
            self._reservation = None
