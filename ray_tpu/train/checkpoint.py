"""Checkpoints: directory handles + top-K retention.

Role-equivalent of the reference's ray.train.Checkpoint
(python/ray/train/_checkpoint.py:56 — a handle to a directory on pluggable
storage) and the v2 CheckpointManager
(v2/_internal/execution/checkpoint/checkpoint_manager.py — registers
reported checkpoints, keeps the top-K by a score attribute).

TPU-first: sharded model state is written with orbax (async, per-host
shards) into the checkpoint directory; every rank reports into the same
indexed directory so a slice-wide checkpoint is one logical dir.
"""

from __future__ import annotations

import json
import os
import shutil
import tempfile
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from .config import CheckpointConfig


class Checkpoint:
    """A handle to a checkpoint directory on shared storage."""

    def __init__(self, path: str):
        self.path = os.path.abspath(path)

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        return cls(path)

    def to_directory(self, dest: Optional[str] = None) -> str:
        """Copy checkpoint contents into a local directory and return it."""
        if dest is None:
            dest = tempfile.mkdtemp(prefix="ckpt_")
        shutil.copytree(self.path, dest, dirs_exist_ok=True)
        return dest

    @contextmanager
    def as_directory(self):
        """Access the checkpoint as a local directory (no copy when the
        storage is a local/shared filesystem, matching the reference's
        fast path)."""
        yield self.path

    def __repr__(self):
        return f"Checkpoint(path={self.path!r})"

    def __eq__(self, other):
        return isinstance(other, Checkpoint) and other.path == self.path

    def __reduce__(self):
        return (Checkpoint, (self.path,))


@dataclass
class _TrackedCheckpoint:
    checkpoint: Checkpoint
    index: int
    metrics: Dict[str, Any] = field(default_factory=dict)

    def score(self, attribute: str):
        return self.metrics.get(attribute)


class CheckpointManager:
    """Controller-side bookkeeping of reported checkpoints."""

    def __init__(self, run_dir: str, config: CheckpointConfig):
        self._run_dir = run_dir
        self._config = config
        self._tracked: List[_TrackedCheckpoint] = []
        self._latest: Optional[_TrackedCheckpoint] = None

    @property
    def latest_checkpoint(self) -> Optional[Checkpoint]:
        return self._latest.checkpoint if self._latest else None

    @property
    def best_checkpoint(self) -> Optional[Checkpoint]:
        attr = self._config.checkpoint_score_attribute
        if not attr or not self._tracked:
            return self.latest_checkpoint
        scored = [t for t in self._tracked if t.score(attr) is not None]
        if not scored:
            return self.latest_checkpoint
        best = (max if self._config.checkpoint_score_order == "max" else min)(
            scored, key=lambda t: t.score(attr)
        )
        return best.checkpoint

    def register(self, checkpoint: Checkpoint, index: int, metrics: Dict[str, Any]):
        for t in self._tracked:
            if t.index == index:  # another rank of the same report
                t.metrics.update(metrics)
                # a lagging rank's report for an older index must not rewind
                # the latest pointer past newer checkpoints
                if self._latest is None or index >= self._latest.index:
                    self._latest = t
                self._write_manifest()
                return
        tracked = _TrackedCheckpoint(checkpoint, index, dict(metrics))
        self._tracked.append(tracked)
        self._latest = tracked
        self._write_manifest()
        self._prune()

    def _prune(self):
        keep = self._config.num_to_keep
        if keep is None or len(self._tracked) <= keep:
            return
        attr = self._config.checkpoint_score_attribute
        candidates = [t for t in self._tracked if t is not self._latest]
        if attr:
            reverse = self._config.checkpoint_score_order == "min"
            candidates.sort(
                key=lambda t: (t.score(attr) is None, t.score(attr) or 0),
                reverse=reverse,
            )
        else:
            candidates.sort(key=lambda t: t.index)
        while len(self._tracked) > keep and candidates:
            victim = candidates.pop(0)
            self._tracked.remove(victim)
            shutil.rmtree(victim.checkpoint.path, ignore_errors=True)
        self._write_manifest()

    def _write_manifest(self):
        os.makedirs(self._run_dir, exist_ok=True)
        manifest = {
            "checkpoints": [
                {"path": t.checkpoint.path, "index": t.index, "metrics": t.metrics}
                for t in sorted(self._tracked, key=lambda t: t.index)
            ],
            "latest": self._latest.checkpoint.path if self._latest else None,
        }
        tmp = os.path.join(self._run_dir, ".manifest.tmp")
        with open(tmp, "w") as f:
            json.dump(manifest, f, indent=2, default=str)
        os.replace(tmp, os.path.join(self._run_dir, "checkpoint_manifest.json"))


def load_latest_checkpoint(run_dir: str) -> Optional[Checkpoint]:
    """Resume support: recover the latest checkpoint recorded for a run."""
    manifest_path = os.path.join(run_dir, "checkpoint_manifest.json")
    if not os.path.exists(manifest_path):
        return None
    with open(manifest_path) as f:
        manifest = json.load(f)
    latest = manifest.get("latest")
    if latest and os.path.isdir(latest):
        return Checkpoint(latest)
    return None
