"""Framework trainers beyond the flagship Jax/Torch pair.

Role-equivalent of the reference's LightningTrainer / TensorflowTrainer /
XGBoostTrainer / LightGBMTrainer entry points (train/lightning, tensorflow,
xgboost, lightgbm). TensorflowTrainer is fully functional (TF is in the
image; the TF_CONFIG backend forms the MultiWorkerMirroredStrategy
cluster). lightning/xgboost/lightgbm are not installed, so those
constructors are import-gated: they keep the reference's API shape and fail
at construction with an actionable message rather than at a confusing point
mid-fit; when the library IS present they delegate to DataParallelTrainer
with the torch backend (those frameworks drive their own training loops).
"""

from __future__ import annotations

from typing import Callable, Optional

from .backend import TensorflowConfig, TorchConfig
from .trainer import DataParallelTrainer


class TensorflowTrainer(DataParallelTrainer):
    """TF trainer (reference: train/tensorflow/tensorflow_trainer.py): the
    TF_CONFIG backend wires the ranked workers into one
    MultiWorkerMirroredStrategy cluster; the user loop builds the strategy
    and trains under its scope."""

    def __init__(self, train_loop_per_worker: Callable, **kwargs):
        try:
            import tensorflow  # noqa: F401
        except ImportError as e:
            raise ImportError(
                "tensorflow is not installed in this image; use JaxTrainer "
                "(the TPU-native path) or TorchTrainer"
            ) from e
        kwargs.setdefault("backend_config", TensorflowConfig())
        super().__init__(train_loop_per_worker, **kwargs)


def _gated_trainer(import_name: str, display: str):
    class _FrameworkTrainer(DataParallelTrainer):
        def __init__(self, train_loop_per_worker: Callable, **kwargs):
            try:
                __import__(import_name)
            except ImportError as e:
                raise ImportError(
                    f"{display} is not installed in this image; "
                    f"{display}Trainer needs it inside the worker loop. "
                    "Use JaxTrainer (the TPU-native path) or TorchTrainer, "
                    f"or bake {import_name} into the runtime image."
                ) from e
            kwargs.setdefault("backend_config", TorchConfig())
            super().__init__(train_loop_per_worker, **kwargs)

    _FrameworkTrainer.__name__ = f"{display}Trainer"
    _FrameworkTrainer.__qualname__ = _FrameworkTrainer.__name__
    return _FrameworkTrainer


LightningTrainer = _gated_trainer("lightning", "Lightning")
XGBoostTrainer = _gated_trainer("xgboost", "XGBoost")
LightGBMTrainer = _gated_trainer("lightgbm", "LightGBM")
